"""Trace-taint dataflow: which values are traced (or live on device).

The second dataflow plane over the :mod:`cfg`/:mod:`callgraph`
infrastructure. :mod:`locksets` answers "which locks are held here";
this module answers "is this expression a *traced* value here" — the
property under every recompile storm, donation bug, and hidden host
sync that PR 18's compile ledger can only measure after a chip paid
for it.

A value is **tainted** when it may be a JAX tracer or a device array:

- parameters of a jit/pjit/Pallas context function (minus the params
  named by ``static_argnums``/``static_argnames`` — those are Python
  values by contract);
- results of ``jnp.*`` / ``jax.lax.*`` / ``jax.random.*`` / ``pl.*``
  calls, and of calling any name bound to a jitted callable;
- anything data-derived from a tainted value: assignments, container
  literals, subscripts, arithmetic, method calls on a tainted
  receiver, iteration.

Taint is a **may** analysis (union joins over the CFG, strong updates
on simple ``name``/``self.attr`` rebinds) — the dual of the lock
plane's must-analysis, because here the dangerous direction is "this
might be traced". What keeps it conservative toward *silence* is the
sanitizer set: ``.shape``/``.dtype``/``.ndim``/``.size`` reads,
``len()``, ``is``/``is not`` comparisons, ``isinstance``, and the
host-materializing calls (``int()``/``float()``, ``.item()``,
``.tolist()``, ``np.asarray``, ``jax.device_get``, the
``*bucket`` shape-class vocabulary of ``ops/autotune``) all produce
host values, so branch-on-shape and bucketed-padding idioms never
taint. Per the analysis plane's contract, a fact that cannot be
proven stays un-flagged.

Interprocedural scope is module-local and bounded (like the lock
plane's ``_PROPAGATION_ROUNDS``): a module-level function called from
a traced context with a tainted argument becomes a traced context
itself, with exactly those parameters tainted; nested ``def``s inside
a traced context are traced with *all* parameters tainted (they are
``scan``/``cond`` bodies by construction). Cross-module calls are
invisible — a documented false-negative, never a false positive.

The module also builds the **jit-site inventory** every compile-plane
rule shares: each ``jax.jit``/``pjit`` call or decoration with its
bound names (``step``, ``self._step``, aliases through plain
assignment), resolved ``static_argnums``/``static_argnames``/
``donate_argnums`` (literal-or-None, per :mod:`astutil`'s
conservatism), loop nesting, and whether the wrapped callable is
fresh per call (lambda / ``functools.partial``). The same inventory
is what ``scripts/run_tpulint.py --compile-audit`` joins against the
recorded ``kftpu_compile_seconds`` events.

Everything is memoized per :class:`ModuleInfo` via
:func:`taint_analysis`; CFGs come from :func:`cfg.cfg_for`, shared
with the lock plane, so the five consuming checkers (TPU014–TPU018)
add one analysis pass per file, not five.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import (Dict, FrozenSet, Iterator, List, Optional, Sequence,
                    Set, Tuple, Union)

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis import cfg as cfg_mod
from kubeflow_tpu.analysis.walker import ModuleInfo

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]

JIT_NAMES = {"jit", "jax.jit", "pjit", "pjit.pjit",
             "jax.experimental.pjit.pjit"}
PALLAS_CALL_SUFFIX = "pallas_call"

_PROPAGATION_ROUNDS = 3   # traced-helper call-site fixpoint bound

# dotted-call prefixes whose results are traced/device values
TRACED_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "lax.",
                   "jax.random.", "jax.nn.", "jax.scipy.", "jax.ops.",
                   "pl.", "pltpu.")
TRACED_EXACT = {"jax.device_put", "jax.eval_shape"}

# attribute reads that are static under tracing — branch-on-shape is
# the idiomatic fix for TPU014, so it must never taint
UNTAINT_ATTRS = {"shape", "dtype", "ndim", "size", "itemsize", "nbytes",
                 "sharding", "aval", "weak_type"}

# calls whose result is a host value (several of them are exactly the
# sync points TPU017 flags — the *result* is host-side either way)
SANITIZER_CALLS = {"int", "float", "bool", "str", "len", "isinstance",
                   "hash", "repr", "range", "np.asarray", "np.array",
                   "numpy.asarray", "numpy.array", "jax.device_get",
                   "np.float32", "np.int32", "np.float64", "np.int64"}
# method calls whose result is a host value; block_until_ready is a
# sync but returns the (device) array itself, so it stays tainted
SANITIZER_METHODS = {"item", "tolist"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _bindable_name(node: ast.AST) -> Optional[str]:
    """A name a callable can be reached by later: bare ``x`` or
    ``self.x`` (as "self.x"). Anything else has no stable handle."""
    if isinstance(node, ast.Name):
        return node.id
    attr = _self_attr(node)
    if attr is not None:
        return "self." + attr
    return None


def iter_exprs(root: ast.AST) -> Iterator[ast.AST]:
    """Walk ``root`` without descending into nested defs/lambdas —
    their bodies are separate (traced) contexts with their own taint."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """``0`` / ``(0, 1)`` / ``[0]`` → a tuple of ints; None when any
    element is not a literal int (conditional expressions, names)."""
    v = astutil.const_int(node)
    if v is not None:
        return (v,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            v = astutil.const_int(el)
            if v is None:
                return None
            out.append(v)
        return tuple(out)
    return None


def _str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    v = astutil.const_str(node)
    if v is not None:
        return (v,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            v = astutil.const_str(el)
            if v is None:
                return None
            out.append(v)
        return tuple(out)
    return None


@dataclasses.dataclass
class JitSite:
    """One ``jax.jit``/``pjit`` entry point: a call site or a
    decorated function."""

    node: ast.AST                 # the Call, or the decorated def
    lineno: int
    kind: str                     # "call" | "decorator"
    wrapped: str                  # wrapped callable's name ("<lambda>")
    bound: Set[str]               # names it is reachable by (+aliases)
    enclosing: Optional[str]      # enclosing function name, if any
    in_loop: bool                 # constructed inside a loop body
    immediate: bool               # jax.jit(f, ...)(args) — used once
    fresh_callee: bool            # wraps a lambda / functools.partial
    static_argnums: Optional[Tuple[int, ...]]
    static_argnames: Optional[Tuple[str, ...]]
    donate_argnums: Optional[Tuple[int, ...]]


class FunctionTaint:
    """May-taint states for one function, one entry assumption."""

    def __init__(self, mt: "ModuleTaint", fn: FunctionNode,
                 entry: FrozenSet[str]) -> None:
        self.mt = mt
        self.fn = fn
        self.entry = entry
        self.cfg = cfg_mod.cfg_for(mt.module, fn)
        self.taint_in: Dict[int, Optional[FrozenSet[str]]] = {
            n.nid: None for n in self.cfg.nodes}
        self._run()

    # -- fixpoint ----------------------------------------------------------

    def _run(self) -> None:
        self.taint_in[self.cfg.entry.nid] = self.entry
        worklist = [self.cfg.entry.nid]
        while worklist:
            nid = worklist.pop()
            state = self.taint_in[nid]
            if state is None:
                continue
            out = self._transfer(self.cfg.nodes[nid], state)
            for s in self.cfg.nodes[nid].succs:
                cur = self.taint_in[s]
                new = out if cur is None else (cur | out)
                if cur is None or new != cur:
                    self.taint_in[s] = frozenset(new)
                    worklist.append(s)

    def _transfer(self, cn: cfg_mod.CfgNode,
                  env: FrozenSet[str]) -> FrozenSet[str]:
        stmt = cn.node
        if stmt is None or cn.kind == cfg_mod.WITH_EXIT:
            return env
        out = set(env)
        if cn.kind == cfg_mod.WITH_ENTER:
            for item in stmt.items:
                if item.optional_vars is not None:
                    t = self._expr(item.context_expr, env)
                    self._assign(out, item.optional_vars, t, env)
            return frozenset(out)
        if isinstance(stmt, ast.Assign):
            t = self._expr(stmt.value, env)
            for tgt in stmt.targets:
                self._assign(out, tgt, t, env, value=stmt.value)
            return frozenset(out)
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            t = self._expr(stmt.value, env)
            self._assign(out, stmt.target, t, env)
            return frozenset(out)
        if isinstance(stmt, ast.AugAssign):
            # target op= value reads the old target; taint only grows
            if self._expr(stmt.value, env):
                name = _bindable_name(stmt.target)
                if name is not None:
                    out.add(name)
            return frozenset(out)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            t = self._expr(stmt.iter, env)
            self._assign(out, stmt.target, t, env)
            return frozenset(out)
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                name = _bindable_name(tgt)
                if name is not None:
                    out.discard(name)
            return frozenset(out)
        return env

    def _assign(self, out: Set[str], target: ast.AST, tainted: bool,
                env: FrozenSet[str],
                value: Optional[ast.AST] = None) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            elts = None
            if isinstance(value, (ast.Tuple, ast.List)) \
                    and len(value.elts) == len(target.elts):
                elts = value.elts
            for i, el in enumerate(target.elts):
                if elts is not None:
                    self._assign(out, el, self._expr(elts[i], env), env,
                                 value=elts[i])
                else:
                    self._assign(out, el, tainted, env)
            return
        if isinstance(target, ast.Starred):
            self._assign(out, target.value, tainted, env)
            return
        name = _bindable_name(target)
        if name is not None:
            # strong update: a simple rebind replaces the old value
            if tainted:
                out.add(name)
            else:
                out.discard(name)
            return
        if isinstance(target, ast.Subscript):
            # x[i] = tainted → x may now hold a tainted element (weak)
            base = _bindable_name(target.value)
            if base is not None and tainted:
                out.add(base)

    # -- expression taint --------------------------------------------------

    def _expr(self, node: ast.AST, env: FrozenSet[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in env
        if isinstance(node, ast.Attribute):
            if node.attr in UNTAINT_ATTRS:
                return False
            name = _bindable_name(node)
            if name is not None:
                return name in env
            return self._expr(node.value, env)
        if isinstance(node, (ast.Constant, ast.Lambda, ast.JoinedStr,
                             ast.FormattedValue)):
            return False
        if isinstance(node, ast.Call):
            return self._call(node, env)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # identity tests are host-decidable
            return (self._expr(node.left, env)
                    or any(self._expr(c, env) for c in node.comparators))
        if isinstance(node, ast.BoolOp):
            return any(self._expr(v, env) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self._expr(node.left, env) \
                or self._expr(node.right, env)
        if isinstance(node, ast.UnaryOp):
            return self._expr(node.operand, env)
        if isinstance(node, ast.IfExp):
            return self._expr(node.body, env) \
                or self._expr(node.orelse, env)
        if isinstance(node, ast.Subscript):
            return self._expr(node.value, env)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._expr(e, env) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self._expr(v, env)
                       for v in node.values if v is not None)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return any(self._expr(g.iter, env) for g in node.generators)
        if isinstance(node, ast.Starred):
            return self._expr(node.value, env)
        if isinstance(node, ast.Await):
            return self._expr(node.value, env)
        if isinstance(node, ast.NamedExpr):
            return self._expr(node.value, env)
        if isinstance(node, ast.Slice):
            return any(self._expr(p, env) for p in
                       (node.lower, node.upper, node.step)
                       if p is not None)
        return False

    def _call(self, node: ast.Call, env: FrozenSet[str]) -> bool:
        func = node.func
        if isinstance(func, ast.Attribute) \
                and func.attr in SANITIZER_METHODS:
            return False
        name = astutil.call_name(node) or ""
        if name:
            if name in SANITIZER_CALLS:
                return False
            last = name.split(".")[-1]
            if last.endswith("bucket"):
                # the ops/autotune shape-class vocabulary (seq_bucket,
                # pow2_bucket): its whole point is a host-side int
                return False
            if name in TRACED_EXACT \
                    or any(name.startswith(p) for p in TRACED_PREFIXES):
                return True
            if name in self.mt.jitted_names:
                return True
        # a method call on a tainted receiver returns a tainted value
        # (x.astype(...), x.sum(), cache.at[i].set(...))
        if isinstance(func, ast.Attribute) \
                and self._expr(func.value, env):
            return True
        if isinstance(func, ast.Name) and func.id in env:
            return True  # calling a value that is itself traced-ish
        return (any(self._expr(a, env) for a in node.args)
                or any(self._expr(kw.value, env)
                       for kw in node.keywords if kw.value is not None))

    # -- queries -----------------------------------------------------------

    def enclosing_stmt(self, node: ast.AST) -> Optional[ast.AST]:
        """Walk up to the CFG statement of this function that evaluates
        ``node``; None when the node sits in a nested def (that def
        has its own FunctionTaint)."""
        cur: Optional[ast.AST] = node
        while cur is not None and cur is not self.fn:
            if cur in self.cfg.stmt_node:
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and cur is not node:
                    return None
                return cur
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return None
            cur = self.mt.module.parents.get(cur)
        return None

    def env_at(self, stmt: ast.AST) -> Optional[FrozenSet[str]]:
        cn = self.cfg.stmt_node.get(stmt)
        if cn is None:
            return None
        return self.taint_in.get(cn.nid)

    def expr_tainted(self, node: ast.AST) -> bool:
        """Is ``node`` (an expression somewhere in this function) a
        traced/device value where it is evaluated? False whenever the
        statement cannot be located or is unreachable — silence over
        guessing."""
        stmt = self.enclosing_stmt(node)
        if stmt is None:
            return False
        env = self.env_at(stmt)
        if env is None:
            return False
        return self._expr(node, env)


class ModuleTaint:
    """Jit-site inventory + traced contexts + per-function taint for
    one module."""

    def __init__(self, module: ModuleInfo) -> None:
        self.module = module
        self.sites: List[JitSite] = []
        # names bound to jitted callables ("step", "self._step", ...)
        self.jitted_names: Set[str] = set()
        # names routed through a *.timed_compile(...) first argument —
        # compiles the CompileLedger can see (TPU018's sanction set)
        self.sanctioned: Set[str] = set()
        # traced functions: id(fn) → (fn, display name)
        self.contexts: Dict[int, Tuple[FunctionNode, str]] = {}
        # id(fn) → tainted entry param names
        self._entry: Dict[int, Set[str]] = {}
        self._taints: Dict[int, FunctionTaint] = {}
        self._defs: Dict[str, List[FunctionNode]] = {}
        if not self._worth_analyzing():
            return
        for fn in astutil.functions(module.tree):
            self._defs.setdefault(fn.name, []).append(fn)
        self._collect_sites()
        self._collect_aliases()
        self._collect_sanctioned()
        self._seed_contexts()
        self._propagate()

    def _worth_analyzing(self) -> bool:
        # cheap textual gate: no jax in the file ⇒ no jit sites, no
        # traced values, nothing for five checkers to do
        src = self.module.source
        return "jax" in src or "jnp" in src

    # -- site inventory ----------------------------------------------------

    def _site_kwargs(self, call: ast.Call) -> Tuple[
            Optional[Tuple[int, ...]], Optional[Tuple[str, ...]],
            Optional[Tuple[int, ...]], bool]:
        """(static_argnums, static_argnames, donate_argnums, present)
        — each resolved to a literal tuple or None for "present but
        unresolvable". ``present`` flags per-kwarg existence via the
        sentinel: absent kwargs resolve to ()."""
        sn: Optional[Tuple[int, ...]] = ()
        sa: Optional[Tuple[str, ...]] = ()
        dn: Optional[Tuple[int, ...]] = ()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                sn = _int_tuple(kw.value)
            elif kw.arg == "static_argnames":
                sa = _str_tuple(kw.value)
            elif kw.arg in ("donate_argnums", "donate_argnames"):
                dn = _int_tuple(kw.value) \
                    if kw.arg == "donate_argnums" else None
        return sn, sa, dn, True

    def _ancestry(self, node: ast.AST) -> Tuple[Optional[str], bool]:
        """(enclosing function name, inside-a-loop?) — the loop check
        stops at the first enclosing def (a jit built inside a nested
        function inside a loop runs on that function's schedule, which
        this module-local analysis cannot see)."""
        in_loop = False
        cur = self.module.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                in_loop = True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur.name, in_loop
            cur = self.module.parents.get(cur)
        return None, in_loop

    def _collect_sites(self) -> None:
        # decorator form
        for fn in astutil.functions(self.module.tree):
            decs = set(astutil.decorator_names(fn))
            if not (decs & JIT_NAMES):
                continue
            sn: Optional[Tuple[int, ...]] = ()
            sa: Optional[Tuple[str, ...]] = ()
            dn: Optional[Tuple[int, ...]] = ()
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call):
                    sn, sa, dn, _ = self._site_kwargs(dec)
            enclosing, in_loop = self._ancestry(fn)
            self.sites.append(JitSite(
                node=fn, lineno=fn.lineno, kind="decorator",
                wrapped=fn.name, bound={fn.name}, enclosing=enclosing,
                in_loop=in_loop, immediate=False, fresh_callee=False,
                static_argnums=sn, static_argnames=sa,
                donate_argnums=dn))
        # call form
        for node in ast.walk(self.module.tree):
            if not isinstance(node, ast.Call):
                continue
            if (astutil.call_name(node) or "") not in JIT_NAMES:
                continue
            wrapped, fresh = "", False
            if node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Lambda):
                    wrapped, fresh = "<lambda>", True
                elif isinstance(arg, ast.Call):
                    inner = astutil.call_name(arg) or ""
                    if inner in ("functools.partial", "partial"):
                        fresh = True
                        if arg.args:
                            wrapped = astutil.dotted_name(arg.args[0]) \
                                or "<partial>"
                        else:
                            wrapped = "<partial>"
                    else:
                        wrapped = inner or "<call>"
                else:
                    wrapped = astutil.dotted_name(arg) or ""
            sn, sa, dn, _ = self._site_kwargs(node)
            bound: Set[str] = set()
            immediate = False
            parent = self.module.parents.get(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                immediate = True
            elif isinstance(parent, ast.Assign):
                for tgt in parent.targets:
                    name = _bindable_name(tgt)
                    if name is not None:
                        bound.add(name)
            elif isinstance(parent, ast.AnnAssign):
                name = _bindable_name(parent.target)
                if name is not None:
                    bound.add(name)
            enclosing, in_loop = self._ancestry(node)
            self.sites.append(JitSite(
                node=node, lineno=node.lineno, kind="call",
                wrapped=wrapped, bound=bound, enclosing=enclosing,
                in_loop=in_loop, immediate=immediate,
                fresh_callee=fresh, static_argnums=sn,
                static_argnames=sa, donate_argnums=dn))
        for site in self.sites:
            self.jitted_names |= site.bound

    def _collect_aliases(self) -> None:
        """``self._prefill = _prefill_and_sample`` style re-bindings of
        a jitted callable: the alias is jitted too (and inherits the
        site's donate/static contract for the call-site rules)."""
        by_name: Dict[str, JitSite] = {}
        for site in self.sites:
            for b in site.bound:
                by_name.setdefault(b, site)
        for _ in range(2):  # alias-of-alias, one extra hop
            changed = False
            for node in ast.walk(self.module.tree):
                if not isinstance(node, ast.Assign):
                    continue
                src = _bindable_name(node.value)
                if src is None or src not in by_name:
                    continue
                site = by_name[src]
                for tgt in node.targets:
                    name = _bindable_name(tgt)
                    if name is not None and name not in site.bound:
                        site.bound.add(name)
                        self.jitted_names.add(name)
                        by_name.setdefault(name, site)
                        changed = True
            if not changed:
                break

    def _collect_sanctioned(self) -> None:
        for node in ast.walk(self.module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) \
                    or func.attr != "timed_compile" or not node.args:
                continue
            name = _bindable_name(node.args[0]) \
                or astutil.dotted_name(node.args[0])
            if name:
                self.sanctioned.add(name)

    def site_for_name(self, name: str) -> Optional[JitSite]:
        """The unique site a bound name resolves to; None when the name
        is unknown or ambiguous (two sites claim it)."""
        found = None
        for site in self.sites:
            if name in site.bound:
                if found is not None and found is not site:
                    return None
                found = site
        return found

    # -- traced-context discovery ------------------------------------------

    def _params_of(self, fn: FunctionNode) -> List[str]:
        a = fn.args
        names = [p.arg for p in a.posonlyargs + a.args]
        return [n for n in names if n != "self"]

    def _context_entry(self, fn: FunctionNode, site: Optional[JitSite],
                       ) -> Set[str]:
        params = self._params_of(fn)
        static: Set[str] = set()
        if site is not None:
            for i in site.static_argnums or ():
                if 0 <= i < len(params):
                    static.add(params[i])
            static |= set(site.static_argnames or ())
        kwonly = [p.arg for p in fn.args.kwonlyargs]
        return (set(params) | set(kwonly)) - static

    def _add_context(self, fn: FunctionNode, name: str,
                     entry: Set[str]) -> bool:
        key = id(fn)
        if key not in self.contexts:
            self.contexts[key] = (fn, name)
            self._entry[key] = set(entry)
            return True
        if not entry <= self._entry[key]:
            self._entry[key] |= entry
            self._taints.pop(key, None)
            return True
        return False

    def _seed_contexts(self) -> None:
        # decorated + call-form jit targets
        for site in self.sites:
            if site.kind == "decorator":
                self._add_context(site.node, site.wrapped,
                                  self._context_entry(site.node, site))
            elif site.wrapped and "." not in site.wrapped:
                for fn in self._defs.get(site.wrapped, ()):
                    self._add_context(fn, fn.name,
                                      self._context_entry(fn, site))
        # Pallas kernel bodies: first arg of pl.pallas_call (optionally
        # through functools.partial) — every Ref param is traced
        for node in ast.walk(self.module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node) or ""
            if name.split(".")[-1] != PALLAS_CALL_SUFFIX or not node.args:
                continue
            arg = node.args[0]
            target = ""
            if isinstance(arg, ast.Name):
                target = arg.id
            elif isinstance(arg, ast.Call):
                inner = astutil.call_name(arg) or ""
                if inner in ("functools.partial", "partial") and arg.args \
                        and isinstance(arg.args[0], ast.Name):
                    target = arg.args[0].id
            for fn in self._defs.get(target, ()):
                self._add_context(fn, fn.name,
                                  set(self._params_of(fn)))
        # nested defs inside any traced context are traced bodies
        # (scan/cond/while_loop callees): every parameter is a tracer
        frontier = [fn for fn, _ in list(self.contexts.values())]
        while frontier:
            ctx = frontier.pop()
            for node in ast.walk(ctx):
                if node is ctx or not isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if id(node) not in self.contexts:
                    self._add_context(
                        node, node.name,
                        set(self._params_of(node))
                        | {p.arg for p in node.args.kwonlyargs})
                    frontier.append(node)

    def _propagate(self) -> None:
        """Module-level helpers called from traced code with tainted
        arguments become traced contexts themselves (those params
        tainted) — bounded rounds, module-local."""
        for _ in range(_PROPAGATION_ROUNDS):
            changed = False
            for fn, name in list(self.contexts.values()):
                ft = self.taint_of(fn)
                for node in iter_exprs(fn):
                    if not isinstance(node, ast.Call) \
                            or not isinstance(node.func, ast.Name):
                        continue
                    targets = self._defs.get(node.func.id, ())
                    if not targets:
                        continue
                    tainted_args = [
                        i for i, a in enumerate(node.args)
                        if ft.expr_tainted(a)]
                    tainted_kw = {
                        kw.arg for kw in node.keywords
                        if kw.arg and ft.expr_tainted(kw.value)}
                    if not tainted_args and not tainted_kw:
                        continue
                    for callee in targets:
                        params = self._params_of(callee)
                        entry = {params[i] for i in tainted_args
                                 if i < len(params)} | tainted_kw
                        if entry and self._add_context(
                                callee, callee.name, entry):
                            changed = True
            if not changed:
                break

    # -- per-function taint ------------------------------------------------

    def taint_of(self, fn: FunctionNode) -> FunctionTaint:
        """The FunctionTaint for ``fn`` — traced entry params when it
        is a traced context, empty entry otherwise (host code still
        taints through calls to jitted names)."""
        key = id(fn)
        got = self._taints.get(key)
        if got is None:
            entry = frozenset(self._entry.get(key, ()))
            got = FunctionTaint(self, fn, entry)
            self._taints[key] = got
        return got

    def traced_functions(self) -> List[Tuple[FunctionNode, str]]:
        return list(self.contexts.values())

    def is_traced(self, fn: FunctionNode) -> bool:
        return id(fn) in self.contexts


def taint_analysis(module: ModuleInfo) -> ModuleTaint:
    """The module's trace-taint analysis, computed once and memoized —
    TPU014–TPU018 share one pass per file, exactly like the lock
    plane's :func:`locksets.lock_analysis`."""
    cached = getattr(module, "_trace_taint", None)
    if cached is None:
        cached = ModuleTaint(module)
        module._trace_taint = cached
    return cached
