"""Shared AST helpers for checkers: dotted names, constant resolution.

Everything here is conservative: a helper that cannot prove a fact
returns None rather than guessing, so checkers err toward silence on
code they cannot resolve (false negatives over false positives — the
baseline workflow only works if a clean run stays clean).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]


def dotted_name(node: ast.AST) -> Optional[str]:
    """``ast.Attribute``/``ast.Name`` chain → "a.b.c" (None if the
    chain includes calls/subscripts that have no static name)."""
    parts = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def const_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def is_const_true(node: ast.AST) -> bool:
    """``while True:`` / ``while 1:`` style constant-truthy tests."""
    return isinstance(node, ast.Constant) and bool(node.value)


def assignments_to(scope: ast.AST, name: str) -> Iterator[ast.AST]:
    """Yield the value expressions assigned to ``name`` anywhere in
    ``scope`` (plain and annotated assigns; ignores augmented)."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    yield node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name) and node.target.id == name:
                yield node.value


def resolve_int(scope: ast.AST, node: ast.AST) -> Optional[int]:
    """Resolve ``node`` to an int: a literal, or a name with exactly one
    literal assignment in ``scope`` (ambiguous names stay None)."""
    v = const_int(node)
    if v is not None:
        return v
    if isinstance(node, ast.Name) and scope is not None:
        values = [const_int(a) for a in assignments_to(scope, node.id)]
        ints = [v for v in values if v is not None]
        if len(values) == 1 and len(ints) == 1:
            return ints[0]
    return None


def functions(tree: ast.AST) -> Iterator[FunctionNode]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def decorator_names(fn: FunctionNode) -> Iterator[str]:
    """Dotted names of decorators, looking through ``functools.partial``
    and bare calls: ``@functools.partial(jax.jit, ...)`` yields both
    "functools.partial" and "jax.jit"."""
    for dec in fn.decorator_list:
        name = dotted_name(dec)
        if name:
            yield name
        elif isinstance(dec, ast.Call):
            name = dotted_name(dec.func)
            if name:
                yield name
            if name in ("functools.partial", "partial") and dec.args:
                inner = dotted_name(dec.args[0])
                if inner:
                    yield inner
