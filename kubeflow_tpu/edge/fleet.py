"""Fleet serving edge: prefix-affinity routing + SLO-class load shedding.

The composition layer over pieces that already exist (docs/EDGE.md):
the edge proxy fronts the fleet, the autoscaler owns the replica set,
each replica runs the paged decode engine with its prefix trie
(PR 6/7), and every hop is traced (PR 3). This module makes them one
edge that serves millions of users fast:

- **Prefix-affinity routing** (:class:`FleetRouter`): a request's
  page-aligned prompt prefix hashes — same content-hash-chain scheme
  as the backend trie, :mod:`kubeflow_tpu.edge.affinity` — onto a
  bounded-load consistent-hash ring of replicas. Repeated and
  shared-prefix prompts land on the replica whose trie already holds
  those pages, turning per-replica ``prefix_hits`` into a fleet
  property; scale events remap only the affected arcs, and a hot
  prefix spills down-ring before it melts one backend.
- **SLO-class admission** (:class:`SloAdmissionGate`): requests carry
  a class (``X-Kftpu-Slo-Class`` header against a table), and under
  overload the edge sheds lowest-class-first BEFORE queue collapse —
  the gate watches the backend queue-wait / free-page telemetry the
  edge already scrapes, every shed increments
  ``kftpu_edge_shed_total{class}`` and records an ``edge.shed`` span
  in the request's trace. Shedding gates ADMISSION only: an in-flight
  streamed response is never cut.
- **Model multiplexing** rides along per backend
  (:mod:`kubeflow_tpu.serving.multiplex`): the router is
  model-agnostic, the multiplexer's snapshot feeds the same autoscaler
  poll, and the fleet view surfaces cold-start ms per model.

Everything here is host-side control plane: deterministic, injectable
clock/dispatch, adjudicable on CPU (hit-rate and shed counters, not
chip clocks).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Tuple

import numpy as np

from kubeflow_tpu.edge.affinity import HashRing, affinity_key
from kubeflow_tpu.obs import TRACER
from kubeflow_tpu.obs import requests as reqobs
from kubeflow_tpu.utils import DEFAULT_REGISTRY

log = logging.getLogger(__name__)

_shed_c = DEFAULT_REGISTRY.counter(
    "kftpu_edge_shed_total", "requests shed by SLO class under overload")
_fleet_requests_c = DEFAULT_REGISTRY.counter(
    "kftpu_edge_fleet_requests_total", "requests dispatched per replica")
_spills_c = DEFAULT_REGISTRY.counter(
    "kftpu_edge_affinity_spills_total",
    "affinity keys routed past their home replica by the load bound")
_pressure_g = DEFAULT_REGISTRY.gauge(
    "kftpu_edge_fleet_pressure", "fleet overload pressure [0, 1]")

SLO_HEADER = "X-Kftpu-Slo-Class"

# class -> (rank, shed_at): rank orders criticality (higher survives
# longer), shed_at is the fleet pressure at which the class sheds.
# Lowest-class-first by construction: shed_at grows with rank, and
# "interactive" holds until actual collapse territory.
DEFAULT_SLO_CLASSES: Dict[str, Tuple[int, float]] = {
    "batch": (0, 0.70),
    "standard": (1, 0.90),
    "interactive": (2, 0.98),
}
DEFAULT_SLO_CLASS = "standard"


def slo_classes_from_env() -> Dict[str, Tuple[int, float]]:
    """``KFTPU_SLO_CLASSES`` JSON (``{"name": [rank, shed_at], ...}``)
    or the default table."""
    raw = os.environ.get("KFTPU_SLO_CLASSES", "")
    if not raw:
        return dict(DEFAULT_SLO_CLASSES)
    table = {}
    for name, spec in json.loads(raw).items():
        rank, shed_at = spec
        table[str(name)] = (int(rank), float(shed_at))
    return table


class DispatchError(RuntimeError):
    """A dispatch that failed WITH a meaningful status: the edge
    relays ``code``/``payload`` to the client (the status-relay
    convention the other proxies follow — a backend 400 must reach the
    client as a 400, a dead replica as a 502, never a generic edge
    500)."""

    def __init__(self, code: int, payload: Any) -> None:
        super().__init__(f"dispatch failed with {code}")
        self.code = int(code)
        self.payload = payload


# pages of prefix the router keys on by default: deep enough to cover
# typical shared system prompts, bounded so the dispatch hot path never
# hashes O(prompt) and late-diverging prompts still share their key
# (and their warm replica). 0 opts into exact full-prefix keying.
DEFAULT_AFFINITY_PAGES = 16


@dataclasses.dataclass
class FleetRequest:
    """One request at the fleet edge. ``prompt``/``prefix_len`` drive
    affinity; ``body``/``path`` are what dispatch forwards; headers
    carry the SLO class."""

    prompt: Any = None
    prefix_len: int = 0
    path: str = ""
    body: Optional[Dict[str, Any]] = None
    headers: Optional[Dict[str, str]] = None


class SloAdmissionGate:
    """Shed-before-collapse admission by SLO class.

    Pressure comes from the backend telemetry the edge already
    scrapes (:meth:`observe_snapshot` per replica): queue wait against
    its SLO bound, KV-page exhaustion, and admission-queue depth per
    slot — the max of whichever signals the snapshot carries, averaged
    across replicas. A class sheds while fleet pressure >= its
    ``shed_at``; admission is the ONLY gate (in-flight work, streamed
    or not, always completes).
    """

    def __init__(self, classes: Optional[Mapping[str, Tuple[int, float]]]
                 = None, *, default_class: Optional[str] = None,
                 queue_wait_slo_s: float = 1.0) -> None:
        # class names are case-insensitive end to end: the header value
        # lowercases at classify(), so table keys must too or an
        # env-configured "Gold" class would be unselectable by any
        # client (it would silently fall to the default)
        self.classes = {str(name).lower(): spec for name, spec in
                        (classes if classes is not None
                         else DEFAULT_SLO_CLASSES).items()}
        if not self.classes:
            raise ValueError("SLO class table may not be empty")
        if default_class is None:
            # a custom table need not contain "standard": unnamed
            # traffic defaults to the LOWEST-rank (most sheddable)
            # class — unknown clients must never inherit the most
            # protected budget
            default_class = (DEFAULT_SLO_CLASS
                             if DEFAULT_SLO_CLASS in self.classes
                             else min(self.classes,
                                      key=lambda n: self.classes[n][0]))
        default_class = default_class.lower()
        if default_class not in self.classes:
            raise ValueError(f"default class {default_class!r} not in "
                             f"table {sorted(self.classes)}")
        self.default_class = default_class
        self.queue_wait_slo_s = float(queue_wait_slo_s)
        self._pressure: Dict[str, float] = {}
        self._lock = threading.Lock()

    # -- classification ----------------------------------------------------

    def classify(self, headers: Optional[Mapping[str, str]]) -> str:
        """Header -> class name; unknown or absent values take the
        default (a client cannot invent a class the table doesn't
        price)."""
        if headers:
            for k, v in headers.items():
                if k.lower() == SLO_HEADER.lower():
                    name = v.strip().lower()
                    if name in self.classes:
                        return name
        return self.default_class

    # -- pressure ----------------------------------------------------------

    def observe_snapshot(self, replica: str, snap: Mapping[str, Any],
                         *, queue_wait_s: Optional[float] = None) -> float:
        """Fold one replica's engine/multiplex snapshot (plus an
        optional scraped ``engine_queue_wait_seconds`` reading) into
        its pressure; returns the replica's new pressure.

        Pressure is clamped to [0, 1]: it is the fraction-of-collapse
        the class thresholds price, and the fleet AVERAGE must not let
        one wedged replica (queue wait 25x its SLO) read as pressure 25
        and shed traffic nine healthy replicas could serve — a sick
        replica contributes at most 1/n to the fleet mean while the
        bounded-load ring routes around it."""
        signals = [0.0]
        if queue_wait_s is not None and self.queue_wait_slo_s > 0:
            signals.append(float(queue_wait_s) / self.queue_wait_slo_s)
        pages_total = float(snap.get("pages_total") or 0.0)
        if pages_total > 0:
            # evictable prefix-store pages are reclaimable cache, not
            # load (the observe_engine stance): affinity deliberately
            # builds deep tries, and a warm IDLE replica must not read
            # as overloaded or good warm-up would shed traffic
            held = (pages_total - float(snap.get("pages_free", 0.0))
                    - float(snap.get("pages_evictable", 0.0)))
            signals.append(max(0.0, held) / pages_total)
        slots = float(snap.get("slots") or 0.0)
        if slots > 0:
            # queue depth in slot units: pending == slots reads as
            # pressure 1.0 (a full extra fleet's worth of waiting work)
            signals.append(float(snap.get("pending", 0.0)) / slots)
        pressure = min(1.0, max(signals))
        with self._lock:
            self._pressure[replica] = pressure
        # the kftpu_edge_fleet_pressure gauge is refreshed once per
        # poll round by the caller (poll_backends / BackendPoller), not
        # per fold — n folds re-summing n entries made a round O(n^2)
        return pressure

    def forget(self, replica: str) -> None:
        with self._lock:
            self._pressure.pop(replica, None)

    def prune(self, keep) -> None:
        """Drop pressure entries for replicas no longer in ``keep`` —
        a scaled-away replica's last reading must not skew the fleet
        mean forever (an overloaded one would shed traffic the healthy
        fleet could serve; an idle one would dilute real pressure)."""
        keep = set(keep)
        with self._lock:
            for name in [n for n in self._pressure if n not in keep]:
                del self._pressure[name]

    def fleet_pressure(self) -> float:
        with self._lock:
            if not self._pressure:
                return 0.0
            return sum(self._pressure.values()) / len(self._pressure)

    def pressure_of(self, replica: str) -> float:
        with self._lock:
            return self._pressure.get(replica, 0.0)

    # -- admission ---------------------------------------------------------

    def admit(self, slo_class: str) -> Tuple[bool, float]:
        """``(admit, fleet_pressure)`` for a request of ``slo_class``."""
        _, shed_at = self.classes.get(slo_class,
                                      self.classes[self.default_class])
        pressure = self.fleet_pressure()
        return pressure < shed_at, pressure


class FleetRouter:
    """Replica picker: prefix-affinity over the bounded-load ring, or
    the round-robin twin (``policy="round_robin"``) the A/B acceptance
    test pins affinity against."""

    def __init__(self, *, page_size: int, vnodes: int = 64,
                 load_factor: float = 1.25,
                 affinity_pages: int = DEFAULT_AFFINITY_PAGES,
                 policy: str = "affinity") -> None:
        if policy not in ("affinity", "round_robin"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self.page_size = int(page_size)
        self.affinity_pages = int(affinity_pages)
        self.policy = policy
        self.ring = HashRing(vnodes=vnodes, load_factor=load_factor)
        self.targets: Dict[str, str] = {}
        self.inflight: Dict[str, int] = {}
        self._rr = 0
        self._lock = threading.Lock()

    # -- membership (autoscaler scale events) ------------------------------

    def sync(self, replicas: Mapping[str, str]
             ) -> Tuple[List[str], List[str]]:
        """Adopt the current replica set (``name -> target URL``);
        returns ``(added, removed)``. Wire this to the autoscaler's
        ready set — every reconcile tick is cheap (no-op when nothing
        changed) and only changed arcs remap."""
        with self._lock:
            added, removed = self.ring.sync(replicas.keys())
            self.targets = dict(replicas)
            for r in added:
                self.inflight.setdefault(r, 0)
            for r in removed:
                self.inflight.pop(r, None)
        if added or removed:
            log.info("fleet router: +%s -%s (%d replicas)",
                     added, removed, len(replicas))
        return added, removed

    # -- picking -----------------------------------------------------------

    def key_for(self, prompt, prefix_len: int) -> Optional[str]:
        if prompt is None:
            return None
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = int(prefix_len) if prefix_len else int(prompt.size)
        return affinity_key(prompt, n, self.page_size,
                            max_pages=self.affinity_pages)

    def pick(self, prompt=None, prefix_len: int = 0
             ) -> Optional[Tuple[str, Optional[str], bool]]:
        """``(replica, affinity_key, spilled)`` or None on an empty
        fleet. Keyless requests (no full prefix page, or the
        round-robin twin) rotate for plain load spreading.

        Picking ACQUIRES one unit of the replica's in-flight load
        under the same lock the bound was evaluated with — a separate
        read-then-increment would let M concurrent requests for one
        hot key all see the home replica idle and overshoot the spill
        bound by M. The caller must :meth:`finish` the pick."""
        key = (self.key_for(prompt, prefix_len)
               if self.policy == "affinity" else None)
        with self._lock:
            if not self.targets:
                return None
            if key is None:
                names = sorted(self.targets)
                replica = names[self._rr % len(names)]
                self._rr += 1
                spilled = False
            else:
                routed = self.ring.route(
                    key, lambda r: self.inflight.get(r, 0))
                if routed is None:
                    return None
                replica, spilled = routed
            self.inflight[replica] = self.inflight.get(replica, 0) + 1
        if spilled:
            _spills_c.inc()
        return replica, key, spilled

    def start(self, replica: str) -> None:
        """Manual load accounting for callers dispatching outside
        :meth:`pick` (pick itself already acquires)."""
        with self._lock:
            # same guard as finish(): a sync() racing the caller may
            # have popped the replica — re-inserting it would leak one
            # entry per scaled-away pod name forever
            if replica in self.inflight:
                self.inflight[replica] += 1

    def finish(self, replica: str) -> None:
        with self._lock:
            # a replica scaled away mid-request: its late finish must
            # not resurrect the popped entry (unique pod names would
            # grow the dict — and the panel's replica list — forever)
            if replica in self.inflight:
                self.inflight[replica] = max(0, self.inflight[replica] - 1)

    def target_of(self, replica: str) -> Optional[str]:
        with self._lock:
            return self.targets.get(replica)

    def view(self) -> Tuple[Dict[str, str], Dict[str, int]]:
        """(targets, inflight) under one lock read."""
        with self._lock:
            return dict(self.targets), dict(self.inflight)


class FleetEdge:
    """The composed edge: classify -> admission gate -> affinity route
    -> dispatch, with one span tree per request.

    ``dispatch(replica, target, request) -> payload`` is injectable
    (tests and the smoke drive fakes; production binds an HTTP
    forwarder). A dispatch returning an *iterator* streams: the edge
    holds the replica's in-flight count until the stream is exhausted,
    and — because the gate runs at admission only — a later shed
    decision can never cut it.
    """

    def __init__(self, router: FleetRouter, gate: SloAdmissionGate, *,
                 dispatch: Callable[[str, Optional[str], FleetRequest], Any],
                 multiplex: Any = None,
                 tracer=None, retry_after_s: int = 1,
                 request_ledger: Optional["reqobs.RequestLedger"]
                 = None) -> None:
        self.router = router
        self.gate = gate
        self.dispatch = dispatch
        self.multiplex = multiplex
        self.tracer = tracer if tracer is not None else TRACER
        # floor/fallback for Retry-After: the live value comes from the
        # scraped queue-drain window (note_drain), clamped [floor, 30]
        self.retry_after_s = int(retry_after_s)
        self.rledger = (request_ledger if request_ledger is not None
                        else reqobs.DEFAULT_LEDGER)
        self.served = 0
        self.shed: Dict[str, int] = {}
        # (pending requests fleet-wide, drain rate in req/s) from the
        # poller's last scrape window; None rate = no window yet
        self._drain: Tuple[float, Optional[float]] = (0.0, None)
        # handle() runs on ThreadingHTTPServer worker threads: the
        # panel counters must not lose increments the (locked) registry
        # counters keep, or the two sources disagree under exactly the
        # bursts the panel explains
        self._count_lock = threading.Lock()

    # -- backoff -----------------------------------------------------------

    def note_drain(self, pending: float,
                   drain_rate: Optional[float]) -> None:
        """Record one scrape window's fleet queue state (total pending
        requests + measured drain rate, req/s) — the inputs
        :meth:`retry_after` prices a shed's backoff from."""
        with self._count_lock:
            self._drain = (float(pending),
                           None if drain_rate is None
                           else float(drain_rate))

    def retry_after(self) -> int:
        """Seconds a shed client should wait: the time the measured
        drain rate needs to clear today's queue, clamped to
        [retry_after_s, 30]. Before the first drain window (or with an
        empty queue) the static floor answers; a non-draining fleet
        with work pending answers the cap — "come back in 1 s" under a
        wedged fleet just re-sheds the whole retry wave."""
        floor = max(1, self.retry_after_s)
        with self._count_lock:
            pending, rate = self._drain
        if rate is None:
            return floor
        if rate <= 0.0:
            return 30 if pending > 0 else floor
        return int(min(30, max(floor, math.ceil(pending / rate))))

    # -- request path ------------------------------------------------------

    def handle(self, request: FleetRequest) -> Tuple[int, Any]:
        """``(code, payload)``; payload is an iterator for streamed
        dispatches. 503 + Retry-After on shed (the class's budget says
        try later, not never) and on an empty fleet."""
        slo = self.gate.classify(request.headers)
        with self.tracer.span("edge.fleet.request",
                              attrs={"slo.class": slo}) as sp:
            # the request's lifecycle record keys on its trace id — the
            # same id the traceparent carries into the backend hop, so
            # the in-process engine CONTINUES this record rather than
            # opening a second one. Edge time before dispatch is
            # `admission`; the hand-off window until the engine's own
            # admission mark is `queue_wait`
            rid = sp.trace_id
            self.rledger.start(rid, t=sp.start, slo_class=slo,
                               phase=reqobs.ADMISSION)
            ok, pressure = self.gate.admit(slo)
            if not ok:
                with self._count_lock:
                    self.shed[slo] = self.shed.get(slo, 0) + 1
                _shed_c.inc(**{"class": slo})
                # the shed decision IS a span in the request trace: the
                # overload burst's trace artifact shows the shed/served
                # split without joining logs
                with self.tracer.span("edge.shed", attrs={
                        "slo.class": slo,
                        "pressure": round(pressure, 4)}):
                    pass
                sp.attrs["http.status"] = 503
                self.rledger.mark(rid, reqobs.SHED, self.tracer.clock())
                retry_s = self.retry_after()
                self.rledger.finish(rid, self.tracer.clock())
                return 503, {
                    "error": f"overloaded; class {slo!r} shed at "
                             f"pressure {pressure:.2f}",
                    "sloClass": slo,
                    "retryAfterSeconds": retry_s,
                }
            picked = self.router.pick(request.prompt, request.prefix_len)
            if picked is None:
                sp.attrs["http.status"] = 503
                self.rledger.finish(rid, self.tracer.clock())
                return 503, {"error": "no replicas in the fleet",
                             "retryAfterSeconds": self.retry_after()}
            replica, key, spilled = picked
            sp.attrs.update({"replica": replica,
                             "affinity": key is not None,
                             "spilled": spilled})
            if key is not None:
                sp.attrs["affinity.key"] = key[:16]
            target = self.router.target_of(replica)
            # pick() already acquired the in-flight unit (atomically
            # with the bound check); this block only releases it
            streaming = False
            self.rledger.mark(rid, reqobs.QUEUE_WAIT,
                              self.tracer.clock())
            try:
                payload = self.dispatch(replica, target, request)
                if _is_stream(payload):
                    streaming = True
                    sp.attrs["streamed"] = True
                    payload = self._guard_stream(replica, payload,
                                                 rid=rid)
            except DispatchError as e:
                sp.attrs["http.status"] = e.code
                self.rledger.finish(rid, self.tracer.clock())
                return e.code, e.payload
            finally:
                if not streaming:
                    self.router.finish(replica)
            with self._count_lock:
                self.served += 1
            _fleet_requests_c.inc(replica=replica)
            sp.attrs["http.status"] = 200
            if not streaming:
                # an in-process engine already finished the shared
                # record at its last token (finish() is then a no-op);
                # remote/simulated backends close here, at response
                # time — either way the record never leaks live
                self.rledger.finish(rid, self.tracer.clock())
            return 200, payload

    def _guard_stream(self, replica: str, it: Iterator, *,
                      rid: Optional[str] = None) -> Iterator:
        """Hold the replica's in-flight count for the stream's whole
        life; release exactly once however it ends — including a
        stream the caller DROPS without ever starting (a generator's
        ``finally`` never runs if no frame was entered, which would
        leak the in-flight count and spill the replica's affinity arc
        for the life of the process; the guard object releases on
        exhaustion, error, close() and GC). The release also closes the
        request's lifecycle record when the backend didn't (an
        in-process engine finishes it at last token; a remote or
        simulated stream ends here)."""
        on_release = None
        if rid is not None:
            def on_release(rid=rid):
                self.rledger.finish(rid, self.tracer.clock())
        return _StreamGuard(self.router, replica, iter(it),
                            on_release=on_release)

    # -- membership + telemetry poll ---------------------------------------

    def sync_replicas(self, replicas: Mapping[str, str]
                      ) -> Tuple[List[str], List[str]]:
        """`FleetRouter.sync` plus gate hygiene: removed replicas'
        pressure entries drop with their ring arcs. Wire THIS (not the
        router directly) to the autoscaler's ready set."""
        added, removed = self.router.sync(replicas)
        for name in removed:
            self.gate.forget(name)
        return added, removed

    def poll_backends(self, snapshots: Mapping[str, Mapping[str, Any]],
                      queue_waits: Optional[Mapping[str, float]] = None
                      ) -> float:
        """Fold one scrape round of per-replica snapshots (and optional
        queue-wait readings) into the gate; returns fleet pressure."""
        for replica, snap in snapshots.items():
            qw = (queue_waits or {}).get(replica)
            self.gate.observe_snapshot(replica, snap, queue_wait_s=qw)
        pressure = self.gate.fleet_pressure()
        _pressure_g.set(round(pressure, 4))
        return pressure

    # -- dashboard ---------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The fleet panel (dashboard ``GET /api/metrics/edge``)."""
        targets, inflight = self.router.view()
        with self._count_lock:
            served = self.served
            shed = dict(sorted(self.shed.items()))
        out: Dict[str, Any] = {
            "policy": self.router.policy,
            "pageSize": self.router.page_size,
            "replicas": [
                {"name": name, "target": targets[name],
                 "inflight": inflight.get(name, 0),
                 "pressure": round(self.gate.pressure_of(name), 4)}
                for name in sorted(targets)],
            "fleetPressure": round(self.gate.fleet_pressure(), 4),
            "sloClasses": {
                name: {"rank": rank, "shedAt": shed_at}
                for name, (rank, shed_at) in
                sorted(self.gate.classes.items())},
            "served": served,
            "shed": shed,
        }
        if self.multiplex is not None:
            snap = self.multiplex.snapshot()
            out["multiplex"] = {
                k: snap[k] for k in
                ("models_resident", "models_max", "models_evictable",
                 "models_pinned", "multiplex_loads",
                 "multiplex_evictions", "models") if k in snap}
        return out


class _StreamGuard:
    """Iterator wrapper releasing a replica's in-flight count exactly
    once, however the stream ends (see ``FleetEdge._guard_stream``)."""

    def __init__(self, router: FleetRouter, replica: str,
                 it: Iterator, *,
                 on_release: Optional[Callable[[], None]] = None) -> None:
        self._router = router
        self._replica = replica
        self._it = it
        self._on_release = on_release
        self._released = False

    def _release(self) -> None:
        if not self._released:
            self._released = True
            self._router.finish(self._replica)
            if self._on_release is not None:
                self._on_release()

    def __iter__(self) -> "_StreamGuard":
        return self

    def __next__(self):
        try:
            return next(self._it)
        except BaseException:
            # StopIteration included: exhaustion IS the happy release
            self._release()
            raise

    def close(self) -> None:
        close = getattr(self._it, "close", None)
        if close is not None:
            close()
        self._release()

    def __del__(self) -> None:
        self._release()


def _is_stream(payload: Any) -> bool:
    """Streamed dispatch = any non-materialized iterable (generators,
    iterators); dict/list/str/bytes payloads are unary."""
    return (hasattr(payload, "__next__")
            or (hasattr(payload, "__iter__")
                and not isinstance(payload, (dict, list, tuple, str,
                                             bytes))))


def http_dispatch(timeout_s: float = 120.0
                  ) -> Callable[[str, Optional[str], FleetRequest], Any]:
    """Production dispatch: POST the request body to the replica's
    target, propagating the current trace context. Unary (the serving
    server's streamed :generate path stays behind the edge proxy's
    chunked relay; the fleet edge fronts the unary plane)."""
    import urllib.error
    import urllib.request

    from kubeflow_tpu.obs import current_context, inject

    def dispatch(replica: str, target: Optional[str],
                 request: FleetRequest) -> Any:
        if not target:
            raise DispatchError(502, {"error": f"replica {replica} "
                                               "has no target"})
        headers = {"Content-Type": "application/json"}
        ctx = current_context()
        if ctx is not None:
            inject(headers, ctx)
        req = urllib.request.Request(
            target.rstrip("/") + (request.path or "/"),
            data=json.dumps(request.body or {}).encode(),
            headers=headers, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            # relay the backend's own verdict (a client's 400 is a
            # 400, not an edge 500) — the serving/edge proxy stance
            try:
                payload = json.loads(e.read() or b"{}")
            except json.JSONDecodeError:
                payload = {"error": f"backend returned {e.code}"}
            raise DispatchError(e.code, payload)
        except (urllib.error.URLError, OSError) as e:
            raise DispatchError(502, {"error": f"replica {replica} "
                                               f"unreachable: {e}"})

    return dispatch


def scrape_snapshot(text: str, *,
                    slots_hint: int = 0) -> Optional[Dict[str, float]]:
    """A backend's ``/metrics`` exposition reduced to the snapshot
    fields the admission gate folds: the paged engine's
    ``kftpu_engine_kv_pages_{free,in_use}`` gauges (summed across its
    per-model label rows) and ``kftpu_engine_pending_requests``.
    Slot capacity comes from the exposition's own
    ``kftpu_engine_slots`` gauge; ``slots_hint`` (env
    ``KFTPU_FLEET_SLOTS``) is only the fallback for backends predating
    that gauge — without either, the queue-depth signal is off. None
    when the target exposes no engine series at all (not a serving
    backend; the gate must not read it as pressure 0)."""
    from kubeflow_tpu.obs.scrape import parse_exposition

    free = in_use = evictable = pending = slots = 0.0
    qw_sum = qw_count = 0.0
    seen = False
    for s in parse_exposition(text):
        if s.name == "kftpu_engine_slots":
            slots += s.value
            seen = True
        elif s.name == "kftpu_engine_kv_pages_free":
            free += s.value
            seen = True
        elif s.name == "kftpu_engine_kv_pages_in_use":
            in_use += s.value
            seen = True
        elif s.name == "kftpu_engine_kv_pages_evictable":
            evictable += s.value
            seen = True
        elif s.name == "kftpu_engine_pending_requests":
            pending += s.value
            seen = True
        elif s.name == "engine_queue_wait_seconds_sum":
            qw_sum += s.value
            seen = True
        elif s.name == "engine_queue_wait_seconds_count":
            qw_count += s.value
            seen = True
    if not seen:
        return None
    return {"pages_total": free + in_use, "pages_free": free,
            "pages_evictable": evictable, "pending": pending,
            "slots": slots if slots > 0 else float(slots_hint),
            # cumulative histogram totals: the POLLER differences
            # consecutive scrapes into a windowed average queue wait
            # (a lifetime average would bury a fresh latency spike)
            "queue_wait_sum": qw_sum, "queue_wait_count": qw_count}


class BackendPoller:
    """Feeds the admission gate from every replica's ``/metrics`` —
    the telemetry loop that makes shedding LIVE in the deployed
    container (without it fleet pressure sits at 0 forever and the
    gate is inert). Runs on the shared reconciler runtime
    (:meth:`build_controller` — uniform ``controller.reconcile`` spans
    + counter like every other periodic loop, so a stalled shed gate
    shows its poll ticks where an operator looks for them); injectable
    ``fetch`` for tests. An unreachable or engine-less target FORGETS
    its pressure entry so a dead replica cannot drag the fleet
    average."""

    def __init__(self, edge: FleetEdge, *, interval_s: float = 2.0,
                 slots_hint: int = 0, metrics_path: str = "/metrics",
                 timeout_s: float = 2.0, fetch=None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.edge = edge
        self.interval_s = float(interval_s)
        self.slots_hint = int(slots_hint)
        self.metrics_path = metrics_path
        self.clock = clock if clock is not None else time.monotonic
        if fetch is None:
            import urllib.request

            def fetch(url: str) -> str:
                with urllib.request.urlopen(url,
                                            timeout=timeout_s) as resp:
                    return resp.read().decode("utf-8", "replace")

        self.fetch = fetch
        self._pool = None  # lazy ThreadPoolExecutor, reused per tick
        # last (queue_wait_sum, queue_wait_count, scrape time) per
        # replica: the increase between scrapes is the in-window
        # average wait — the engine_queue_wait_seconds signal the gate
        # prices against its SLO — and the count delta over wall time
        # is the replica's drain rate (a single scrape only sees
        # lifetime cumulative totals)
        self._qw_last: Dict[str, Tuple[float, float, float]] = {}

    def _window(self, name: str, snap: Mapping[str, float]
                ) -> Tuple[Optional[float], Optional[float]]:
        """``(avg queue wait s, drain rate req/s)`` over the scrape
        window, either None when this tick can't difference it (first
        scrape or a counter reset; an IDLE window still reports drain
        rate 0.0 — a queue that isn't moving is a real reading, the
        one Retry-After must price at its cap)."""
        cur = (float(snap.get("queue_wait_sum", 0.0)),
               float(snap.get("queue_wait_count", 0.0)),
               self.clock())
        prev = self._qw_last.get(name)
        self._qw_last[name] = cur
        if prev is None or cur[1] < prev[1] or cur[0] < prev[0]:
            return None, None
        dt = cur[2] - prev[2]
        rate = (cur[1] - prev[1]) / dt if dt > 0 else None
        if cur[1] <= prev[1]:
            return None, rate
        return (cur[0] - prev[0]) / (cur[1] - prev[1]), rate

    def _scrape_one(self, name: str, target: str):
        try:
            return name, scrape_snapshot(
                self.fetch(target.rstrip("/") + self.metrics_path),
                slots_hint=self.slots_hint)
        except Exception as e:  # noqa: BLE001 — any failure = down,
            # the Scraper.tick stance: a garbled backend (BadStatusLine
            # is an HTTPException, not an OSError) must cost ITS
            # reading, never abort the whole round out of pool.map and
            # freeze the fleet's pressure map
            log.warning("fleet poll: %s (%s) unreachable: %s",
                        name, target, e)
            return name, None

    def poll_once(self) -> float:
        targets, _ = self.edge.router.view()
        self.edge.gate.prune(targets)       # scaled-away replicas out
        for name in [n for n in self._qw_last if n not in targets]:
            # the queue-wait baseline goes with the replica: churned
            # pod names must not accumulate, and a re-added replica
            # must not difference its first scrape against a baseline
            # from before its absence (a window spanning the gap)
            del self._qw_last[name]
        if not targets:
            return self.edge.gate.fleet_pressure()
        # fetch CONCURRENTLY: a serial walk blocks timeout_s on each
        # dead target, staling every healthy replica's pressure by a
        # full round exactly when overload/churn makes the gate
        # matter. ONE executor for the poller's lifetime — spinning up
        # and joining a fresh pool every 2 s tick is pure thread churn
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="fleet-poll")
        results = list(self._pool.map(lambda kv: self._scrape_one(*kv),
                                      sorted(targets.items())))
        pending_total = 0.0
        drain_total: Optional[float] = None
        for name, snap in results:
            if snap is None:
                self.edge.gate.forget(name)
                self._qw_last.pop(name, None)
            else:
                wait_s, rate = self._window(name, snap)
                self.edge.gate.observe_snapshot(
                    name, snap, queue_wait_s=wait_s)
                pending_total += float(snap.get("pending", 0.0))
                if rate is not None:
                    drain_total = (rate if drain_total is None
                                   else drain_total + rate)
        # the fleet queue-drain window Retry-After is priced from:
        # pending work across every reachable replica vs how fast the
        # fleet admitted work this window
        self.edge.note_drain(pending_total, drain_total)
        pressure = self.edge.gate.fleet_pressure()
        _pressure_g.set(round(pressure, 4))
        return pressure

    def build_controller(self, interval_s: Optional[float] = None):
        """Run the poll on the shared reconciler runtime (the
        ``Controller.periodic`` lift every hand-rolled while/sleep loop
        moved to — autoscaler tick, queue cycle, scraper tick)."""
        from kubeflow_tpu.operators.controller import Controller

        interval = (interval_s if interval_s is not None
                    else self.interval_s)

        def reconcile(_ns: str, _name: str) -> float:
            self.poll_once()
            return interval

        return Controller.periodic(reconcile, name="fleet-edge-poller")


# -- deterministic fleet harness ---------------------------------------------


class ReplicaSim:
    """A backend replica reduced to what routing quality measures: a
    REAL page pool + prefix trie (the exact structures the decode
    engine places against) and the hit/miss counters. Used by the A/B
    acceptance test, ``scripts/edge_smoke.py`` and the
    ``edge_fleet`` bench config — no device, fully deterministic.

    ``serve`` mirrors the engine's paged placement accounting: trie
    match -> hit/miss -> admit a slot -> store the prefix chain ->
    retire. Serving WARMS the replica, so a router that concentrates a
    shared prefix builds one deep trie while a router that spreads it
    re-prefills everywhere — the effect under test.
    """

    def __init__(self, name: str, *, page_size: int = 4,
                 pages_total: int = 256, trie_budget_pages: int = 64,
                 slots: int = 8) -> None:
        from kubeflow_tpu.serving.kvpool import PagePool, PrefixPageStore

        self.name = name
        self.page_size = page_size
        self.pool = PagePool(pages_total, page_size, slots=slots,
                             pages_per_slot=pages_total)
        self.store = PrefixPageStore(self.pool, trie_budget_pages)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.requests = 0

    def serve(self, prompt, prefix_len: int = 0) -> Dict[str, Any]:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        prefix_len = int(prefix_len) or int(prompt.size)
        self.requests += 1
        hit = False
        if prefix_len >= self.page_size:
            match = self.store.match(prompt, prefix_len)
            hit = match.hit
            if hit:
                self.prefix_hits += 1
            else:
                self.prefix_misses += 1
            slot = 0
            need = self.pool.pages_needed(prefix_len)
            self.pool.reserve(slot, need)
            self.pool.ensure(slot, prefix_len)
            self.store.store(prompt, self.store.aligned_len(prefix_len),
                             slot)
            self.pool.release_slot(slot)
        return {"replica": self.name, "prefix_hit": hit,
                "tokens": int(prompt.size)}

    def snapshot(self) -> Dict[str, Any]:
        return {"active_slots": 0, "pending": 0,
                "slots": self.pool.slots,
                "pages_total": self.pool.pages_total,
                "pages_free": self.pool.pages_free,
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "closed": False}


def sim_dispatch(sims: Mapping[str, ReplicaSim]
                 ) -> Callable[[str, Optional[str], FleetRequest], Any]:
    """Dispatch into :class:`ReplicaSim` backends by name."""
    def dispatch(replica: str, target: Optional[str],
                 request: FleetRequest) -> Any:
        return sims[replica].serve(request.prompt, request.prefix_len)

    return dispatch


def fleet_prefix_hits(sims: Mapping[str, ReplicaSim]) -> int:
    """The fleet-level number the A/B acceptance compares."""
    return sum(s.prefix_hits for s in sims.values())


def main() -> None:  # pragma: no cover - container entrypoint
    logging.basicConfig(level=logging.INFO)
    from kubeflow_tpu.utils.jsonhttp import serve_json

    replicas = json.loads(os.environ.get("KFTPU_FLEET_REPLICAS", "{}"))
    router = FleetRouter(
        page_size=int(os.environ.get("KFTPU_FLEET_PAGE_SIZE", "16")),
        vnodes=int(os.environ.get("KFTPU_RING_VNODES", "64")),
        load_factor=float(os.environ.get("KFTPU_RING_LOAD_FACTOR",
                                         "1.25")),
        affinity_pages=int(os.environ.get(
            "KFTPU_AFFINITY_PAGES", str(DEFAULT_AFFINITY_PAGES))))
    router.sync(replicas)
    gate = SloAdmissionGate(
        slo_classes_from_env(),
        default_class=os.environ.get("KFTPU_SLO_DEFAULT_CLASS") or None,
        queue_wait_slo_s=float(os.environ.get("KFTPU_QUEUE_WAIT_SLO_S",
                                              "1.0")))
    edge = FleetEdge(router, gate, dispatch=http_dispatch())
    # the gate is only as live as its telemetry: scrape every replica's
    # /metrics on the shared reconciler runtime (docs/EDGE.md)
    BackendPoller(
        edge,
        interval_s=float(os.environ.get("KFTPU_FLEET_POLL_S", "2.0")),
        slots_hint=int(os.environ.get("KFTPU_FLEET_SLOTS", "0")),
    ).build_controller().start()

    def handler(method: str, path: str, body, user: str = "",
                headers=None):
        # route on the bare path: /healthz?probe=1 is still the probe
        bare = path.partition("?")[0]
        if method == "GET" and bare == "/healthz":
            return 200, {"ok": True, "replicas": len(replicas)}
        if method != "POST":
            # kubelet/LB probes of "/" and stray GETs must not be
            # admitted against an SLO budget, counted served, or
            # POSTed into a backend as an empty generate
            return 405, {"error": "the fleet edge serves POST "
                                  "generate/predict requests"}
        body = body or {}
        try:
            request = FleetRequest(
                prompt=body.get("prompt"),
                prefix_len=int(body.get("prefix_len", 0) or 0),
                path=path, body=body, headers=headers or {})
            return edge.handle(request)
        except (ValueError, TypeError) as e:
            # a malformed body (non-integer prompt tokens, bad
            # prefix_len) is the CLIENT's error: 400, never the
            # generic 500 serve_json answers for handler crashes
            return 400, {"error": f"bad request: {e}"}

    # the edge's own kftpu_edge_*/kftpu_multiplex_* series must be
    # scrapable where they matter (the deployed monitoring tier), not
    # only in-process: exposition on its own port, annotated on the
    # gateway-rendered Service
    from kubeflow_tpu.utils.metrics import serve_metrics

    serve_metrics(int(os.environ.get("KFTPU_FLEET_METRICS_PORT",
                                     "8089")))
    # serve_json blocks in serve_forever; the pod's lifecycle ends it
    serve_json(handler, int(os.environ.get("KFTPU_FLEET_PORT", "8088")))


if __name__ == "__main__":  # pragma: no cover
    main()
