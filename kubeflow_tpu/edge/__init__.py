"""Edge tier: ingress reverse proxy, TLS material, gateway manifests.

The reference fronts every UI/API with an API gateway + auth pair —
Ambassador (``/root/reference/kubeflow/common/ambassador.libsonnet:152-179``)
or the IAP/Envoy ingress (``/root/reference/kubeflow/gcp/iap.libsonnet``),
with basic-auth via gatekeeper + kflogin. Here the gateway is in-framework:
:mod:`kubeflow_tpu.edge.proxy` terminates the session cookie, stamps the
verified identity header, and routes path prefixes to the platform's
services. Behind it, :mod:`kubeflow_tpu.edge.fleet` composes the
serving fleet — prefix-affinity routing over a bounded-load
consistent-hash ring (:mod:`kubeflow_tpu.edge.affinity`) plus
SLO-class load shedding (docs/EDGE.md).
"""

from kubeflow_tpu.edge.proxy import EdgeProxy, Route  # noqa: F401
