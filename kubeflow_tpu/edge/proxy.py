"""Ingress reverse proxy: auth at the edge + prefix routing.

The ambassador/IAP-envoy role (reference:
``/root/reference/kubeflow/common/ambassador.libsonnet:152-179`` routes,
``/root/reference/kubeflow/gcp/iap.libsonnet`` auth-at-edge): one
process in front of every web service that

- verifies the gatekeeper session cookie on each request,
- STRIPS any client-supplied ``X-Kubeflow-Userid`` and stamps the
  verified identity instead (the backends trust this header — see
  ``kubeflow_tpu/utils/jsonhttp.py``),
- routes path prefixes to backend services (``/jupyter/`` →
  notebook web app with the prefix stripped, ``/serving/`` → model
  server, ``/login``/``/logout``/``/verify`` → gatekeeper, everything
  else → central dashboard),
- leaves the login page itself reachable without a session.

Routes are static config (env ``KFTPU_ROUTES`` JSON), not CRDs: the
platform's service set is known at deploy time, and the per-notebook
dynamic routes ride Istio VirtualServices rendered by the notebook
controller instead.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import selectors
import socket
import threading
import urllib.error
import urllib.request
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from kubeflow_tpu.obs import (
    REQUEST_ID_HEADER,
    TRACEPARENT_HEADER,
    TRACER,
    TRACESTATE_HEADER,
    format_traceparent,
)
from kubeflow_tpu.utils.jsonhttp import USER_HEADER
from kubeflow_tpu.utils.metrics import DEFAULT_REGISTRY

log = logging.getLogger(__name__)

_proxied = DEFAULT_REGISTRY.counter(
    "kftpu_edge_requests_total", "requests routed by the edge proxy")
_denied = DEFAULT_REGISTRY.counter(
    "kftpu_edge_denied_total", "requests denied at the edge")
_latency_h = DEFAULT_REGISTRY.histogram(
    "request_latency_seconds",
    "end-to-end request latency observed at the edge proxy")

# request paths that must work without a session (the login flow)
PUBLIC_PATHS = ("/login", "/login.html", "/style.css", "/logout", "/healthz")

# hop-by-hop headers never forwarded (RFC 7230 §6.1)
_HOP_BY_HOP = {"connection", "keep-alive", "proxy-authenticate",
               "proxy-authorization", "te", "trailers",
               "transfer-encoding", "upgrade", "host"}

# headers only the mesh may assert: identity (any casing) and trace
# context — a client-forged traceparent would graft its request onto an
# arbitrary trace, and a forged X-Request-Id would poison log
# correlation. Stripped exactly like X-Kubeflow-Userid, then re-stamped
# with verified values.
_STRIP_INBOUND = {USER_HEADER.lower(), REQUEST_ID_HEADER.lower(),
                  TRACEPARENT_HEADER, TRACESTATE_HEADER}


@dataclass(frozen=True)
class Route:
    prefix: str          # e.g. "/jupyter/"
    target: str          # e.g. "http://notebook-webapp"
    strip_prefix: bool = True

    def matches(self, path: str) -> bool:
        return path == self.prefix.rstrip("/") or path.startswith(self.prefix)

    def rewrite(self, path: str) -> str:
        if not self.strip_prefix:
            return path
        out = path[len(self.prefix.rstrip("/")):]
        return out if out.startswith("/") else "/" + out


def default_routes(*, dashboard: str = "http://centraldashboard",
                   webapp: str = "http://notebook-webapp",
                   serving: str = "http://model-server:8500",
                   gatekeeper: str = "http://gatekeeper:8085",
                   tensorboard: str = "http://tensorboard:80",
                   registry: str = "http://model-registry:6543") -> List[Route]:
    return [
        Route("/login", gatekeeper, strip_prefix=False),
        Route("/logout", gatekeeper, strip_prefix=False),
        Route("/jupyter/", webapp),
        Route("/serving/", serving),
        Route("/tensorboard/", tensorboard),
        # model registry API behind auth (modeldb-frontend role; the
        # dashboard's models page drives it)
        Route("/registry/", registry),
        Route("/", dashboard, strip_prefix=False),  # catch-all, keep last
        # the dashboard's /studies.html + /runs.html pages (katib-ui / KFP
        # runs parity) ride the catch-all
    ]


def routes_from_env() -> List[Route]:
    raw = os.environ.get("KFTPU_ROUTES", "")
    if not raw:
        return default_routes()
    return [Route(r["prefix"], r["target"], bool(r.get("stripPrefix", True)))
            for r in json.loads(raw)]


IAP_EMAIL_HEADER = "X-Goog-Authenticated-User-Email"


def iap_authenticator(headers: Dict[str, str]) -> Optional[str]:
    """Identity from Cloud IAP's authenticated-user header.

    Parity with the reference's IAP ingress (``/root/reference/kubeflow/
    gcp/iap.libsonnet`` — envoy checks the IAP JWT and forwards identity).
    Trust boundary: this proxy must only be reachable through the
    GCLB+IAP path (the NetworkPolicy the gateway component renders), where
    IAP strips any client-supplied copy of the header and sets
    ``accounts.google.com:<email>``."""
    value = headers.get(IAP_EMAIL_HEADER, "")
    if not value:
        return None
    return value.split(":", 1)[-1] or None


class EdgeProxy:
    """Threaded reverse proxy with cookie auth via the gatekeeper."""

    def __init__(self, routes: List[Route], *,
                 verify_url: Optional[str] = None,
                 authenticator=None) -> None:
        """``verify_url``: the gatekeeper's external-auth endpoint
        (GET, cookie in headers → 200/401, reference AuthServer.go flow);
        ``authenticator``: in-process alternative (headers → user|None).
        Neither set = auth disabled (dev mode)."""
        self.routes = list(routes)
        self.verify_url = verify_url
        self.authenticator = authenticator
        self.tunnel_idle_s = 300.0  # WebSocket idle reclaim (Jupyter pings)
        self._httpd: Optional[ThreadingHTTPServer] = None

    # -- auth --------------------------------------------------------------

    def authenticate(self, headers: Dict[str, str]) -> Optional[str]:
        if self.authenticator is not None:
            return self.authenticator(headers)
        if not self.verify_url:
            return headers.get(USER_HEADER, "") or "anonymous"
        req = urllib.request.Request(self.verify_url)
        if headers.get("Cookie"):
            req.add_header("Cookie", headers["Cookie"])
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                verdict = json.loads(resp.read())
                return verdict.get("user")
        except urllib.error.HTTPError:
            return None
        except OSError:
            log.warning("gatekeeper unreachable at %s", self.verify_url)
            return None

    def route_for(self, path: str) -> Optional[Route]:
        for r in self.routes:
            if r.matches(path):
                return r
        return None

    # -- plumbing ----------------------------------------------------------

    def _make_handler(self):
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def send_response(self, code, message=None):  # noqa: N802
                # remember the status for the root span / latency
                # histogram, and stamp the verified request id on every
                # response so a client error report names its trace
                self._last_status = code
                super().send_response(code, message)
                rid = getattr(self, "_request_id", None)
                if rid:
                    self.send_header(REQUEST_ID_HEADER, rid)

            def _forward(self) -> None:
                # keep-alive: no stale id/status leaks between requests
                self._request_id = None
                self._last_status = 0
                self._tunneled = False
                path = self.path
                clean = path.split("?")[0]
                route = proxy.route_for(clean)
                if route is None:
                    self._send(404, b'{"error": "no route"}')
                    return
                # the edge is the trace root: every request gets a fresh
                # span here (client-supplied trace context was stripped —
                # the mesh trusts only its own ids)
                with TRACER.span("edge.request", attrs={
                        "http.method": self.command,
                        "http.path": clean,
                        "route": route.prefix}) as sp:
                    self._request_id = sp.trace_id
                    try:
                        self._forward_routed(route, path, clean, sp)
                    finally:
                        code = getattr(self, "_last_status", 0)
                        sp.attrs["http.status"] = code
                        if self._tunneled:
                            # a WebSocket splice lives for hours — its
                            # lifetime is not request latency and would
                            # wreck the histogram's _sum/p99
                            sp.attrs["websocket"] = True
                        else:
                            # exemplar: the request's own trace id, so a
                            # latency bucket links straight to the trace
                            # of a request that landed in it
                            _latency_h.observe(TRACER.clock() - sp.start,
                                               exemplar_trace_id=sp.trace_id,
                                               route=route.prefix,
                                               code=str(code))

            def _forward_routed(self, route: Route, path: str, clean: str,
                                span) -> None:
                # drop hop-by-hop headers and — never trust identity or
                # trace context from outside the mesh — any casing of
                # the identity/request-id/traceparent headers
                headers = {k: v for k, v in self.headers.items()
                           if k.lower() not in _HOP_BY_HOP
                           and k.lower() not in _STRIP_INBOUND}
                public = clean in PUBLIC_PATHS or clean.rstrip("/") in (
                    p.rstrip("/") for p in PUBLIC_PATHS)
                if not public and (proxy.verify_url or proxy.authenticator):
                    user = proxy.authenticate(
                        {k: v for k, v in self.headers.items()})
                    if user is None:
                        _denied.inc()
                        if self.command == "GET" and "text/html" in \
                                self.headers.get("Accept", ""):
                            self.send_response(302)
                            self.send_header(
                                "Location", "/login.html?next=" + clean)
                            self.send_header("Content-Length", "0")
                            self.end_headers()
                            return
                        self._send(401, b'{"log": "authentication required"}')
                        return
                    headers[USER_HEADER] = user
                # stamp VERIFIED trace context (the values forged copies
                # were stripped for): backends continue this span
                headers[TRACEPARENT_HEADER] = format_traceparent(
                    span.context())
                headers[REQUEST_ID_HEADER] = span.trace_id
                if self._is_upgrade():
                    self._tunneled = True
                    self._tunnel(route, route.rewrite(path), headers)
                    return
                length = int(self.headers.get("Content-Length", "0") or 0)
                body = self.rfile.read(length) if length else None
                target = route.target.rstrip("/") + route.rewrite(path)
                req = urllib.request.Request(target, data=body,
                                             headers=headers,
                                             method=self.command)
                headers_sent = False
                try:
                    with urllib.request.urlopen(req, timeout=120) as resp:
                        self.send_response(resp.status)
                        clen = resp.headers.get("Content-Length")
                        for k, v in resp.headers.items():
                            if k.lower() not in _HOP_BY_HOP and \
                                    k.lower() != "content-length":
                                self.send_header(k, v)
                        bodiless = (resp.status in (204, 304)
                                    or self.command == "HEAD")
                        if bodiless:
                            # chunked framing is forbidden on 204/304;
                            # a stray terminator would desync keep-alive.
                            # HEAD responses legally carry the size of
                            # the body a GET would return — forward it
                            # (clients use it for existence/size probes)
                            if self.command == "HEAD" and clen is not None \
                                    and resp.status not in (204, 304):
                                self.send_header("Content-Length", clen)
                            self.end_headers()
                            headers_sent = True
                        elif clen is not None:
                            # sized upstream: stream through verbatim
                            self.send_header("Content-Length", clen)
                            self.end_headers()
                            headers_sent = True
                            while True:
                                block = resp.read(1 << 16)
                                if not block:
                                    break
                                self.wfile.write(block)
                        else:
                            # chunked upstream (streamed :generate):
                            # re-chunk AS DATA ARRIVES — buffering here
                            # would undo the server's token streaming
                            self.send_header("Transfer-Encoding",
                                             "chunked")
                            self.end_headers()
                            headers_sent = True
                            while True:
                                block = resp.read1(1 << 16)
                                if not block:
                                    break
                                self.wfile.write(
                                    f"{len(block):x}\r\n".encode() +
                                    block + b"\r\n")
                                self.wfile.flush()
                            self.wfile.write(b"0\r\n\r\n")
                        _proxied.inc(route=route.prefix)
                except urllib.error.HTTPError as e:
                    # ordered before OSError (HTTPError subclasses it):
                    # upstream 4xx/5xx bodies forward as-is
                    data = e.read()
                    self.send_response(e.code)
                    self.send_header("Content-Type",
                                     e.headers.get("Content-Type",
                                                   "application/json"))
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    if self.command != "HEAD":  # bodiless by definition
                        self.wfile.write(data)
                except (OSError, http.client.HTTPException) as e:
                    if headers_sent:
                        # mid-stream upstream death (reset, truncation —
                        # IncompleteRead is an HTTPException): the status
                        # line is long gone, so abort the connection
                        # instead of corrupting the body with a second
                        # response
                        log.warning("upstream %s died mid-stream: %s",
                                    route.target, e)
                        self.close_connection = True
                        return
                    self._send(502, json.dumps(
                        {"error": f"upstream {route.target}: {e}"}).encode())

            def _is_upgrade(self) -> bool:
                return ("upgrade" in self.headers.get("Connection", "").lower()
                        and self.headers.get("Upgrade", "").lower()
                        == "websocket")

            def _tunnel(self, route: Route, target_path: str,
                        headers: Dict[str, str]) -> None:
                """HTTP/1.1 Upgrade passthrough (RFC 6455 handshake relay).

                Replays the client's upgrade request upstream, then splices
                raw bytes in both directions — the upstream's 101 response
                and every WebSocket frame after it pass through untouched.
                This is what lets a Jupyter kernel channel (which is a
                WebSocket under ``/api/kernels/.../channels``) survive the
                auth-at-edge hop; the reference relies on ambassador for
                the same (``/root/reference/kubeflow/common/
                ambassador.libsonnet:152-179``)."""
                u = urlsplit(route.target)
                port = u.port or (443 if u.scheme == "https" else 80)
                try:
                    upstream = socket.create_connection(
                        (u.hostname, port), timeout=10)
                except OSError as e:
                    self._send(502, json.dumps(
                        {"error": f"upstream {route.target}: {e}"}).encode())
                    return
                if u.scheme == "https":
                    import ssl

                    upstream = ssl.create_default_context().wrap_socket(
                        upstream, server_hostname=u.hostname)
                # the connect timeout must not govern the splice: a slow
                # frame mid-tunnel is not connection death
                upstream.settimeout(None)
                # replay the handshake: identity-stamped headers plus the
                # hop-by-hop upgrade pair the forwarding filter stripped
                lines = [f"{self.command} {target_path} HTTP/1.1",
                         f"Host: {u.netloc}",
                         "Connection: Upgrade",
                         f"Upgrade: {self.headers.get('Upgrade')}"]
                lines += [f"{k}: {v}" for k, v in headers.items()]
                try:
                    upstream.sendall(
                        ("\r\n".join(lines) + "\r\n\r\n").encode())
                except OSError as e:
                    upstream.close()
                    self._send(502, json.dumps(
                        {"error": f"upstream {route.target}: {e}"}).encode())
                    return
                _proxied.inc(route=route.prefix)
                client = self.connection
                # drain bytes the request parser read ahead into rfile (a
                # client may pipeline its first frame with the handshake);
                # zero-timeout so an empty buffer doesn't block on the OS
                client.settimeout(0)
                try:
                    pending = self.rfile.read1(65536)
                except (OSError, ValueError):
                    pending = b""
                finally:
                    client.settimeout(None)
                if pending:
                    upstream.sendall(pending)
                sel = selectors.DefaultSelector()
                sel.register(client, selectors.EVENT_READ, upstream)
                sel.register(upstream, selectors.EVENT_READ, client)
                try:
                    alive = True
                    while alive:
                        events = sel.select(timeout=proxy.tunnel_idle_s)
                        if not events:
                            break  # idle tunnel: reclaim the thread
                        for key, _ in events:
                            try:
                                data = key.fileobj.recv(65536)
                                if not data:
                                    alive = False
                                    break
                                # TLS: drain plaintext buffered inside the
                                # SSL object — select() only sees the raw fd
                                while getattr(key.fileobj, "pending",
                                              lambda: 0)():
                                    data += key.fileobj.recv(65536)
                                key.data.sendall(data)
                            except OSError:
                                alive = False
                                break
                finally:
                    sel.close()
                    try:
                        upstream.close()
                    except OSError:
                        pass
                    self.close_connection = True

            def _send(self, code: int, data: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                # HEAD responses advertise the length but carry no body
                # — writing one would desync a keep-alive connection
                if self.command != "HEAD":
                    self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                if self.path.split("?")[0] == "/healthz":
                    self._request_id = None
                    self._send(200, b'{"ok": true}')
                    return
                self._forward()

            do_POST = do_PUT = do_DELETE = do_PATCH = _forward
            # HEAD forwards like GET; the bodiless branch above keeps
            # the upstream Content-Length and sends no body
            do_HEAD = _forward

            def log_message(self, *a):
                pass

        return Handler

    def start(self, port: int = 8080) -> int:
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port),
                                          self._make_handler())
        port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        log.info("edge proxy on :%d (%d routes)", port, len(self.routes))
        return port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()


def main() -> None:
    import time

    logging.basicConfig(level=logging.INFO)
    if os.environ.get("KFTPU_EDGE_AUTH_MODE", "cookie") == "iap":
        proxy = EdgeProxy(routes_from_env(),
                          authenticator=iap_authenticator)
    else:
        proxy = EdgeProxy(
            routes_from_env(),
            verify_url=os.environ.get("KFTPU_VERIFY_URL",
                                      "http://gatekeeper:8085/verify")
            or None)
    proxy.start(int(os.environ.get("KFTPU_EDGE_PORT", "8080")))
    while True:  # serve forever; the pod's lifecycle ends the process
        time.sleep(3600)  # tpulint: disable=TPU003,TPU005


if __name__ == "__main__":
    main()
