"""Self-signed TLS material for in-cluster webhooks and edge TLS.

The reference's admission webhook ships cert Secrets in its manifests and
the API server trusts them via ``caBundle`` on the
MutatingWebhookConfiguration (``/root/reference/components/
admission-webhook/main.go:69`` + its manifests). Here the webhook pod
mints its own CA + server cert at bootstrap (cert-manager's
self-signed-issuer role, ``/root/reference/kubeflow/gcp/
cert-manager.libsonnet``) and patches the caBundle itself.
"""

from __future__ import annotations

import base64
import datetime
from dataclasses import dataclass
from typing import List, Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID


@dataclass(frozen=True)
class CertPair:
    cert_pem: bytes
    key_pem: bytes

    @property
    def cert_b64(self) -> str:
        return base64.b64encode(self.cert_pem).decode()


def _key() -> rsa.RSAPrivateKey:
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _key_pem(key: rsa.RSAPrivateKey) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())


def make_ca(common_name: str = "kubeflow-tpu-ca",
            days: int = 3650) -> Tuple[CertPair, rsa.RSAPrivateKey]:
    key = _key()
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    return CertPair(cert.public_bytes(serialization.Encoding.PEM),
                    _key_pem(key)), key


def make_server_cert(ca: CertPair, ca_key: rsa.RSAPrivateKey,
                     dns_names: List[str], days: int = 825) -> CertPair:
    """Server cert for the in-cluster DNS names (``svc.ns.svc`` forms)."""
    key = _key()
    ca_cert = x509.load_pem_x509_certificate(ca.cert_pem)
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(x509.Name([x509.NameAttribute(
                NameOID.COMMON_NAME, dns_names[0])]))
            .issuer_name(ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName(n) for n in dns_names]), critical=False)
            .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                           critical=True)
            .sign(ca_key, hashes.SHA256()))
    return CertPair(cert.public_bytes(serialization.Encoding.PEM),
                    _key_pem(key))


def webhook_certs(service: str, namespace: str) -> Tuple[CertPair, CertPair]:
    """(ca, server) pair for ``<service>.<namespace>.svc``."""
    ca, ca_key = make_ca()
    server = make_server_cert(ca, ca_key, [
        f"{service}.{namespace}.svc",
        f"{service}.{namespace}.svc.cluster.local",
        "localhost",
    ])
    return ca, server
