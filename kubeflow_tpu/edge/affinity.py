"""Prefix-affinity keys + bounded-load consistent-hash ring.

The fleet edge (:mod:`kubeflow_tpu.edge.fleet`) routes a request by
its page-aligned prompt prefix so repeated/shared-prefix prompts land
on the replica whose prefix trie already holds those KV pages
(docs/EDGE.md). Two pieces live here:

**Chain keys.** :class:`~kubeflow_tpu.serving.kvpool.PrefixPageStore`
keys each trie node on ONE full page of prompt tokens
(``tokens[i*ps:(i+1)*ps].tobytes()`` of the int32 prompt) chained under
its predecessor page. :func:`page_chain_hashes` builds a digest chain
over exactly those byte slices — ``h_i = blake2b(h_{i-1} || page_i)``
— so two prompts produce the same depth-``k`` router key **iff** their
first ``k`` pages would share the same trie chain on a backend. Router
keys and trie keys agree by construction, not by convention: there is
no second tokenizer-ish normalization step to drift.

**Bounded-load ring.** Replicas hash onto a consistent-hash ring of
virtual nodes; a key routes to the first ring position clockwise of its
hash whose replica is under its load bound (the classic
consistent-hashing-with-bounded-loads shape: capacity per replica is
``ceil(load_factor * (total_inflight + 1) / n)``). Replica add/remove
remaps only the arcs adjacent to the changed virtual nodes, and a hot
prefix spills to the NEXT ring position once its home replica hits the
bound — affinity never melts one backend.

Deterministic by design: blake2b digests, no process-seeded hashing —
the same fleet membership routes the same keys everywhere (every edge
pod computes the same ring).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

# digest size: 16 bytes is plenty for ring placement and collision
# resistance at fleet scale, and keeps keys printable in span attrs
_DIGEST_BYTES = 16


def _page_bytes(tokens: np.ndarray, i: int, page_size: int) -> bytes:
    return tokens[i * page_size:(i + 1) * page_size].tobytes()


def page_chain_hashes(tokens, prefix_len: int, page_size: int, *,
                      max_pages: int = 0) -> List[str]:
    """Digest chain over the FULL pages of ``tokens[:prefix_len]``.

    ``out[k]`` keys the chain of pages ``0..k`` — the same chain a
    backend's :class:`~kubeflow_tpu.serving.kvpool.PrefixPageStore`
    walks, built from the same int32 page byte slices. The partial
    boundary page is deliberately excluded: the trie shares it
    copy-on-write under the last FULL node, so the full-page chain is
    the unit of cross-request affinity. ``max_pages`` (> 0) stops the
    chain at that depth — the capped router key costs O(max_pages)
    hashing however long the prompt runs (this sits on the dispatch
    hot path)."""
    toks = np.asarray(tokens, np.int32).reshape(-1)
    prefix_len = min(int(prefix_len), int(toks.size))
    n_full = max(0, prefix_len) // int(page_size)
    if max_pages > 0:
        n_full = min(n_full, int(max_pages))
    out: List[str] = []
    h = b""
    for i in range(n_full):
        h = hashlib.blake2b(h + _page_bytes(toks, i, page_size),
                            digest_size=_DIGEST_BYTES).digest()
        out.append(h.hex())
    return out


def affinity_key(tokens, prefix_len: int, page_size: int, *,
                 max_pages: int = 0) -> Optional[str]:
    """The routing key for a request: the deepest chain digest of its
    page-aligned prefix, or None when the prefix holds no full page
    (nothing a backend trie could share — the router falls back to
    load-based placement).

    ``max_pages`` caps the chain depth (0 = uncapped): keying on the
    first few pages groups prompts that share a long system prefix but
    diverge later onto the SAME replica, which is where the shared
    pages live."""
    chain = page_chain_hashes(tokens, prefix_len, page_size,
                              max_pages=max_pages)
    return chain[-1] if chain else None


def _point(s: str) -> int:
    """A ring position in [0, 2^64): deterministic across processes."""
    return int.from_bytes(
        hashlib.blake2b(s.encode(), digest_size=8).digest(), "big")


class HashRing:
    """Bounded-load consistent-hash ring over named replicas.

    ``vnodes`` virtual nodes per replica smooth the arc distribution;
    ``load_factor`` (> 1.0) bounds how far any replica may run above
    the fleet mean before keys spill to the next position.
    """

    def __init__(self, replicas: Iterable[str] = (), *,
                 vnodes: int = 64, load_factor: float = 1.25) -> None:
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        if load_factor <= 1.0:
            raise ValueError("load_factor must be > 1.0 (1.0 leaves no "
                             "headroom and every hot key would spill)")
        self.vnodes = int(vnodes)
        self.load_factor = float(load_factor)
        self._points: List[Tuple[int, str]] = []  # sorted (position, replica)
        self._replicas: Dict[str, List[int]] = {}
        for r in replicas:
            self.add(r)

    # -- membership --------------------------------------------------------

    def __len__(self) -> int:
        return len(self._replicas)

    def __contains__(self, replica: str) -> bool:
        return replica in self._replicas

    @property
    def replicas(self) -> Tuple[str, ...]:
        return tuple(sorted(self._replicas))

    def add(self, replica: str) -> None:
        if replica in self._replicas:
            return
        points = [_point(f"{replica}#{i}") for i in range(self.vnodes)]
        self._replicas[replica] = points
        for p in points:
            bisect.insort(self._points, (p, replica))

    def remove(self, replica: str) -> None:
        points = self._replicas.pop(replica, None)
        if points is None:
            return
        self._points = [(p, r) for p, r in self._points if r != replica]

    def sync(self, replicas: Iterable[str]) -> Tuple[List[str], List[str]]:
        """Make membership match ``replicas`` (the autoscaler's current
        ready set); returns ``(added, removed)``. Only the changed
        replicas' arcs remap — surviving assignments are untouched, so
        a scale event never cold-starts the whole fleet's prefix
        locality."""
        want = set(replicas)
        added = sorted(want - set(self._replicas))
        removed = sorted(set(self._replicas) - want)
        for r in added:
            self.add(r)
        for r in removed:
            self.remove(r)
        return added, removed

    # -- routing -----------------------------------------------------------

    def _walk(self, key: str):
        """Replicas in ring order from the key's hash point, each
        yielded once (distinct-replica walk)."""
        if not self._points:
            return
        # chr(0x10FFFF) sorts after any replica name sharing the exact
        # hash point, so the walk starts strictly clockwise of the key
        start = bisect.bisect_right(self._points,
                                    (_point(key), chr(0x10FFFF)))
        seen = set()
        n = len(self._points)
        for i in range(n):
            _, replica = self._points[(start + i) % n]
            if replica not in seen:
                seen.add(replica)
                yield replica

    def owner(self, key: str) -> Optional[str]:
        """The key's home replica, ignoring load (the arc assignment —
        what bounded-load routing degrades to at low load)."""
        for replica in self._walk(key):
            return replica
        return None

    def route(self, key: str,
              load_of: Callable[[str], float]) -> Optional[Tuple[str, bool]]:
        """``(replica, spilled)`` for a key under the load bound, or
        None on an empty ring. ``spilled`` is True when the home
        replica was at capacity and the key moved down-ring."""
        if not self._replicas:
            return None
        loads = {r: float(load_of(r)) for r in self._replicas}
        total = sum(loads.values())
        # the request being placed counts toward the mean (total + 1),
        # keeping the bound strictly positive: an idle home replica
        # (load 0) always takes the first request for its arc. Note
        # the idle bound is load_factor/n, NOT >= 1 — on an otherwise
        # idle fleet the second concurrent request for one key already
        # spills, by design (the bound prices the fleet mean)
        bound = self.load_factor * (total + 1.0) / len(self._replicas)
        first = None
        for replica in self._walk(key):
            if first is None:
                first = replica
            if loads[replica] < bound:
                return replica, replica is not first
        # every replica at the bound simultaneously can only happen on
        # adversarial load_of readings; degrade to least-loaded
        least = min(self._replicas, key=lambda r: (loads[r], r))
        return least, least is not first
