"""ThreadSanitizer tier for the native cores — `go test -race` parity.

The reference's Go controllers get race coverage from the Go race
detector in CI; the framework's C++ runtime gets the same from a TSan
build of the stress harness (``stress_main.cc``): compile
``placement.cc`` + harness with ``-fsanitize=thread``, run it
multi-threaded, fail on any ThreadSanitizer report. Wired into the test
suite (``tests/test_native_scheduler.py``), skipping cleanly where the
toolchain lacks libtsan.
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = [os.path.join(_DIR, "placement.cc"),
            os.path.join(_DIR, "dataloader.cc"),
            os.path.join(_DIR, "stress_main.cc")]
_BIN = os.path.join(_DIR, "_kftpu_tsan_stress")


def build_tsan_stress() -> Optional[str]:
    """Build the TSan stress binary; None when the toolchain can't."""
    if (os.path.exists(_BIN)
            and all(os.path.getmtime(s) <= os.path.getmtime(_BIN)
                    for s in _SOURCES)):
        return _BIN
    cmd = ["g++", "-std=c++17", "-O1", "-g", "-fsanitize=thread",
           "-pthread", "-o", _BIN, *_SOURCES]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=180)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return _BIN if proc.returncode == 0 else None


def run_tsan_stress(n_threads: int = 8,
                    iters: int = 300) -> Tuple[bool, str]:
    """(clean, report). clean=False on races, invalid results, or crash."""
    path = build_tsan_stress()
    if path is None:
        raise RuntimeError("TSan toolchain unavailable")
    proc = subprocess.run(
        [path, str(n_threads), str(iters)], capture_output=True, text=True,
        timeout=300,
        env={**os.environ, "TSAN_OPTIONS": "halt_on_error=0 exitcode=66"})
    report = (proc.stdout + proc.stderr)[-4000:]
    clean = proc.returncode == 0 and "ThreadSanitizer" not in report
    return clean, report
