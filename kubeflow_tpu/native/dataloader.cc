// Native host-side input pipeline: threaded shard reader + batcher.
//
// The TPU equivalent of the reference workloads' tf.data input stack
// (tf_cnn_benchmarks reads TFRecords with a multi-threaded dataset;
// /root/reference/tf-controller-examples/tf-cnn/ runs it inside the
// workload container): producer threads assemble shuffled fixed-length
// float32 batches into a bounded buffer pool so host IO and device
// compute overlap. The Python side (kubeflow_tpu/data/loader.py) turns
// ready batches into device arrays with an async double-buffer.
//
// Data format: a directory of raw little-endian float32 shard files
// ("*.f32"), each a contiguous array of records of `record_len` floats.
// Epoch semantics: one shared permutation over all records per epoch,
// drop-remainder batching (the tf.data `shuffle().batch(drop=True)`
// shape).
//
// Concurrency: free-list + ready-queue of preallocated batch buffers
// (mutex + condvars), an atomic cursor over the permutation, and an
// epoch-advance critical section. The TSan stress tier exercises this
// file's locking (kubeflow_tpu/native/tsan.py).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <numeric>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<float> data;
  int64_t epoch = 0;
};

struct Loader {
  // immutable after construction. `records` is BORROWED: the caller
  // (kubeflow_tpu/data/loader.py keeps the numpy array alive for the
  // handle's lifetime) owns the memory — copying ImageNet-scale datasets
  // into the loader would double host RAM
  const float* records = nullptr;
  int64_t n_records = 0;
  int64_t record_len = 0;
  int64_t batch = 0;
  uint64_t seed = 0;

  // epoch state (all guarded by epoch_mu)
  std::mutex epoch_mu;
  std::vector<int64_t> perm;
  int64_t cursor = 0;
  int64_t epoch = 0;

  // buffer pool
  std::mutex mu;
  std::condition_variable ready_cv;
  std::condition_variable free_cv;
  std::deque<Batch*> ready;
  std::deque<Batch*> free_list;
  std::vector<Batch> pool;

  std::vector<std::thread> threads;
  std::atomic<bool> stop{false};

  void shuffle_locked() {
    std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch));
    perm.resize(static_cast<size_t>(n_records));
    std::iota(perm.begin(), perm.end(), 0);
    std::shuffle(perm.begin(), perm.end(), rng);
  }

  // claim a batch of record indices: the SNAPSHOT happens inside the
  // critical section, so a reshuffle by another producer can never
  // mutate a claim mid-copy (exactly-once per epoch is exact); only the
  // tiny index copy is serialized — the record memcpy runs unlocked
  int64_t claim(std::vector<int64_t>* idx) {
    std::lock_guard<std::mutex> lock(epoch_mu);
    if (cursor + batch > n_records) {
      // epoch exhausted (drop remainder)
      epoch += 1;
      shuffle_locked();
      cursor = 0;
    }
    idx->assign(perm.begin() + cursor, perm.begin() + cursor + batch);
    cursor += batch;
    return epoch;
  }

  void producer() {
    std::vector<int64_t> idx;
    while (!stop.load()) {
      Batch* buf = nullptr;
      {
        std::unique_lock<std::mutex> lock(mu);
        free_cv.wait(lock, [&] {
          return stop.load() || !free_list.empty();
        });
        if (stop.load()) return;
        buf = free_list.front();
        free_list.pop_front();
      }
      buf->epoch = claim(&idx);
      for (int64_t i = 0; i < batch; ++i) {
        std::memcpy(buf->data.data() + i * record_len,
                    records + idx[static_cast<size_t>(i)] * record_len,
                    static_cast<size_t>(record_len) * sizeof(float));
      }
      {
        std::lock_guard<std::mutex> lock(mu);
        ready.push_back(buf);
      }
      ready_cv.notify_one();
    }
  }
};

}  // namespace

extern "C" {

// Create a loader over `data` (n_records x record_len floats, BORROWED:
// the caller must keep the buffer alive until kftpu_loader_destroy).
// Returns an opaque handle, or null on invalid arguments.
void* kftpu_loader_create(const float* data, int64_t n_records,
                          int64_t record_len, int64_t batch,
                          int32_t n_threads, int32_t pool_size,
                          uint64_t seed) {
  if (!data || n_records <= 0 || record_len <= 0 || batch <= 0 ||
      batch > n_records || n_threads <= 0 || pool_size < 2) {
    return nullptr;
  }
  auto* l = new Loader();
  l->records = data;
  l->n_records = n_records;
  l->record_len = record_len;
  l->batch = batch;
  l->seed = seed;
  {
    std::lock_guard<std::mutex> lock(l->epoch_mu);
    l->shuffle_locked();
  }
  l->pool.resize(static_cast<size_t>(pool_size));
  for (auto& b : l->pool) {
    b.data.resize(static_cast<size_t>(batch * record_len));
    l->free_list.push_back(&b);
  }
  for (int32_t t = 0; t < n_threads; ++t) {
    l->threads.emplace_back([l] { l->producer(); });
  }
  return l;
}

// Copy the next ready batch into `out` (batch x record_len floats).
// Returns the batch's epoch number (>= 0), or -1 on shutdown.
int64_t kftpu_loader_next(void* handle, float* out) {
  auto* l = static_cast<Loader*>(handle);
  Batch* buf = nullptr;
  {
    std::unique_lock<std::mutex> lock(l->mu);
    l->ready_cv.wait(lock, [&] {
      return l->stop.load() || !l->ready.empty();
    });
    if (l->ready.empty()) return -1;
    buf = l->ready.front();
    l->ready.pop_front();
  }
  std::memcpy(out, buf->data.data(),
              static_cast<size_t>(l->batch * l->record_len) * sizeof(float));
  int64_t ep = buf->epoch;
  {
    std::lock_guard<std::mutex> lock(l->mu);
    l->free_list.push_back(buf);
  }
  l->free_cv.notify_one();
  return ep;
}

// Ready-queue depth (observability; approximate by nature).
int32_t kftpu_loader_ready(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  std::lock_guard<std::mutex> lock(l->mu);
  return static_cast<int32_t>(l->ready.size());
}

void kftpu_loader_destroy(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  l->stop.store(true);
  l->ready_cv.notify_all();
  l->free_cv.notify_all();
  for (auto& t : l->threads) t.join();
  delete l;
}

}  // extern "C"
