// ThreadSanitizer stress harness for the native placement core.
//
// The reference's CI runs its Go controllers under `go test -race`; this
// is the equivalent tier for the framework's C++ runtime (SURVEY.md §5
// race detection): hammer the exported C ABI from many threads under
// -fsanitize=thread and fail on any reported race. The core is designed
// stateless (pure functions over caller buffers) — this harness is the
// proof that stays true as the native surface grows.
//
// Built and run by kubeflow_tpu/native/tsan.py; not part of the normal
// .so build.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" {
int32_t kftpu_place_slices(const int32_t* slice_hosts,
                           const int32_t* free_hosts, int32_t n,
                           int32_t want, int32_t need_hosts, int32_t* out);
int32_t kftpu_ring_order(int32_t n_hosts, int32_t rows, int32_t cols,
                         int32_t* out);
void* kftpu_loader_create(const float* data, int64_t n_records,
                          int64_t record_len, int64_t batch,
                          int32_t n_threads, int32_t pool_size,
                          uint64_t seed);
int64_t kftpu_loader_next(void* handle, float* out);
int32_t kftpu_loader_ready(void* handle);
void kftpu_loader_destroy(void* handle);
}

namespace {

void hammer(int seed, int iters, int* failures) {
  unsigned state = static_cast<unsigned>(seed) * 2654435761u + 1u;
  auto next = [&state]() {
    state = state * 1664525u + 1013904223u;
    return state >> 8;
  };
  for (int it = 0; it < iters; ++it) {
    const int32_t n = 1 + static_cast<int32_t>(next() % 64);
    std::vector<int32_t> hosts(n), free_hosts(n), out(n);
    for (int32_t i = 0; i < n; ++i) {
      hosts[i] = 1 + static_cast<int32_t>(next() % 8);
      free_hosts[i] = static_cast<int32_t>(next() % (hosts[i] + 1));
    }
    const int32_t want = 1 + static_cast<int32_t>(next() % 4);
    const int32_t need = 1 + static_cast<int32_t>(next() % 4);
    const int32_t got =
        kftpu_place_slices(hosts.data(), free_hosts.data(), n, want, need,
                           out.data());
    if (got > 0) {
      for (int32_t k = 0; k < got; ++k) {
        if (out[k] < 0 || out[k] >= n) ++*failures;
      }
    }
    const int32_t rows = 1 + static_cast<int32_t>(next() % 4);
    const int32_t cols = 1 + static_cast<int32_t>(next() % 4);
    std::vector<int32_t> ring(rows * cols);
    if (kftpu_ring_order(rows * cols, rows, cols, ring.data()) < 0) {
      ++*failures;
    }
  }
}

}  // namespace

namespace {

// the loader's producers + several consumer threads against one handle:
// free-list/ready-queue locking, atomic epoch cursor, epoch reshuffle
int loader_stress(int n_consumers, int batches_per_consumer) {
  const int64_t n_records = 64, record_len = 8, batch = 16;
  std::vector<float> data(
      static_cast<size_t>(n_records * record_len));
  for (int64_t i = 0; i < n_records; ++i) {
    data[static_cast<size_t>(i * record_len)] = static_cast<float>(i);
  }
  void* h = kftpu_loader_create(data.data(), n_records, record_len, batch,
                                /*n_threads=*/4, /*pool_size=*/4,
                                /*seed=*/42);
  if (!h) return 1;
  std::vector<std::thread> consumers;
  std::vector<int> bad(static_cast<size_t>(n_consumers), 0);
  for (int c = 0; c < n_consumers; ++c) {
    consumers.emplace_back([&, c] {
      std::vector<float> out(static_cast<size_t>(batch * record_len));
      for (int k = 0; k < batches_per_consumer; ++k) {
        if (kftpu_loader_next(h, out.data()) < 0) {
          ++bad[static_cast<size_t>(c)];
          return;
        }
        for (int64_t r = 0; r < batch; ++r) {
          const float id = out[static_cast<size_t>(r * record_len)];
          if (id < 0 || id >= static_cast<float>(n_records)) {
            ++bad[static_cast<size_t>(c)];
          }
        }
        (void)kftpu_loader_ready(h);
      }
    });
  }
  for (auto& th : consumers) th.join();
  kftpu_loader_destroy(h);
  int total = 0;
  for (int b : bad) total += b;
  return total;
}

}  // namespace

int main(int argc, char** argv) {
  const int n_threads = argc > 1 ? std::atoi(argv[1]) : 8;
  const int iters = argc > 2 ? std::atoi(argv[2]) : 300;
  std::vector<std::thread> threads;
  std::vector<int> failures(static_cast<size_t>(n_threads), 0);
  for (int t = 0; t < n_threads; ++t) {
    threads.emplace_back(hammer, t, iters, &failures[static_cast<size_t>(t)]);
  }
  for (auto& th : threads) th.join();
  int total = 0;
  for (int f : failures) total += f;
  total += loader_stress(/*n_consumers=*/4,
                         /*batches_per_consumer=*/iters / 2);
  if (total) {
    std::fprintf(stderr, "stress: %d invalid results\n", total);
    return 1;
  }
  std::printf("stress ok: %d threads x %d iters (+loader)\n", n_threads,
              iters);
  return 0;
}
