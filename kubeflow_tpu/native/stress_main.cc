// ThreadSanitizer stress harness for the native placement core.
//
// The reference's CI runs its Go controllers under `go test -race`; this
// is the equivalent tier for the framework's C++ runtime (SURVEY.md §5
// race detection): hammer the exported C ABI from many threads under
// -fsanitize=thread and fail on any reported race. The core is designed
// stateless (pure functions over caller buffers) — this harness is the
// proof that stays true as the native surface grows.
//
// Built and run by kubeflow_tpu/native/tsan.py; not part of the normal
// .so build.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" {
int32_t kftpu_place_slices(const int32_t* slice_hosts,
                           const int32_t* free_hosts, int32_t n,
                           int32_t want, int32_t need_hosts, int32_t* out);
int32_t kftpu_ring_order(int32_t n_hosts, int32_t rows, int32_t cols,
                         int32_t* out);
}

namespace {

void hammer(int seed, int iters, int* failures) {
  unsigned state = static_cast<unsigned>(seed) * 2654435761u + 1u;
  auto next = [&state]() {
    state = state * 1664525u + 1013904223u;
    return state >> 8;
  };
  for (int it = 0; it < iters; ++it) {
    const int32_t n = 1 + static_cast<int32_t>(next() % 64);
    std::vector<int32_t> hosts(n), free_hosts(n), out(n);
    for (int32_t i = 0; i < n; ++i) {
      hosts[i] = 1 + static_cast<int32_t>(next() % 8);
      free_hosts[i] = static_cast<int32_t>(next() % (hosts[i] + 1));
    }
    const int32_t want = 1 + static_cast<int32_t>(next() % 4);
    const int32_t need = 1 + static_cast<int32_t>(next() % 4);
    const int32_t got =
        kftpu_place_slices(hosts.data(), free_hosts.data(), n, want, need,
                           out.data());
    if (got > 0) {
      for (int32_t k = 0; k < got; ++k) {
        if (out[k] < 0 || out[k] >= n) ++*failures;
      }
    }
    const int32_t rows = 1 + static_cast<int32_t>(next() % 4);
    const int32_t cols = 1 + static_cast<int32_t>(next() % 4);
    std::vector<int32_t> ring(rows * cols);
    if (kftpu_ring_order(rows * cols, rows, cols, ring.data()) < 0) {
      ++*failures;
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  const int n_threads = argc > 1 ? std::atoi(argv[1]) : 8;
  const int iters = argc > 2 ? std::atoi(argv[2]) : 300;
  std::vector<std::thread> threads;
  std::vector<int> failures(static_cast<size_t>(n_threads), 0);
  for (int t = 0; t < n_threads; ++t) {
    threads.emplace_back(hammer, t, iters, &failures[static_cast<size_t>(t)]);
  }
  for (auto& th : threads) th.join();
  int total = 0;
  for (int f : failures) total += f;
  if (total) {
    std::fprintf(stderr, "stress: %d invalid results\n", total);
    return 1;
  }
  std::printf("stress ok: %d threads x %d iters\n", n_threads, iters);
  return 0;
}
