"""Native (C++) runtime cores, loaded via ctypes.

The compute path is JAX/XLA; these are the *runtime* pieces around it
(scheduler placement today; candidates tomorrow: IO, batching). Each core
has a pure-Python twin with identical semantics — the native library is a
drop-in accelerator, never a behavioral fork — and builds on demand with
g++ (no pybind11 dependency; plain C ABI + ctypes).
"""

from kubeflow_tpu.native.build import load_library, native_available  # noqa: F401
