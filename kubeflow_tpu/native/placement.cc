// Native gang-placement core.
//
// The hot path of the slice-aware scheduler (SURVEY.md §7 hard part (a)):
// given a cluster inventory of TPU slices (free-host counts per slice,
// grouped into ICI "pods" by adjacency) and a request for S slices x H
// hosts, pick concrete slices atomically so that
//   1) only fully-free matching slices are used (a slice is indivisible),
//   2) multi-slice jobs land on adjacency-close slices (DCN hops scale
//      with id distance in the inventory ordering),
//   3) fragmentation is minimized (best-fit: prefer exact-capacity
//      slices over larger ones).
// Plus the boustrophedon host ring used for ICI-neighbor ordering.
//
// The reference has no native scheduling (optional kube-batch podgroups
// only); this core exists because placement over thousands-of-slice
// inventories sits on the operator's reconcile path.
//
// Exposed as a C ABI for ctypes; Python fallback implements the same
// algorithm (kubeflow_tpu/scheduler/native.py) and tests assert equality.

#include <algorithm>
#include <cstdint>
#include <vector>

extern "C" {

// Choose `want` slices from an inventory of `n` slices.
//   slice_hosts[i]  — host count of slice i's shape
//   free_hosts[i]   — currently free hosts in slice i
//   need_hosts      — hosts required per chosen slice (H)
//   out[want]       — chosen slice indices (inventory order)
// Returns 0 on success, -1 if infeasible.
//
// Algorithm: among feasible slices (fully free AND shape-host count ==
// need_hosts preferred; larger fully-free slices allowed as fallback),
// choose a contiguous-in-id window of `want` feasible slices minimizing
// (a) total wasted hosts, then (b) window span (adjacency proxy).
int32_t kftpu_place_slices(const int32_t* slice_hosts,
                           const int32_t* free_hosts,
                           int32_t n,
                           int32_t want,
                           int32_t need_hosts,
                           int32_t* out) {
  if (want <= 0 || n <= 0 || want > n) return -1;
  // feasible = fully free and big enough
  std::vector<int32_t> feas;
  feas.reserve(n);
  for (int32_t i = 0; i < n; ++i) {
    if (free_hosts[i] == slice_hosts[i] && slice_hosts[i] >= need_hosts) {
      feas.push_back(i);
    }
  }
  if ((int32_t)feas.size() < want) return -1;

  // slide a window of `want` feasible slices; score = (waste, span)
  int64_t best_waste = INT64_MAX;
  int64_t best_span = INT64_MAX;
  int32_t best_start = -1;
  for (int32_t s = 0; s + want <= (int32_t)feas.size(); ++s) {
    int64_t waste = 0;
    for (int32_t k = 0; k < want; ++k) {
      waste += slice_hosts[feas[s + k]] - need_hosts;
    }
    int64_t span = feas[s + want - 1] - feas[s];
    if (waste < best_waste ||
        (waste == best_waste && span < best_span)) {
      best_waste = waste;
      best_span = span;
      best_start = s;
    }
  }
  if (best_start < 0) return -1;
  for (int32_t k = 0; k < want; ++k) out[k] = feas[best_start + k];
  return 0;
}

// Boustrophedon (snake) host ring over a rows x cols host grid.
// out[n_hosts] receives the visitation order; identity when the grid
// doesn't tile. Mirrors scheduler.placement.ring_order.
int32_t kftpu_ring_order(int32_t n_hosts, int32_t rows, int32_t cols,
                         int32_t* out) {
  if (n_hosts <= 0) return -1;
  if (rows <= 0 || cols <= 0 || rows * cols != n_hosts || n_hosts <= 2) {
    for (int32_t i = 0; i < n_hosts; ++i) out[i] = i;
    return 0;
  }
  int32_t idx = 0;
  for (int32_t r = 0; r < rows; ++r) {
    if (r % 2 == 0) {
      for (int32_t c = 0; c < cols; ++c) out[idx++] = r * cols + c;
    } else {
      for (int32_t c = cols - 1; c >= 0; --c) out[idx++] = r * cols + c;
    }
  }
  return 0;
}

}  // extern "C"
