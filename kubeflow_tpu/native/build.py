"""On-demand build + ctypes loading for the native cores.

Builds ``placement.cc`` into ``_kftpu_native.so`` next to the sources the
first time it's needed (g++ -O2 -shared -fPIC; ~100ms), then caches by
source mtime. Every consumer must tolerate ``load_library() is None`` and
fall back to its Python twin — a missing compiler can never break the
framework.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = [os.path.join(_DIR, "placement.cc"),
            os.path.join(_DIR, "dataloader.cc")]
_LIB = os.path.join(_DIR, "_kftpu_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _needs_build() -> bool:
    if not os.path.exists(_LIB):
        return True
    return any(os.path.getmtime(src) > os.path.getmtime(_LIB)
               for src in _SOURCES)


def _build() -> bool:
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
           "-o", _LIB, *_SOURCES]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native build unavailable (%s); using Python fallback", e)
        return False
    if proc.returncode != 0:
        log.warning("native build failed; using Python fallback:\n%s",
                    proc.stderr[-800:])
        return False
    return True


def load_library() -> Optional[ctypes.CDLL]:
    """The loaded native library, building if required; None on failure."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if _needs_build() and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            log.warning("could not load %s (%s); using Python fallback",
                        _LIB, e)
            _load_failed = True
            return None
        lib.kftpu_place_slices.restype = ctypes.c_int32
        lib.kftpu_place_slices.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.kftpu_ring_order.restype = ctypes.c_int32
        lib.kftpu_ring_order.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.kftpu_loader_create.restype = ctypes.c_void_p
        lib.kftpu_loader_create.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_uint64,
        ]
        lib.kftpu_loader_next.restype = ctypes.c_int64
        lib.kftpu_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]
        lib.kftpu_loader_ready.restype = ctypes.c_int32
        lib.kftpu_loader_ready.argtypes = [ctypes.c_void_p]
        lib.kftpu_loader_destroy.restype = None
        lib.kftpu_loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_library() is not None
