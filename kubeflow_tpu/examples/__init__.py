"""In-framework example workloads (the tf-cnn / examples-prototypes parity)."""
