"""Podracer-style decoupled actor/learner RL — the elastic scenario proof.

The Podracer architectures paper (PAPERS.md: "Podracer architectures
for scalable Reinforcement Learning") splits an RL workload into a
*learner* gang that owns the optimizer state and a fleet of *actor*
slices that only hold a read-only copy of the policy — so the two scale
independently: actors come and go with cluster weather (preemptible
capacity, shrink offers) while the learner never restarts.

That is exactly the shape the elastic plane (docs/ELASTIC.md) exists
for, and this example proves the scenario end to end on the CPU tier:

- the **learner** trains a policy net on a fixed mesh; its TrainState
  is created once and only ever advanced by ``apply_gradients`` — the
  acceptance assertion is that its step clock is strictly monotone and
  its mesh is never rebuilt;
- the **actors** run batched rollouts of a synthetic vectorized
  environment, each actor slice on its own small mesh; when the actor
  slice count changes (2 → 1 → 2 here — a shrink offer followed by the
  capacity coming back), the pool rebuilds the actor meshes with
  :func:`~kubeflow_tpu.elastic.reshard.mesh_for_slices` and re-places
  the current policy through the SAME logical-axis reshard path the
  checkpoint-resume uses (:func:`~kubeflow_tpu.elastic.reshard.
  shard_put`) — no checkpoint needed, the params are live;
- learner → actor publication is the same ``shard_put`` each time the
  policy updates, so an actor joining after a resize sees the newest
  weights immediately.

Run: ``python -m kubeflow_tpu.examples.podracer --iterations 9``.
"""

from __future__ import annotations

import argparse
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from kubeflow_tpu.elastic.reshard import mesh_for_slices, shard_put
from kubeflow_tpu.examples.common import log_metrics, setup_logging
from kubeflow_tpu.parallel.mesh import mesh_context
from kubeflow_tpu.train import TrainState

OBS_DIM = 8
N_ACTIONS = 4
HORIZON = 16


class Policy(nn.Module):
    """Tiny policy net: obs -> action logits."""

    hidden: int = 32

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> jnp.ndarray:
        x = jnp.tanh(nn.Dense(self.hidden, name="body")(obs))
        return nn.Dense(N_ACTIONS, name="head")(x)


def policy_axes(path: Any, leaf: Any) -> tuple:
    """Logical axes for the policy's leaves — the workload-owned half
    of the reshard contract: 2-D kernels are ("embed", "mlp") (mlp
    rides tp when an actor mesh has one; replicated otherwise via
    ``shape_aware_spec``), everything else replicates."""
    ndim = getattr(leaf, "ndim", 0)
    if ndim == 2:
        return ("embed", "mlp")
    return (None,) * ndim


def _env_params(seed: int = 7) -> Dict[str, jnp.ndarray]:
    """A fixed synthetic MDP: linear-tanh dynamics, quadratic cost.
    Deterministic from the seed so every actor slice (and every test
    run) steps the identical world."""
    k1, k2 = jax.random.split(jax.random.key(seed))
    return {
        "A": jax.random.normal(k1, (OBS_DIM, OBS_DIM)) * 0.3,
        "B": jax.random.normal(k2, (N_ACTIONS, OBS_DIM)) * 0.5,
    }


def make_rollout(mesh: Any, apply_fn: Callable[..., Any],
                 env: Dict[str, jnp.ndarray]) -> Callable[..., Any]:
    """One actor slice's jitted rollout: (params, rng, s0) ->
    (obs, actions, rewards), each ``(HORIZON, batch, ...)``."""

    def rollout(params, rng, s0):
        def step(carry, _):
            s, r = carry
            r, k = jax.random.split(r)
            logits = apply_fn({"params": params}, s)
            a = jax.random.categorical(k, logits)
            s2 = jnp.tanh(s @ env["A"]
                          + jax.nn.one_hot(a, N_ACTIONS) @ env["B"])
            reward = -jnp.sum(s2 * s2, axis=-1)
            return (s2, r), (s, a, reward)

        (_, _), (obs, acts, rews) = jax.lax.scan(
            step, (s0, rng), None, length=HORIZON)
        return obs, acts, rews

    jitted = jax.jit(rollout)

    def run(params, rng, s0):
        with mesh_context(mesh):
            return jitted(params, rng, s0)

    return run


def make_update(mesh: Any) -> Callable[..., Any]:
    """The learner's jitted policy-gradient step (REINFORCE with
    reward-to-go): (state, obs, acts, rews) -> (state, metrics)."""

    def update(state: TrainState, obs, acts, rews):
        rtg = jnp.cumsum(rews[::-1], axis=0)[::-1]
        rtg = rtg - jnp.mean(rtg)

        def loss_fn(params):
            logits = state.apply_fn({"params": params}, obs)
            logp = jax.nn.log_softmax(logits)
            lp = jnp.take_along_axis(logp, acts[..., None], -1)[..., 0]
            return -jnp.mean(lp * rtg)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        new_state = state.apply_gradients(grads=grads)
        return new_state, {"loss": loss,
                           "reward": jnp.mean(rews),
                           "step": new_state.step}

    jitted = jax.jit(update)

    def run(state, obs, acts, rews):
        with mesh_context(mesh):
            return jitted(state, obs, acts, rews)

    return run


class ActorPool:
    """The elastically-scaled half: N independent actor slices, each on
    its own mesh over a fixed per-slice device budget. ``scale(n)``
    IS the resize path — rebuild meshes via ``mesh_for_slices``,
    re-place the live policy via ``shard_put`` — and never touches the
    learner."""

    def __init__(self, devices: Sequence[jax.Device], apply_fn: Any,
                 env: Dict[str, jnp.ndarray], *,
                 devices_per_slice: int = 2) -> None:
        self.devices = list(devices)
        self.apply_fn = apply_fn
        self.env = env
        self.devices_per_slice = devices_per_slice
        self.max_slices = len(self.devices) // devices_per_slice
        self.meshes: List[Any] = []
        self.rollouts: List[Callable[..., Any]] = []
        self.params: List[Any] = []
        self.resizes = 0

    @property
    def n_slices(self) -> int:
        return len(self.meshes)

    def scale(self, n: int) -> None:
        """Resize the actor fleet to ``n`` slices (the elastic event).
        Each slice's mesh is rebuilt and the CURRENT policy re-placed
        through the logical-axis reshard path."""
        if not 1 <= n <= self.max_slices:
            raise ValueError(
                f"actor slices must be in [1, {self.max_slices}], got {n}")
        live = self.params[0] if self.params else None
        per = self.devices_per_slice
        self.meshes = [
            mesh_for_slices(1, devices=self.devices[i * per:(i + 1) * per])
            for i in range(n)]
        self.rollouts = [make_rollout(m, self.apply_fn, self.env)
                         for m in self.meshes]
        self.params = ([] if live is None else
                       [shard_put(live, m, axes_fn=policy_axes)
                        for m in self.meshes])
        self.resizes += 1

    def publish(self, params: Any) -> None:
        """Learner -> actors weight push, through the same reshard
        placement (a fresh actor slice and a long-lived one get
        byte-identical copies)."""
        self.params = [shard_put(params, m, axes_fn=policy_axes)
                       for m in self.meshes]

    def collect(self, rng: Any, envs_per_actor: int) -> tuple:
        """One round of rollouts across every live actor slice;
        trajectories concatenate on the batch axis for the learner."""
        obs, acts, rews = [], [], []
        for i, run in enumerate(self.rollouts):
            k = jax.random.fold_in(rng, i)
            s0 = jax.random.normal(
                jax.random.fold_in(k, 1), (envs_per_actor, OBS_DIM))
            o, a, r = run(self.params[i], jax.random.fold_in(k, 2), s0)
            obs.append(jax.device_get(o))
            acts.append(jax.device_get(a))
            rews.append(jax.device_get(r))
        cat = lambda xs: jnp.concatenate(  # noqa: E731
            [jnp.asarray(x) for x in xs], axis=1)
        return cat(obs), cat(acts), cat(rews)


def main(argv: Optional[List[str]] = None) -> Dict[str, Any]:
    p = argparse.ArgumentParser()
    p.add_argument("--iterations", type=int, default=9)
    p.add_argument("--envs-per-actor", type=int, default=4)
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--learning-rate", type=float, default=1e-2)
    p.add_argument("--learner-devices", type=int, default=None,
                   help="devices for the learner mesh (default: half)")
    args = p.parse_args(argv)

    setup_logging()
    devs = jax.devices()
    n_learner = (args.learner_devices if args.learner_devices
                 else max(len(devs) // 2, 1))
    learner_devs = devs[:n_learner]
    actor_devs = devs[n_learner:] or devs[:1]

    model = Policy(hidden=args.hidden)
    env = _env_params()
    learner_mesh = mesh_for_slices(1, devices=learner_devs)

    params = model.init(jax.random.key(0),
                        jnp.zeros((1, OBS_DIM)))["params"]
    params = shard_put(params, learner_mesh, axes_fn=policy_axes)
    state = TrainState.create(
        apply_fn=model.apply, params=params,
        tx=optax.adam(args.learning_rate))
    update = make_update(learner_mesh)

    pool = ActorPool(actor_devs, model.apply, env)
    pool.scale(min(2, pool.max_slices))
    pool.publish(state.params)
    initial_resizes = pool.resizes

    # the elastic schedule: shrink the actor fleet mid-run (a scheduler
    # shrink offer), then grow it back (capacity returned) — 2 -> 1 -> 2
    third = max(args.iterations // 3, 1)
    schedule = {third: 1, 2 * third: min(2, pool.max_slices)}

    steps_seen: List[int] = []
    last_reward = 0.0
    for it in range(1, args.iterations + 1):
        target = schedule.get(it)
        if target is not None and target != pool.n_slices:
            pool.scale(target)
            pool.publish(state.params)
            log_metrics(it, actor_slices=pool.n_slices,
                        event="actor_resize")
        obs, acts, rews = pool.collect(
            jax.random.fold_in(jax.random.key(42), it),
            args.envs_per_actor)
        state, metrics = update(state, obs, acts, rews)
        pool.publish(state.params)
        steps_seen.append(int(metrics["step"]))
        last_reward = float(metrics["reward"])
        log_metrics(it, loss=metrics["loss"], reward=last_reward,
                    actor_slices=pool.n_slices,
                    learner_step=int(metrics["step"]))

    # the Podracer acceptance: the learner gang never restarted — its
    # step clock advanced exactly once per iteration, monotone, while
    # the actor fleet resized around it
    monotone = all(b == a + 1 for a, b in zip(steps_seen, steps_seen[1:]))
    return {
        "learner_steps": steps_seen[-1] if steps_seen else 0,
        "learner_monotone": monotone,
        "actor_resizes": pool.resizes - initial_resizes,
        "actor_slices": pool.n_slices,
        "last_reward": last_reward,
    }


if __name__ == "__main__":
    main()
