"""ResNet-50 training benchmark — the tf_cnn_benchmarks equivalent.

The reference's headline TFJob runs tf_cnn_benchmarks ResNet-50 with
synthetic data and reports images/sec (``/root/reference/kubeflow/examples/
prototypes/tf-job-simple-v1.jsonnet:28-38``). Same contract here, as an SPMD
pjit loop: ``python -m kubeflow_tpu.examples.resnet --steps 50``.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp

from kubeflow_tpu.data import DataLoader, device_feed, read_shards
from kubeflow_tpu.examples.common import launcher_init, log_metrics
from kubeflow_tpu.models.resnet import resnet50
from kubeflow_tpu.train import (
    TrainState,
    create_sharded_state,
    make_image_train_step,
    make_optimizer,
)
from kubeflow_tpu.utils.profiler import StepProfiler


def main(argv=None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--warmup-steps", type=int, default=3)
    p.add_argument("--per-device-batch", type=int, default=128)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--data-dir", default=None,
                   help="directory of .f32 shards (record = [label, "
                        "pixels...]); default: synthetic tensors")
    args = p.parse_args(argv)

    penv, mesh = launcher_init()
    batch = args.per_device_batch * jax.device_count()
    model = resnet50(num_classes=args.num_classes)
    tx = make_optimizer(0.1, warmup_steps=10, decay_steps=args.steps + 10)

    # synthetic tensors only when no real data: on the --data-dir path
    # init needs just a 2-example shape carrier, not a full resident batch
    if args.data_dir:
        images = jax.random.normal(
            jax.random.key(0), (2, args.image_size, args.image_size, 3),
            jnp.bfloat16)
        labels = None
    else:
        images = jax.random.normal(
            jax.random.key(0),
            (batch, args.image_size, args.image_size, 3), jnp.bfloat16)
        labels = jnp.zeros((batch,), jnp.int32)

    def init_fn(rng):
        variables = model.init(rng, images[:2], train=True)
        return TrainState.create(
            apply_fn=model.apply, params=variables["params"],
            batch_stats=variables["batch_stats"], tx=tx,
        )

    state, _ = create_sharded_state(init_fn, jax.random.key(0), mesh)
    step_fn = make_image_train_step(mesh)

    # real-data path: native threaded loader + async sharded device feed
    # (the tf.data role; records = [label, pixels...]). Labels split out
    # and pixels cast to bf16 on the HOST so only half the bytes cross to
    # the device; warmup also runs on feed batches so the timed loop hits
    # the warm executable (jit specializes on input shardings).
    loader = None
    feed = None
    if args.data_dir:
        import ml_dtypes
        import numpy as np

        record_len = args.image_size * args.image_size * 3 + 1
        loader = DataLoader(read_shards(args.data_dir, record_len), batch)

        def split(rec):
            return (rec[:, 1:].reshape(
                        batch, args.image_size, args.image_size, 3
                    ).astype(ml_dtypes.bfloat16),
                    rec[:, 0].astype(np.int32))

        feed = device_feed(loader, mesh, transform=split)

    def next_batch():
        if feed is not None:
            return next(feed)
        return images, labels

    try:
        metrics = None
        for _ in range(args.warmup_steps):
            state, metrics = step_fn(state, *next_batch())
        if metrics is not None:
            float(metrics["loss"])  # force completion before timing

        prof = StepProfiler.from_env()
        t0 = time.perf_counter()
        for step in range(1, args.steps + 1):
            prof.step(step)
            state, metrics = step_fn(state, *next_batch())
            if step % args.log_every == 0 or step == args.steps:
                float(metrics["loss"])
                elapsed = time.perf_counter() - t0
                ips = step * batch / elapsed
                log_metrics(step, loss=metrics["loss"],
                            images_per_sec=ips,
                            images_per_sec_per_chip=ips / jax.device_count())
        float(metrics["loss"])
        prof.close()
    finally:
        if loader is not None:
            loader.close()
    dt = time.perf_counter() - t0
    ips = args.steps * batch / dt
    log_metrics(args.steps, final=True, images_per_sec=ips,
                images_per_sec_per_chip=ips / jax.device_count())
    return ips


if __name__ == "__main__":
    main()
