"""In-container entrypoint for DataPrepJob mappers/reducers.

The spark-parity job's executor image: the operator injects the
``KFTPU_PREP_*`` env contract (:mod:`kubeflow_tpu.operators.dataprep`)
and this module runs the stage named by ``--stage`` with a built-in
record transform — or import :mod:`kubeflow_tpu.data.prep` directly for
custom transforms.

Built-in transforms (all float32 record files, ``--record-len`` wide):

- ``normalize``  — per-feature standardize to mean 0 / std 1 with EXACT
  global stats: mappers copy shards through, the reduce stage computes
  the statistics over the full merged set (per-shard normalization at
  map time would destroy cross-shard scale information);
- ``scale``      — multiply by ``--factor`` (elementwise: map-local);
- ``identity``   — copy (useful to re-shard via the reduce stage).

Example CR (see also docs/QUICKSTART.md §6b)::

    dataprep_job("prep", ns, {
        "image": "kubeflow-tpu/platform:v1alpha1",
        "command": ["python", "-m", "kubeflow_tpu.examples.dataprep"],
        "args": ["--stage", "map", "--transform", "normalize",
                 "--record-len", "16"],
        "numShards": 64, "workers": 8,
        "input": "/data/raw", "output": "/data/ready",
        # normalize is applied BY THE REDUCE (global stats): its args
        # must carry the transform too, or the output stays raw
        "reduce": {"args": ["--stage", "reduce", "--transform",
                            "normalize", "--record-len", "16",
                            "--out-shards", "8"]},
    })
"""

from __future__ import annotations

import argparse

import numpy as np

from kubeflow_tpu.data import prep


def _transform(name: str, factor: float):
    if name == "normalize":
        def normalize(x: np.ndarray) -> np.ndarray:
            mu = x.mean(axis=0, keepdims=True)
            sd = x.std(axis=0, keepdims=True)
            return (x - mu) / np.maximum(sd, 1e-6)

        return normalize
    if name == "scale":
        return lambda x: x * factor
    if name == "identity":
        return lambda x: x
    raise SystemExit(f"unknown transform {name!r}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--stage", choices=("map", "reduce"), required=True)
    p.add_argument("--transform", default="identity",
                   help="normalize|scale|identity")
    p.add_argument("--factor", type=float, default=1.0)
    p.add_argument("--record-len", type=int, required=True)
    p.add_argument("--out-shards", type=int, default=1,
                   help="final shard count (reduce stage)")
    args = p.parse_args(argv)

    ctx = prep.PrepContext.from_env()
    fn = _transform(args.transform, args.factor)
    if args.stage == "map":
        # normalize is a GLOBAL transform: mapping with per-shard stats
        # would squash cross-shard scale/offset irreversibly before the
        # reduce sees the data — mappers copy, the reduce normalizes
        map_fn = fn
        if args.transform == "normalize":
            map_fn = lambda x: x  # noqa: E731
            print("NOTE: normalize applies at the reduce stage (global "
                  "stats); the job's reduce args must include "
                  "'--transform normalize' or the output stays raw")
        written = prep.run_map(ctx, map_fn, record_len=args.record_len)
        print(f"mapped shards {list(ctx.shards)} -> {len(written)} files")
    else:
        gfn = fn if args.transform == "normalize" else None
        written = prep.run_reduce(ctx, gfn, record_len=args.record_len,
                                  out_shards=args.out_shards)
        print(f"reduced {ctx.num_shards} shards -> {len(written)} final")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
