"""Transformer LM training job with checkpoint/resume — the flagship workload.

The DDP-BERT-equivalent of BASELINE.md config 3, as SPMD pjit with optional
tensor parallelism: ``python -m kubeflow_tpu.examples.lm --steps 100 --tp 2``.
Resumes from ``KFTPU_CHECKPOINT_DIR`` automatically after a gang restart.
"""

from __future__ import annotations

import argparse
import os
import time

import jax
import jax.numpy as jnp

from kubeflow_tpu.examples.common import (
    checkpoint_dir,
    launcher_init,
    log_metrics,
    make_step_telemetry,
)
from kubeflow_tpu.parallel.mesh import data_parallel_size
from kubeflow_tpu.models import Transformer, TransformerConfig
from kubeflow_tpu.train import (
    TrainState,
    create_sharded_state,
    make_lm_train_step,
    make_optimizer,
)
from kubeflow_tpu.train.checkpoint import CheckpointManager
from kubeflow_tpu.utils.profiler import StepProfiler


def main(argv=None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--per-device-batch", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--vocab-size", type=int, default=32000)
    p.add_argument("--d-model", type=int, default=768)
    p.add_argument("--n-layers", type=int, default=12)
    p.add_argument("--n-heads", type=int, default=12)
    p.add_argument("--d-ff", type=int, default=3072)
    p.add_argument("--n-experts", type=int, default=0)
    p.add_argument("--tp", type=int, default=None)
    p.add_argument("--learning-rate", type=float, default=3e-4)
    p.add_argument("--checkpoint-every", type=int, default=50)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--export", default=None, metavar="DIR",
                   help="export the trained model for serving "
                        "(versioned model-store layout)")
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, greedy-decode N tokens as a "
                        "smoke sample")
    p.add_argument("--draft-layers", type=int, default=0, metavar="L",
                   help="with --export: also distill an L-layer draft "
                        "from the trained model and export it as the "
                        "paired speculative draft (<export>-draft, "
                        "draft_of pairing)")
    p.add_argument("--draft-distill-steps", type=int, default=200)
    args = p.parse_args(argv)

    penv, mesh = launcher_init(tp=args.tp)
    config = TransformerConfig(
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=args.n_heads,
        n_kv_heads=args.n_heads,
        d_ff=args.d_ff,
        max_seq_len=args.seq_len,
        n_experts=args.n_experts,
    )
    model = Transformer(config)
    batch = args.per_device_batch * data_parallel_size(mesh)
    tx = make_optimizer(args.learning_rate, warmup_steps=20,
                        decay_steps=args.steps + 1)
    sample = jnp.zeros((batch, args.seq_len), jnp.int32)

    def init_fn(rng):
        params = model.init(rng, sample)["params"]
        return TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    state, _ = create_sharded_state(init_fn, jax.random.key(0), mesh)

    ckpt = None
    start_step = 0
    if checkpoint_dir():
        ckpt = CheckpointManager(checkpoint_dir())
        state, start_step = ckpt.restore_or_init(state)
    if start_step >= args.steps:
        # restarted after the final checkpoint: nothing left to train —
        # but the export/sample side effects must still happen, or a
        # job preempted between its last checkpoint and exit never
        # delivers the model it was asked to export
        log_metrics(start_step, done=True)
        _finish(args, config, state)
        if ckpt:
            ckpt.close()
        return 0.0

    # step telemetry (docs/OBSERVABILITY.md training plane): wall time,
    # tokens/s, MFU + recompiles into the metrics registry, per-host
    # beacons to the operator when inside a gang, flight-recorder dump
    # on step failure / slow step
    telem = make_step_telemetry(tokens_per_step=batch * args.seq_len)
    step_fn = telem.wrap(make_lm_train_step(mesh))
    prof = StepProfiler.from_env()
    data_rng = jax.random.key(1234)
    t0 = time.perf_counter()
    tokens_done = 0
    for step in range(start_step + 1, args.steps + 1):
        prof.step(step)
        rng = jax.random.fold_in(data_rng, step)
        tokens = jax.random.randint(rng, (batch, args.seq_len), 0,
                                    config.vocab_size)
        state, metrics = step_fn(state, tokens)
        tokens_done += batch * args.seq_len
        if step % args.log_every == 0 or step == args.steps:
            tps = tokens_done / (time.perf_counter() - t0)
            log_metrics(step, loss=metrics["loss"],
                        grad_norm=metrics["grad_norm"],
                        tokens_per_sec=tps,
                        tokens_per_sec_per_chip=tps / jax.device_count(),
                        **{f"step_{k}": v
                           for k, v in telem.summary().items()})
        if ckpt and (step % args.checkpoint_every == 0 or step == args.steps):
            ckpt.save(step, state)
    prof.close()
    if ckpt:
        ckpt.wait()
        ckpt.close()
    _finish(args, config, state)
    return float(metrics["loss"])


def _finish(args, config, state) -> None:
    """Post-training side effects: sample + export (also on the
    restarted-after-final-checkpoint path)."""
    if args.generate:
        # train -> decode, end to end: greedy sample from the trained
        # weights through the KV-cache path (models/decode.py)
        from kubeflow_tpu.models.decode import generate

        prompt_len = max(1, min(8, config.max_seq_len // 2))
        max_new = min(args.generate, config.max_seq_len - prompt_len)
        if max_new < 1:
            log_metrics(args.steps, sample_skipped=(
                f"max_seq_len {config.max_seq_len} leaves no room to "
                "generate"))
        else:
            prompt = jax.random.randint(jax.random.key(7),
                                        (1, prompt_len), 0,
                                        config.vocab_size)
            out = generate(config, state.params, prompt,
                           max_new_tokens=max_new)
            log_metrics(args.steps, sample_tokens=out[0].tolist())
    if args.export:
        from kubeflow_tpu.serving import export_model, transformer_export_config

        vdir = export_model(
            args.export, "transformer", state.params, version=1,
            config=transformer_export_config(config))
        log_metrics(args.steps, exported=vdir)
        if args.draft_layers:
            # train → serve WITH speculative decoding, end to end: a
            # layer-truncated, self-distilled draft exported as this
            # model's paired draft (serving routes "speculative": true
            # requests through it; see train/distill.py)
            from kubeflow_tpu.train.distill import make_draft

            dcfg, dparams, stats = make_draft(
                config, state.params, n_layers=args.draft_layers,
                distill_steps=args.draft_distill_steps)
            name = os.path.basename(os.path.normpath(args.export))
            droot = os.path.join(os.path.dirname(
                os.path.normpath(args.export)), f"{name}-draft")
            ddir = export_model(
                droot, "transformer", dparams, version=1,
                config=transformer_export_config(dcfg),
                draft_of=f"{name}@1")
            log_metrics(args.steps, draft_exported=ddir,
                        draft_distill_loss=stats["last_loss"])


if __name__ == "__main__":
    main()
