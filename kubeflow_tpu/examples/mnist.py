"""MNIST training job — the 1-worker correctness smoke (BASELINE.md config 1).

Runs as a TpuJob workload: ``python -m kubeflow_tpu.examples.mnist``.
Synthetic data by default (zero-egress clusters); real MNIST via
``--data-dir`` pointing at pre-staged idx files.
"""

from __future__ import annotations

import argparse
import gzip
import os
import struct

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.examples.common import checkpoint_dir, launcher_init, log_metrics
from kubeflow_tpu.models import MnistCnn
from kubeflow_tpu.train import (
    TrainState,
    create_sharded_state,
    make_image_train_step,
    make_optimizer,
)


def load_mnist(data_dir: str) -> tuple[np.ndarray, np.ndarray]:
    """Read pre-staged idx files (train-images-idx3-ubyte.gz etc.)."""
    def read_idx(path):
        with gzip.open(path, "rb") as f:
            magic = struct.unpack(">I", f.read(4))[0]
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            return np.frombuffer(f.read(), np.uint8).reshape(dims)

    images = read_idx(os.path.join(data_dir, "train-images-idx3-ubyte.gz"))
    labels = read_idx(os.path.join(data_dir, "train-labels-idx1-ubyte.gz"))
    return images.astype(np.float32)[..., None] / 255.0, labels.astype(np.int32)


def synthetic_mnist(n: int = 4096, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Class-conditional gaussian blobs: learnable, so loss/accuracy move."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int32)
    protos = rng.randn(10, 28, 28, 1).astype(np.float32)
    images = protos[labels] + 0.3 * rng.randn(n, 28, 28, 1).astype(np.float32)
    return images, labels


def main(argv=None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument("--data-dir", default="")
    p.add_argument("--log-every", type=int, default=10)
    args = p.parse_args(argv)

    penv, mesh = launcher_init()
    images, labels = (load_mnist(args.data_dir) if args.data_dir
                      else synthetic_mnist())

    model = MnistCnn()
    tx = make_optimizer(args.learning_rate, warmup_steps=10,
                        decay_steps=args.steps)
    sample = jnp.zeros((2, 28, 28, 1))

    def init_fn(rng):
        params = model.init(rng, sample)["params"]
        return TrainState.create(
            apply_fn=lambda v, x, train=True: model.apply(v, x),
            params=params, tx=tx,
        )

    state, _ = create_sharded_state(init_fn, jax.random.key(0), mesh)
    step_fn = make_image_train_step(mesh)

    rng = np.random.RandomState(penv.process_id)
    final_acc = 0.0
    for step in range(1, args.steps + 1):
        idx = rng.randint(0, len(images), size=args.batch_size)
        state, metrics = step_fn(state, jnp.asarray(images[idx]),
                                 jnp.asarray(labels[idx]))
        if step % args.log_every == 0 or step == args.steps:
            final_acc = float(metrics["accuracy"])
            log_metrics(step, loss=metrics["loss"], accuracy=final_acc)
    return final_acc


if __name__ == "__main__":
    main()
