"""Launcher scaffolding for in-cluster training workloads.

The reference's workloads bootstrap through ``launcher.py``: parse TF_CONFIG
into PS flags, exec the benchmark, emit JSON-ish logs
(``/root/reference/tf-controller-examples/tf-cnn/launcher.py:61-93``). Here
the scaffolding is: parse the operator's env contract, bring up
``jax.distributed``, build the mesh, and log structured JSON lines the
metrics collector can scrape.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, Dict, Optional

import jax

from kubeflow_tpu.parallel import MeshConfig, ProcessEnv, create_mesh
from kubeflow_tpu.parallel import distributed as dist


def setup_logging() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(levelname)s|%(asctime)s|%(pathname)s|%(lineno)d| %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
        stream=sys.stderr,
    )


def log_metrics(step: int, **metrics: Any) -> None:
    """One JSON line per step on stdout — the scrape contract for the
    benchmark reporter and the tuning metrics collector. When the operator
    injects ``KFTPU_RESULTS_DIR`` (the kubebench experiment-PVC equivalent),
    the same line is appended to ``<dir>/<job-name>.jsonl`` for the
    ClusterRunner's collect step."""
    rec: Dict[str, Any] = {"step": step, "ts": round(time.time(), 3)}
    for k, v in metrics.items():
        rec[k] = float(v) if hasattr(v, "__float__") else v
    line = json.dumps(rec)
    print(line, flush=True)
    results_dir = os.environ.get("KFTPU_RESULTS_DIR")
    if results_dir:
        job = os.environ.get("KFTPU_JOB_NAME", "job")
        try:
            os.makedirs(results_dir, exist_ok=True)
            with open(os.path.join(results_dir, f"{job}.jsonl"), "a") as f:
                f.write(line + "\n")
        except OSError:
            logging.exception("cannot write results to %s", results_dir)


def launcher_init(
    *, pp: int = 1, tp: Optional[int] = None
) -> tuple[ProcessEnv, "jax.sharding.Mesh"]:
    """Distributed bootstrap + mesh over all visible devices.

    Consumes the operator's full env contract: on a multi-slice job
    (``MEGASCALE_NUM_SLICES > 1``) the mesh gets a ``dcn`` outer-dp axis
    across slices; pp/tp always stay within one slice so their per-layer
    collectives never cross DCN."""
    setup_logging()
    penv = dist.initialize()
    from kubeflow_tpu.parallel.mesh import auto_mesh_config

    if penv.is_multislice:
        per_slice = jax.device_count() // penv.num_slices
        slice_cfg = auto_mesh_config(per_slice, pp=pp, tp=tp)
        mesh = dist.multislice_mesh(penv, pp=slice_cfg.pp, tp=slice_cfg.tp)
        config = MeshConfig(dcn=penv.num_slices, dp=slice_cfg.dp,
                            pp=slice_cfg.pp, tp=slice_cfg.tp)
    else:
        config = auto_mesh_config(jax.device_count(), pp=pp, tp=tp)
        mesh = create_mesh(config)
    logging.info(
        "launcher up: rank %d/%d, %d devices, mesh dcn=%d dp=%d pp=%d tp=%d",
        penv.process_id, penv.num_processes, jax.device_count(),
        config.dcn, config.dp, config.pp, config.tp,
    )
    return penv, mesh


def checkpoint_dir(default: str = "") -> str:
    return os.environ.get("KFTPU_CHECKPOINT_DIR", default)


def make_step_telemetry(*, tokens_per_step: int = 0,
                        examples_per_step: int = 0,
                        client=None, **kwargs):
    """A :class:`~kubeflow_tpu.obs.steps.StepTelemetry` wired from the
    operator's env contract: job/namespace/uid identity (so the step
    spans join the operator's trace), worker index, and — when running
    inside a TpuJob gang — a beacon sink publishing this host's health
    ConfigMap for the operator's straggler aggregation. Outside a gang
    (no ``KFTPU_JOB_NAME``) telemetry stays local: metrics + flight
    recorder, no cluster traffic."""
    from kubeflow_tpu.obs.steps import (
        ENV_JOB_UID,
        StepTelemetry,
        kube_beacon_sink,
    )

    penv = dist.from_env()
    job_uid = os.environ.get(ENV_JOB_UID, "")
    sink = None
    if penv.job_name and os.environ.get("KFTPU_BEACONS", "1") != "0":
        if client is None:
            try:
                from kubeflow_tpu.k8s.client import HttpKubeClient

                client = HttpKubeClient()
            except Exception:  # noqa: BLE001 — no cluster: local-only
                client = None
        if client is not None:
            # job_uid stamps the ownerReference: beacons GC with the CR
            sink = kube_beacon_sink(client, penv.namespace, penv.job_name,
                                    penv.process_id, job_uid=job_uid)
    kwargs.setdefault("beacon_every", 10)
    kwargs.setdefault("span_every", 10)
    kwargs.setdefault("n_chips", jax.device_count())
    if "hbm_sampler" not in kwargs:
        # live HBM watermarks on every beacon (docs/OBSERVABILITY.md
        # "Compile & memory"); CPU backends (memory_stats() is None)
        # degrade to no hbm block at zero cost
        from kubeflow_tpu.obs.xprof import HbmSampler

        kwargs["hbm_sampler"] = HbmSampler(
            namespace=penv.namespace, job=penv.job_name,
            worker=penv.process_id)
    return StepTelemetry(
        job=penv.job_name, namespace=penv.namespace,
        uid=job_uid, worker=penv.process_id,
        tokens_per_step=tokens_per_step,
        examples_per_step=examples_per_step,
        beacon_sink=sink, **kwargs)


def make_compile_ledger(*, install: bool = True):
    """A :class:`~kubeflow_tpu.obs.xprof.CompileLedger` wired from the
    operator's env contract (job/namespace/uid identity so compile
    spans join the job's trace tree) and, by default, subscribed to
    ``jax.monitoring`` — from here on every backend compile this
    worker pays becomes a ``kftpu_compile_seconds`` observation and a
    ground-truth ``startup_compile`` second in the goodput ledger.
    Call ``.uninstall()`` at shutdown (or use it as a context
    manager)."""
    from kubeflow_tpu.obs.steps import ENV_JOB_UID
    from kubeflow_tpu.obs.xprof import CompileLedger

    penv = dist.from_env()
    ledger = CompileLedger(
        namespace=penv.namespace, job=penv.job_name,
        uid=os.environ.get(ENV_JOB_UID, ""), worker=penv.process_id)
    if install:
        ledger.install()
    return ledger


def report_tuning_metrics(step: int, metrics: Dict[str, Any],
                          *, final: bool = False, client=None,
                          telemetry=None) -> None:
    """Publish trial metrics when running inside a study (no-op outside).

    The study controller injects ``KFTPU_TRIAL_NAME`` and
    ``KFTPU_OBJECTIVE_METRIC``; this appends the objective's step series
    (what median early stopping reads) and, on ``final``, the metrics the
    controller harvests on success. With ``telemetry`` (a
    :class:`~kubeflow_tpu.obs.steps.StepTelemetry`), the objective series
    comes from the telemetry's per-step records
    (:func:`kubeflow_tpu.tuning.study.append_history_from_telemetry`) —
    the same measurement stream the operator beacons and the flight
    recorder see — and the final report carries its p50/p99/recompile
    summary. Failures only log — a metrics hiccup must never kill a
    training step."""
    trial = os.environ.get("KFTPU_TRIAL_NAME")
    if not trial:
        return
    # exactly one reporter per gang: every worker shares the trial env,
    # and concurrent read-modify-writes of the one metrics ConfigMap
    # would drop or duplicate history points
    if dist.from_env().process_id != 0:
        return
    ns = os.environ.get("KFTPU_NAMESPACE", "default")
    objective = os.environ.get("KFTPU_OBJECTIVE_METRIC", "")
    try:
        from kubeflow_tpu.tuning.study import (
            append_history_points,
            append_trial_history,
            report_trial_metrics,
        )

        if client is None:
            from kubeflow_tpu.k8s.client import HttpKubeClient

            # one client for the trial's lifetime, not one per step
            client = getattr(report_tuning_metrics, "_client", None)
            if client is None:
                client = HttpKubeClient()
                report_tuning_metrics._client = client
        series = (telemetry.objective_series(objective)
                  if objective and telemetry is not None else [])
        if series:
            # the telemetry series IS the objective history; 0 appended
            # from a NON-EMPTY series means the points are already
            # persisted — never an ad-hoc append that would duplicate a
            # step. An empty series (metric unresolvable from step
            # records, e.g. "accuracy" under sync=False) falls through
            # to the explicit-value path below.
            append_history_points(client, ns, trial, series)
        elif objective and objective in metrics:
            append_trial_history(client, ns, trial, step,
                                 float(metrics[objective]))
        if final:
            harvest = {k: float(v) for k, v in metrics.items()
                       if hasattr(v, "__float__")}
            if telemetry is not None:
                harvest.update({k: float(v)
                                for k, v in telemetry.summary().items()
                                if isinstance(v, (int, float))})
            report_trial_metrics(client, ns, trial, harvest)
    except Exception:  # noqa: BLE001
        logging.exception("trial metrics report failed (continuing)")
