"""Launcher scaffolding for in-cluster training workloads.

The reference's workloads bootstrap through ``launcher.py``: parse TF_CONFIG
into PS flags, exec the benchmark, emit JSON-ish logs
(``/root/reference/tf-controller-examples/tf-cnn/launcher.py:61-93``). Here
the scaffolding is: parse the operator's env contract, bring up
``jax.distributed``, build the mesh, and log structured JSON lines the
metrics collector can scrape.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, Dict, Optional

import jax

from kubeflow_tpu.parallel import MeshConfig, ProcessEnv, create_mesh
from kubeflow_tpu.parallel import distributed as dist


def setup_logging() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(levelname)s|%(asctime)s|%(pathname)s|%(lineno)d| %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
        stream=sys.stderr,
    )


def log_metrics(step: int, **metrics: Any) -> None:
    """One JSON line per step on stdout — the scrape contract for the
    benchmark reporter and the tuning metrics collector. When the operator
    injects ``KFTPU_RESULTS_DIR`` (the kubebench experiment-PVC equivalent),
    the same line is appended to ``<dir>/<job-name>.jsonl`` for the
    ClusterRunner's collect step."""
    rec: Dict[str, Any] = {"step": step, "ts": round(time.time(), 3)}
    for k, v in metrics.items():
        rec[k] = float(v) if hasattr(v, "__float__") else v
    line = json.dumps(rec)
    print(line, flush=True)
    results_dir = os.environ.get("KFTPU_RESULTS_DIR")
    if results_dir:
        job = os.environ.get("KFTPU_JOB_NAME", "job")
        try:
            os.makedirs(results_dir, exist_ok=True)
            with open(os.path.join(results_dir, f"{job}.jsonl"), "a") as f:
                f.write(line + "\n")
        except OSError:
            logging.exception("cannot write results to %s", results_dir)


def launcher_init(
    *, pp: int = 1, tp: Optional[int] = None
) -> tuple[ProcessEnv, "jax.sharding.Mesh"]:
    """Distributed bootstrap + mesh over all visible devices.

    Consumes the operator's full env contract: on a multi-slice job
    (``MEGASCALE_NUM_SLICES > 1``) the mesh gets a ``dcn`` outer-dp axis
    across slices; pp/tp always stay within one slice so their per-layer
    collectives never cross DCN."""
    setup_logging()
    penv = dist.initialize()
    from kubeflow_tpu.parallel.mesh import auto_mesh_config

    if penv.is_multislice:
        per_slice = jax.device_count() // penv.num_slices
        slice_cfg = auto_mesh_config(per_slice, pp=pp, tp=tp)
        mesh = dist.multislice_mesh(penv, pp=slice_cfg.pp, tp=slice_cfg.tp)
        config = MeshConfig(dcn=penv.num_slices, dp=slice_cfg.dp,
                            pp=slice_cfg.pp, tp=slice_cfg.tp)
    else:
        config = auto_mesh_config(jax.device_count(), pp=pp, tp=tp)
        mesh = create_mesh(config)
    logging.info(
        "launcher up: rank %d/%d, %d devices, mesh dcn=%d dp=%d pp=%d tp=%d",
        penv.process_id, penv.num_processes, jax.device_count(),
        config.dcn, config.dp, config.pp, config.tp,
    )
    return penv, mesh


def checkpoint_dir(default: str = "") -> str:
    return os.environ.get("KFTPU_CHECKPOINT_DIR", default)


def report_tuning_metrics(step: int, metrics: Dict[str, Any],
                          *, final: bool = False, client=None) -> None:
    """Publish trial metrics when running inside a study (no-op outside).

    The study controller injects ``KFTPU_TRIAL_NAME`` and
    ``KFTPU_OBJECTIVE_METRIC``; this appends the objective's step series
    (what median early stopping reads) and, on ``final``, the metrics the
    controller harvests on success. Failures only log — a metrics hiccup
    must never kill a training step."""
    trial = os.environ.get("KFTPU_TRIAL_NAME")
    if not trial:
        return
    # exactly one reporter per gang: every worker shares the trial env,
    # and concurrent read-modify-writes of the one metrics ConfigMap
    # would drop or duplicate history points
    if dist.from_env().process_id != 0:
        return
    ns = os.environ.get("KFTPU_NAMESPACE", "default")
    objective = os.environ.get("KFTPU_OBJECTIVE_METRIC", "")
    try:
        from kubeflow_tpu.tuning.study import (
            append_trial_history,
            report_trial_metrics,
        )

        if client is None:
            from kubeflow_tpu.k8s.client import HttpKubeClient

            # one client for the trial's lifetime, not one per step
            client = getattr(report_tuning_metrics, "_client", None)
            if client is None:
                client = HttpKubeClient()
                report_tuning_metrics._client = client
        if objective and objective in metrics:
            append_trial_history(client, ns, trial, step,
                                 float(metrics[objective]))
        if final:
            report_trial_metrics(client, ns, trial, {
                k: float(v) for k, v in metrics.items()
                if hasattr(v, "__float__")})
    except Exception:  # noqa: BLE001
        logging.exception("trial metrics report failed (continuing)")
