"""ViT image-classification training job.

Transformer-native vision workload beside the ResNet baseline (the
reference's vision examples are all tf_cnn_benchmarks CNNs,
``/root/reference/tf-controller-examples/tf-cnn/``):
``python -m kubeflow_tpu.examples.vit --steps 100``. Synthetic data;
same launcher/env contract as every other workload.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp

from kubeflow_tpu.examples.common import launcher_init, log_metrics
from kubeflow_tpu.models import ViT, ViTConfig
from kubeflow_tpu.train import (
    TrainState,
    create_sharded_state,
    make_image_train_step,
    make_optimizer,
)
from kubeflow_tpu.utils.profiler import StepProfiler


def main(argv=None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--per-device-batch", type=int, default=64)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--patch-size", type=int, default=16)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--d-model", type=int, default=768)
    p.add_argument("--n-layers", type=int, default=12)
    p.add_argument("--n-heads", type=int, default=12)
    p.add_argument("--d-ff", type=int, default=3072)
    p.add_argument("--tp", type=int, default=None)
    p.add_argument("--log-every", type=int, default=10)
    args = p.parse_args(argv)

    penv, mesh = launcher_init(tp=args.tp)
    batch = args.per_device_batch * jax.device_count()
    model = ViT(ViTConfig(
        image_size=args.image_size, patch_size=args.patch_size,
        num_classes=args.num_classes, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads, d_ff=args.d_ff))
    tx = make_optimizer(3e-4, warmup_steps=10, decay_steps=args.steps + 10)

    images = jax.random.normal(
        jax.random.key(0), (batch, args.image_size, args.image_size, 3),
        jnp.bfloat16)
    labels = jnp.zeros((batch,), jnp.int32)

    def init_fn(rng):
        params = model.init(rng, images[:2])["params"]
        return TrainState.create(
            apply_fn=lambda v, x, train=True: model.apply(v, x),
            params=params, tx=tx)

    state, _ = create_sharded_state(init_fn, jax.random.key(0), mesh)
    step_fn = make_image_train_step(mesh)

    metrics = None
    state, metrics = step_fn(state, images, labels)
    float(metrics["loss"])  # force compile + first step before timing

    prof = StepProfiler.from_env()
    t0 = time.perf_counter()
    for step in range(1, args.steps + 1):
        prof.step(step)
        state, metrics = step_fn(state, images, labels)
        if step % args.log_every == 0 or step == args.steps:
            float(metrics["loss"])
            elapsed = time.perf_counter() - t0
            ips = step * batch / elapsed
            log_metrics(step, loss=metrics["loss"], images_per_sec=ips,
                        images_per_sec_per_chip=ips / jax.device_count())
    float(metrics["loss"])
    prof.close()
    dt = time.perf_counter() - t0
    ips = args.steps * batch / dt
    log_metrics(args.steps, final=True, images_per_sec=ips,
                images_per_sec_per_chip=ips / jax.device_count())
    return ips


if __name__ == "__main__":
    main()
