"""BERT masked-LM pretraining job — the DDP-BERT baseline workload.

BASELINE.md config 3 (DDP BERT-base step time + scaling) as SPMD pjit:
``python -m kubeflow_tpu.examples.bert --steps 100``. Synthetic token
streams with 15% masking; checkpoint/resume like the LM flagship.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp

from kubeflow_tpu.examples.common import checkpoint_dir, launcher_init, log_metrics
from kubeflow_tpu.parallel.mesh import data_parallel_size
from kubeflow_tpu.models.bert import Bert, BertConfig, mask_tokens
from kubeflow_tpu.train import (
    TrainState,
    create_sharded_state,
    make_mlm_train_step,
    make_optimizer,
)
from kubeflow_tpu.train.checkpoint import CheckpointManager
from kubeflow_tpu.utils.profiler import StepProfiler


def main(argv=None) -> float:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--per-device-batch", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--vocab-size", type=int, default=30522)
    p.add_argument("--d-model", type=int, default=768)
    p.add_argument("--n-layers", type=int, default=12)
    p.add_argument("--n-heads", type=int, default=12)
    p.add_argument("--d-ff", type=int, default=3072)
    p.add_argument("--tp", type=int, default=None)
    p.add_argument("--learning-rate", type=float, default=1e-4)
    p.add_argument("--checkpoint-every", type=int, default=50)
    p.add_argument("--log-every", type=int, default=10)
    args = p.parse_args(argv)

    penv, mesh = launcher_init(tp=args.tp)
    config = BertConfig(
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=args.n_heads,
        d_ff=args.d_ff,
        max_seq_len=args.seq_len,
    )
    model = Bert(config)
    batch = args.per_device_batch * data_parallel_size(mesh)
    tx = make_optimizer(args.learning_rate, warmup_steps=20,
                        decay_steps=args.steps + 1)
    sample = jnp.zeros((batch, args.seq_len), jnp.int32)

    def init_fn(rng):
        params = model.init(rng, sample)["params"]
        return TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    state, _ = create_sharded_state(init_fn, jax.random.key(0), mesh)

    ckpt = None
    start_step = 0
    if checkpoint_dir():
        ckpt = CheckpointManager(checkpoint_dir())
        state, start_step = ckpt.restore_or_init(state)
    if start_step >= args.steps:
        log_metrics(start_step, done=True)
        if ckpt:
            ckpt.close()
        return 0.0

    step_fn = make_mlm_train_step(mesh)
    data_rng = jax.random.key(99)
    tokens_per_step = batch * args.seq_len
    last_loss = float("nan")
    t_window = time.perf_counter()
    prof = StepProfiler.from_env()
    for step in range(start_step, args.steps):
        prof.step(step)
        data_rng, tok_rng, mask_rng = jax.random.split(data_rng, 3)
        labels = jax.random.randint(
            tok_rng, (batch, args.seq_len), 0, args.vocab_size, jnp.int32)
        tokens, weights = mask_tokens(mask_rng, labels)
        state, metrics = step_fn(state, tokens, labels, weights)
        if (step + 1) % args.log_every == 0 or step + 1 == args.steps:
            last_loss = float(metrics["loss"])
            dt = time.perf_counter() - t_window
            steps_done = (step + 1 - start_step) % args.log_every or \
                args.log_every
            log_metrics(
                step + 1,
                loss=round(last_loss, 4),
                tokens_per_sec=round(tokens_per_step * steps_done / dt, 1),
                step_time_ms=round(dt / steps_done * 1e3, 2),
            )
            t_window = time.perf_counter()
        if ckpt and (step + 1) % args.checkpoint_every == 0:
            ckpt.save(state, step + 1)
    if ckpt:
        ckpt.save(state, args.steps)
        ckpt.close()
    prof.close()
    log_metrics(args.steps, loss=round(last_loss, 4), done=True)
    return last_loss


if __name__ == "__main__":
    main()
