"""kubeflow_tpu — a TPU-native ML platform.

A ground-up rebuild of the capabilities of the Kubeflow monorepo
(reference: rbrishabh/kubeflow) designed TPU-first:

- compute path: JAX/XLA, pjit/shard_map over ``jax.sharding.Mesh``, Pallas
  kernels for hot ops; SPMD replaces the reference's PS/NCCL/MPI wiring.
- control plane: a single slice-aware ``TpuJob`` operator replaces the
  TFJob/PyTorchJob/MPIJob operator family; gang placement onto TPU pod
  slices (``google.com/tpu``) replaces GPU node pools.
- platform: typed deployment config + manifest engine + ``ctl`` CLI replace
  kfctl/ksonnet/kustomize; a JAX serving component replaces TF-Serving.

See SURVEY.md at the repo root for the full capability map.
"""

__version__ = "0.1.0"
