"""Per-deployment worker process for the deploy service.

The reference's v2 bootstrap server never runs a deployment in its own
process: each deploy spawns a dedicated kfctl StatefulSet
(``/root/reference/bootstrap/cmd/bootstrap/app/router.go:235,370``) so
one wedged or crashing deploy cannot take the service — or the other
deployments — down with it. This module is that isolation boundary,
TPU-framework style: the deploy server (``bootstrap/server.py``,
``isolation="process"``) spawns

    python -m kubeflow_tpu.bootstrap.worker <app_root> <name> <flow>

with the request body as JSON on stdin; the worker runs exactly the
same flow implementation the in-process mode uses and reports phase
transitions through ``<app_root>/<name>/status.json`` (atomic
write-then-rename, the file the server's status route reads). A worker
that dies without reporting (segfault, OOM-kill) is detected by the
server's reaper thread and surfaced as Failed.
"""

from __future__ import annotations

import json
import os
import sys


def build_client():
    """The worker's cluster client, from the env the server passed:
    ``KFTPU_FAKE_STATE`` selects the file-backed fake cluster (tests,
    local dev — the same state file the server uses, so the worker's
    applies land in the same 'cluster'); otherwise the standard
    in-cluster/kubeconfig HTTP client."""
    state = os.environ.get("KFTPU_FAKE_STATE")
    if state:
        from kubeflow_tpu.k8s.fakefile import FileBackedFakeClient

        return FileBackedFakeClient(state)
    from kubeflow_tpu.k8s.client import HttpKubeClient

    return HttpKubeClient()


def main(argv=None) -> int:
    argv = list(sys.argv if argv is None else argv)
    if len(argv) != 4:
        print("usage: worker <app_root> <name> <deploy|delete|reapply>",
              file=sys.stderr)
        return 2
    app_root, name, flow = argv[1:4]
    body = {}
    if flow == "deploy":
        raw = sys.stdin.read().strip()
        body = json.loads(raw) if raw else {}

    from kubeflow_tpu.bootstrap.server import DeployServer

    # run_async=False + thread isolation: THIS process is the isolation
    # unit; the flow runs synchronously and exits
    srv = DeployServer(build_client(), app_root=app_root,
                       run_async=False, isolation="thread")
    # seed from the persisted status so the rolling log survives the
    # process boundary (thread mode keeps history; process mode must too)
    prior = srv.peek_status(name)
    if prior:
        with srv._state_lock:
            srv._status[name] = dict(prior)
    if flow == "deploy":
        srv._deploy_flow(name, body)
    elif flow == "delete":
        srv._delete_flow(name)
    elif flow == "reapply":
        srv._reapply_flow(name)
    else:
        print(f"unknown flow {flow!r}", file=sys.stderr)
        return 2
    phase = srv.peek_status(name).get("phase")
    return 0 if phase == "Succeeded" else 1


if __name__ == "__main__":
    sys.exit(main())
