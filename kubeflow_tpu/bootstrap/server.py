"""Deploy REST service: init/generate/apply flows with per-deployment locks.

Route parity with ksServer (``/root/reference/bootstrap/cmd/bootstrap/
app/ksServer.go:900-906``):

- ``POST /kfctl/e2eDeploy``  {"name", "preset", "platform", "namespace",
  "components": {...param overrides}} — full init→generate→apply in a
  background thread (the reference's flow takes minutes; clients poll)
- ``GET  /kfctl/status/<name>`` — deployment phase + log tail
- ``POST /kfctl/apps/apply``  {"name"} — re-apply an existing deployment
- ``DELETE /kfctl/deployments/<name>`` — tear down
- ``GET  /metrics`` handled by the shared metrics server

Per-deployment mutexes mirror ``GetProjectLock`` (ksServer.go:358-368):
concurrent requests for one deployment serialize; different deployments
run in parallel.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import traceback
from typing import Any, Dict, Optional, Tuple

from kubeflow_tpu.config import DeploymentConfig, preset
from kubeflow_tpu.config.deployment import ComponentSpec
from kubeflow_tpu.k8s.apply import apply_all, delete_all
from kubeflow_tpu.k8s.client import KubeClient
from kubeflow_tpu.manifests import render_all
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.jsonhttp import serve_json

log = logging.getLogger(__name__)

_deploys = DEFAULT_REGISTRY.counter(
    "kftpu_bootstrap_deploys_total", "e2eDeploy requests accepted")

PHASE_PENDING = "Pending"
PHASE_RUNNING = "Running"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"


class DeployServer:
    """Holds deployment state; serves the kfctl REST surface."""

    def __init__(self, client: KubeClient, *, app_root: str = "/tmp/kftpu",
                 run_async: bool = True) -> None:
        self.client = client
        self.app_root = app_root
        self.run_async = run_async
        self._state_lock = threading.Lock()
        self._locks: Dict[str, threading.Lock] = {}
        self._status: Dict[str, Dict[str, Any]] = {}

    # -- locks (GetProjectLock parity) -------------------------------------

    def _lock_for(self, name: str) -> threading.Lock:
        with self._state_lock:
            return self._locks.setdefault(name, threading.Lock())

    def _set(self, name: str, phase: str, message: str = "") -> None:
        with self._state_lock:
            entry = self._status.setdefault(name, {"log": []})
            entry["phase"] = phase
            if message:
                entry["log"] = (entry.get("log", []) + [message])[-50:]
            entry["updatedAt"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())

    # -- flows -------------------------------------------------------------

    def _deploy_flow(self, name: str, body: Dict[str, Any]) -> None:
        with self._lock_for(name):
            try:
                self._set(name, PHASE_RUNNING, "building config")
                config = preset(body.get("preset", "standard"), name)
                config.namespace = body.get("namespace", config.namespace)
                if body.get("platform"):
                    config.platform = body["platform"]
                for comp, params in (body.get("components") or {}).items():
                    spec = config.component(comp)
                    if spec is None:
                        config.components.append(
                            ComponentSpec(comp, params=dict(params)))
                    else:
                        spec.params.update(params)
                config.validate()
                app_dir = os.path.join(self.app_root, name)
                os.makedirs(app_dir, exist_ok=True)
                config.save(os.path.join(app_dir, "app.yaml"))

                self._set(name, PHASE_RUNNING, "rendering manifests")
                objs = render_all(config)
                self._set(name, PHASE_RUNNING,
                          f"applying {len(objs)} objects")
                apply_all(self.client, objs)
                self._set(name, PHASE_SUCCEEDED,
                          f"applied {len(objs)} objects")
            except Exception as e:  # noqa: BLE001 — reported via status
                log.error("deploy %s failed:\n%s", name,
                          traceback.format_exc())
                self._set(name, PHASE_FAILED, f"{type(e).__name__}: {e}")

    def _delete_flow(self, name: str) -> None:
        with self._lock_for(name):
            try:
                app_dir = os.path.join(self.app_root, name, "app.yaml")
                if not os.path.exists(app_dir):
                    self._set(name, PHASE_FAILED, "unknown deployment")
                    return
                config = DeploymentConfig.load(app_dir)
                objs = render_all(config)
                delete_all(self.client, objs)
                self._set(name, PHASE_SUCCEEDED, "deleted")
            except Exception as e:  # noqa: BLE001
                self._set(name, PHASE_FAILED, f"{type(e).__name__}: {e}")

    def _run(self, target, *args) -> None:
        if self.run_async:
            threading.Thread(target=target, args=args, daemon=True).start()
        else:
            target(*args)

    # -- routes ------------------------------------------------------------

    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str = "") -> Tuple[int, Any]:
        body = body or {}
        if method == "POST" and path == "/kfctl/e2eDeploy":
            name = body.get("name", "")
            if not name:
                return 400, {"error": "name is required"}
            # atomic check-and-set: a second POST racing the Pending window
            # must not queue a duplicate flow
            with self._state_lock:
                current = self._status.get(name, {}).get("phase")
                if current in (PHASE_PENDING, PHASE_RUNNING):
                    return 409, {
                        "error": f"deployment {name!r} already in progress"}
                entry = self._status.setdefault(name, {"log": []})
                entry["phase"] = PHASE_PENDING
                entry["log"] = (entry.get("log", []) + ["accepted"])[-50:]
                entry["updatedAt"] = time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            _deploys.inc()
            self._run(self._deploy_flow, name, body)
            return 200, {"name": name, "phase": PHASE_PENDING}
        if method == "POST" and path == "/kfctl/apps/apply":
            name = body.get("name", "")
            if not name:
                return 400, {"error": "name is required"}
            app_yaml = os.path.join(self.app_root, name, "app.yaml")
            if not os.path.exists(app_yaml):
                return 404, {"error": f"deployment {name!r} not found"}
            self._set(name, PHASE_PENDING, "re-apply accepted")
            self._run(self._reapply_flow, name)
            return 200, {"name": name, "phase": PHASE_PENDING}
        if method == "GET" and path.startswith("/kfctl/status/"):
            name = path.rsplit("/", 1)[1]
            with self._state_lock:
                status = self._status.get(name)
            if status is None:
                return 404, {"error": f"deployment {name!r} not found"}
            return 200, {"name": name, **status}
        if method == "DELETE" and path.startswith("/kfctl/deployments/"):
            name = path.rsplit("/", 1)[1]
            if not os.path.exists(os.path.join(self.app_root, name,
                                               "app.yaml")):
                return 404, {"error": f"deployment {name!r} not found"}
            self._set(name, PHASE_PENDING, "delete accepted")
            self._run(self._delete_flow, name)
            return 200, {"name": name, "phase": PHASE_PENDING}
        if method == "GET" and path == "/healthz":
            return 200, {"ok": True}
        return 404, {"error": f"no route {method} {path}"}

    def _reapply_flow(self, name: str) -> None:
        with self._lock_for(name):
            try:
                config = DeploymentConfig.load(
                    os.path.join(self.app_root, name, "app.yaml"))
                objs = render_all(config)
                apply_all(self.client, objs)
                self._set(name, PHASE_SUCCEEDED,
                          f"re-applied {len(objs)} objects")
            except Exception as e:  # noqa: BLE001
                self._set(name, PHASE_FAILED, f"{type(e).__name__}: {e}")


def main() -> None:
    from kubeflow_tpu.k8s.client import HttpKubeClient
    from kubeflow_tpu.utils import serve_metrics

    logging.basicConfig(level=logging.INFO)
    serve_metrics(int(os.environ.get("KFTPU_MONITORING_PORT", "8091")))
    from kubeflow_tpu.auth.gatekeeper import authenticator_from_env

    server = DeployServer(
        HttpKubeClient(),
        app_root=os.environ.get("KFTPU_APP_ROOT", "/tmp/kftpu"))
    serve_json(server.handle,
               int(os.environ.get("KFTPU_BOOTSTRAP_PORT", "8086")),
               authenticator=authenticator_from_env(),
               static_dir=os.path.join(os.path.dirname(__file__), "static"))


if __name__ == "__main__":
    main()
