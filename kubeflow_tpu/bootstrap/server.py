"""Deploy REST service: init/generate/apply flows with per-deployment locks.

Route parity with ksServer (``/root/reference/bootstrap/cmd/bootstrap/
app/ksServer.go:900-906``):

- ``POST /kfctl/e2eDeploy``  {"name", "preset", "platform", "namespace",
  "components": {...param overrides}} — full init→generate→apply in a
  background thread (the reference's flow takes minutes; clients poll)
- ``GET  /kfctl/status/<name>`` — deployment phase + log tail
- ``POST /kfctl/apps/apply``  {"name"} — re-apply an existing deployment
- ``DELETE /kfctl/deployments/<name>`` — tear down
- ``GET  /metrics`` handled by the shared metrics server

Per-deployment mutexes mirror ``GetProjectLock`` (ksServer.go:358-368):
concurrent requests for one deployment serialize; different deployments
run in parallel.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import traceback
from typing import Any, Dict, Optional, Tuple

from kubeflow_tpu.config import DeploymentConfig, preset
from kubeflow_tpu.config.deployment import ComponentSpec
from kubeflow_tpu.k8s.apply import apply_all, delete_all
from kubeflow_tpu.k8s.client import KubeClient
from kubeflow_tpu.manifests import render_all
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.jsonhttp import serve_json

log = logging.getLogger(__name__)

_deploys = DEFAULT_REGISTRY.counter(
    "kftpu_bootstrap_deploys_total", "e2eDeploy requests accepted")

PHASE_PENDING = "Pending"
PHASE_RUNNING = "Running"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"


class _SpawnPending:
    """Placeholder in ``_procs`` while the worker process is spawned
    outside the state lock: answers ``poll()`` as alive so the accept
    gate and concurrent spawners treat the slot as taken while
    ``Popen`` runs unlocked."""

    def poll(self) -> None:
        return None


class DeployServer:
    """Holds deployment state; serves the kfctl REST surface.

    ``isolation`` selects where deployment flows execute:

    - ``"thread"`` — in-process background threads (the default; fine
      for trusted single-tenant use).
    - ``"process"`` — one OS process per flow
      (``bootstrap/worker.py``): a wedged or crashing deploy cannot
      take the service or other deployments down. This is the
      reference's per-deploy kfctl StatefulSet isolation
      (``bootstrap/cmd/bootstrap/app/router.go:235,370``) with an OS
      process as the unit instead of a pod. ``KFTPU_DEPLOY_ISOLATION``
      sets the default for the container entrypoint.

    Status is exchanged through ``<app_root>/<name>/status.json``
    (atomic rename) in both modes, so the status route reads one source
    of truth regardless of which process ran the flow.
    """

    def __init__(self, client: KubeClient, *, app_root: str = "/tmp/kftpu",
                 run_async: bool = True,
                 isolation: str = "thread") -> None:
        if isolation not in ("thread", "process"):
            raise ValueError(f"isolation must be 'thread' or 'process', "
                             f"got {isolation!r}")
        self.client = client
        self.app_root = app_root
        self.run_async = run_async
        self.isolation = isolation
        self._state_lock = threading.Lock()
        self._locks: Dict[str, threading.Lock] = {}
        self._status: Dict[str, Dict[str, Any]] = {}
        self._procs: Dict[str, Any] = {}  # live per-deploy workers

    # -- locks (GetProjectLock parity) -------------------------------------

    def _lock_for(self, name: str) -> threading.Lock:
        with self._state_lock:
            return self._locks.setdefault(name, threading.Lock())

    def _status_path(self, name: str) -> str:
        return os.path.join(self.app_root, name, "status.json")

    def _set(self, name: str, phase: str, message: str = "") -> None:
        with self._state_lock:
            entry = self._status.setdefault(name, {"log": []})
            entry["phase"] = phase
            if message:
                entry["log"] = (entry.get("log", []) + [message])[-50:]
            entry["updatedAt"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())
            snapshot = dict(entry)
        self._persist_status(name, snapshot)

    def _persist_status(self, name: str, snapshot: Dict[str, Any]) -> None:
        """The cross-process status channel (worker ↔ server): atomic
        write-then-rename so a reader never sees a torn file."""
        from kubeflow_tpu.workflows.archive import _atomic_write

        try:
            _atomic_write(self._status_path(name),
                          json.dumps(snapshot).encode())
        except OSError:
            log.warning("could not persist status for %s", name,
                        exc_info=True)

    def _accept(self, name: str, message: str) -> bool:
        """Atomic check-and-accept: refuse (False) when the deployment
        is in progress (phase, or a live worker process); otherwise mark
        it Pending with ``message`` and persist. A refused request must
        never mutate the deployment's status — a 409 that clobbered a
        worker's final state could wedge the phase at Pending."""
        with self._state_lock:
            if self._status.get(name, {}).get("phase") in (
                    PHASE_PENDING, PHASE_RUNNING):
                return False
            proc = self._procs.get(name)
            if proc is not None and proc.poll() is None:
                return False
            entry = self._status.setdefault(name, {"log": []})
            entry["phase"] = PHASE_PENDING
            entry["log"] = (entry.get("log", []) + [message])[-50:]
            entry["updatedAt"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())
            snapshot = dict(entry)
        self._persist_status(name, snapshot)
        return True

    def peek_status(self, name: str) -> Dict[str, Any]:
        """The deployment's status: file and memory merged by
        freshness. A worker process may have progressed the FILE past
        this process's memory; a failed persist (disk full) may have
        left MEMORY ahead of the file. ``updatedAt`` is ISO-8601 UTC,
        so string comparison orders correctly."""
        file_status: Dict[str, Any] = {}
        try:
            with open(self._status_path(name)) as f:
                file_status = json.load(f)
        except (OSError, ValueError):
            pass
        with self._state_lock:
            mem_status = dict(self._status.get(name) or {})
        if not file_status:
            return mem_status
        if not mem_status:
            return file_status
        return (file_status
                if file_status.get("updatedAt", "")
                >= mem_status.get("updatedAt", "") else mem_status)

    # -- flows -------------------------------------------------------------

    def _deploy_flow(self, name: str, body: Dict[str, Any]) -> None:
        with self._lock_for(name):
            try:
                self._set(name, PHASE_RUNNING, "building config")
                config = preset(body.get("preset", "standard"), name)
                config.namespace = body.get("namespace", config.namespace)
                if body.get("platform"):
                    config.platform = body["platform"]
                for comp, params in (body.get("components") or {}).items():
                    spec = config.component(comp)
                    if spec is None:
                        config.components.append(
                            ComponentSpec(comp, params=dict(params)))
                    else:
                        spec.params.update(params)
                config.validate()
                app_dir = os.path.join(self.app_root, name)
                os.makedirs(app_dir, exist_ok=True)
                config.save(os.path.join(app_dir, "app.yaml"))

                self._set(name, PHASE_RUNNING, "rendering manifests")
                objs = render_all(config)
                self._set(name, PHASE_RUNNING,
                          f"applying {len(objs)} objects")
                apply_all(self.client, objs)
                self._set(name, PHASE_SUCCEEDED,
                          f"applied {len(objs)} objects")
            except Exception as e:  # noqa: BLE001 — reported via status
                log.error("deploy %s failed:\n%s", name,
                          traceback.format_exc())
                self._set(name, PHASE_FAILED, f"{type(e).__name__}: {e}")

    def _delete_flow(self, name: str) -> None:
        with self._lock_for(name):
            try:
                app_dir = os.path.join(self.app_root, name, "app.yaml")
                if not os.path.exists(app_dir):
                    self._set(name, PHASE_FAILED, "unknown deployment")
                    return
                config = DeploymentConfig.load(app_dir)
                objs = render_all(config)
                delete_all(self.client, objs)
                self._set(name, PHASE_SUCCEEDED, "deleted")
            except Exception as e:  # noqa: BLE001
                self._set(name, PHASE_FAILED, f"{type(e).__name__}: {e}")

    def _run(self, target, *args) -> None:
        if self.run_async:
            threading.Thread(target=target, args=args, daemon=True).start()
        else:
            target(*args)

    # -- process isolation (per-deploy worker, router.go:235 parity) -------

    def _spawn_worker(self, name: str, flow: str,
                      body: Optional[Dict[str, Any]] = None) -> bool:
        """Run ``flow`` for ``name`` in its own OS process
        (``bootstrap/worker.py``). Returns False when a live worker for
        this deployment already exists (the caller 409s)."""
        import subprocess
        import sys

        # the worker's stderr lands here — when it dies without
        # reporting, this file is the diagnosis (DEVNULL would make the
        # exact failures the isolation exists for undiagnosable)
        wlog_path = os.path.join(self.app_root, name, "worker.log")
        # reserve the slot under the lock, spawn OUTSIDE it (TPU011:
        # fork/exec latency must not stall every status reader), then
        # re-lock to publish the real process
        with self._state_lock:
            prior = self._procs.get(name)
            if prior is not None and prior.poll() is None:
                log.warning("worker for %s still alive; not spawning "
                            "(raced past the accept gate?)", name)
                return False
            pending = _SpawnPending()
            self._procs[name] = pending
            # the fake-cluster state file (tests/local): the worker must
            # apply into the SAME cluster the server reads
            state_path = getattr(self.client, "path", None)
        wlog = None
        try:
            env = dict(os.environ)
            if state_path:
                env["KFTPU_FAKE_STATE"] = str(state_path)
            os.makedirs(os.path.dirname(wlog_path), exist_ok=True)
            wlog = open(wlog_path, "w")
            proc = subprocess.Popen(
                [sys.executable, "-m", "kubeflow_tpu.bootstrap.worker",
                 self.app_root, name, flow],
                stdin=subprocess.PIPE, stdout=subprocess.DEVNULL,
                stderr=wlog, env=env, text=True)
        except BaseException:
            # ANY failure on the unlocked stretch (unwritable app_root,
            # full disk, fork failure) must release the reservation, or
            # the always-alive placeholder wedges the slot forever
            with self._state_lock:
                if self._procs.get(name) is pending:
                    del self._procs[name]
            if wlog is not None:
                wlog.close()
            raise
        wlog.close()  # the child holds its own descriptor
        with self._state_lock:
            self._procs[name] = proc
        try:
            proc.stdin.write(json.dumps(body or {}))
            proc.stdin.close()
        except OSError:
            pass  # worker died instantly; the reaper reports it
        t = threading.Thread(target=self._reap, args=(name, proc),
                             daemon=True)
        t.start()
        if not self.run_async:
            t.join()
        return True

    def _reap(self, name: str, proc) -> None:
        """Surface workers that die WITHOUT reporting (segfault,
        OOM-kill) as Failed, and sync the worker's final status back
        into server memory — the e2eDeploy duplicate guard reads
        memory, so a finished process-mode deploy must not read as
        in-progress forever."""
        rc = proc.wait()
        status = self.peek_status(name)
        # adopt the worker's file status (its log lines included) as
        # this process's view before any further transition
        with self._state_lock:
            if status:
                self._status[name] = dict(status)
        if rc != 0 and status.get("phase") in (PHASE_PENDING,
                                               PHASE_RUNNING):
            # surface the worker's last stderr lines — the crash the
            # isolation exists for must be diagnosable from the status
            tail = ""
            try:
                with open(os.path.join(self.app_root, name,
                                       "worker.log")) as f:
                    tail = f.read()[-300:].strip()
            except OSError:
                pass
            log.error("deploy worker for %s exited rc=%d; stderr tail: "
                      "%s", name, rc, tail or "<empty>")
            self._set(name, PHASE_FAILED,
                      f"deploy worker exited with code {rc} without "
                      "reporting" + (f": {tail}" if tail
                                     else " — see server logs"))

    def _dispatch(self, name: str, flow: str,
                  body: Optional[Dict[str, Any]] = None) -> bool:
        """Route a flow to the configured isolation unit. Returns False
        on a 409-worthy conflict (live worker for this name)."""
        if self.isolation == "process":
            return self._spawn_worker(name, flow, body)
        target = {"deploy": self._deploy_flow,
                  "delete": self._delete_flow,
                  "reapply": self._reapply_flow}[flow]
        self._run(target, *((name, body) if flow == "deploy"
                            else (name,)))
        return True

    def _start(self, name: str, flow: str,
               body: Optional[Dict[str, Any]] = None
               ) -> Optional[Tuple[int, Any]]:
        """Dispatch an ACCEPTED flow; on any startup failure roll the
        Pending phase to Failed (a Pending that nothing will ever
        advance would 409 the name forever) and return the error
        response. None = started."""
        try:
            ok = self._dispatch(name, flow, body)
        except Exception as e:  # noqa: BLE001 — fork/IO failures
            log.exception("failed to start %s flow for %s", flow, name)
            self._set(name, PHASE_FAILED,
                      f"failed to start {flow}: {type(e).__name__}: {e}")
            return 500, {"error": f"failed to start {flow}: {e}"}
        if not ok:
            self._set(name, PHASE_FAILED,
                      f"failed to start {flow}: worker conflict")
            return 503, {"error": "worker conflict at spawn; retry"}
        return None

    # -- routes ------------------------------------------------------------

    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str = "") -> Tuple[int, Any]:
        body = body or {}
        if method == "POST" and path == "/kfctl/e2eDeploy":
            name = body.get("name", "")
            if not name:
                return 400, {"error": "name is required"}
            # atomic check-and-accept: a second POST racing the Pending
            # window must not queue a duplicate flow, and a refused one
            # must not touch the status. Memory is authoritative for
            # in-progress-ness (the reaper syncs worker completions
            # back); the accepted state is persisted so the status file
            # — the route's source of truth — can't serve a stale run.
            if not self._accept(name, "accepted"):
                return 409, {
                    "error": f"deployment {name!r} already in progress"}
            _deploys.inc()
            err = self._start(name, "deploy", body)
            if err:
                return err
            return 200, {"name": name, "phase": PHASE_PENDING}
        if method == "POST" and path == "/kfctl/apps/apply":
            name = body.get("name", "")
            if not name:
                return 400, {"error": "name is required"}
            app_yaml = os.path.join(self.app_root, name, "app.yaml")
            if not os.path.exists(app_yaml):
                return 404, {"error": f"deployment {name!r} not found"}
            if not self._accept(name, "re-apply accepted"):
                return 409, {
                    "error": f"deployment {name!r} already in progress"}
            err = self._start(name, "reapply")
            if err:
                return err
            return 200, {"name": name, "phase": PHASE_PENDING}
        if method == "GET" and path.startswith("/kfctl/status/"):
            name = path.rsplit("/", 1)[1]
            status = self.peek_status(name)
            if not status:
                return 404, {"error": f"deployment {name!r} not found"}
            return 200, {"name": name, **status}
        if method == "DELETE" and path.startswith("/kfctl/deployments/"):
            name = path.rsplit("/", 1)[1]
            if not os.path.exists(os.path.join(self.app_root, name,
                                               "app.yaml")):
                return 404, {"error": f"deployment {name!r} not found"}
            if not self._accept(name, "delete accepted"):
                return 409, {
                    "error": f"deployment {name!r} already in progress"}
            err = self._start(name, "delete")
            if err:
                return err
            return 200, {"name": name, "phase": PHASE_PENDING}
        if method == "GET" and path == "/healthz":
            return 200, {"ok": True}
        return 404, {"error": f"no route {method} {path}"}

    def _reapply_flow(self, name: str) -> None:
        with self._lock_for(name):
            try:
                config = DeploymentConfig.load(
                    os.path.join(self.app_root, name, "app.yaml"))
                objs = render_all(config)
                apply_all(self.client, objs)
                self._set(name, PHASE_SUCCEEDED,
                          f"re-applied {len(objs)} objects")
            except Exception as e:  # noqa: BLE001
                self._set(name, PHASE_FAILED, f"{type(e).__name__}: {e}")


def main() -> None:
    from kubeflow_tpu.k8s.client import HttpKubeClient
    from kubeflow_tpu.utils import serve_metrics

    logging.basicConfig(level=logging.INFO)
    serve_metrics(int(os.environ.get("KFTPU_MONITORING_PORT", "8091")))
    from kubeflow_tpu.auth.gatekeeper import authenticator_from_env

    server = DeployServer(
        HttpKubeClient(),
        app_root=os.environ.get("KFTPU_APP_ROOT", "/tmp/kftpu"),
        isolation=os.environ.get("KFTPU_DEPLOY_ISOLATION", "thread"))
    serve_json(server.handle,
               int(os.environ.get("KFTPU_BOOTSTRAP_PORT", "8086")),
               authenticator=authenticator_from_env(),
               static_dir=os.path.join(os.path.dirname(__file__), "static"))


if __name__ == "__main__":
    main()
