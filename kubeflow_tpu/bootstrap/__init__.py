"""Bootstrap deploy server: run deployment flows server-side over REST.

Reference: ``/root/reference/bootstrap/cmd/bootstrap/app/ksServer.go`` —
the long-running service behind the click-to-deploy UI with per-project
locks (``GetProjectLock :358``), endpoints ``/kfctl/e2eDeploy``,
``/kfctl/apps/apply`` (``:900-906``), and a ``/metrics`` endpoint.
"""

from kubeflow_tpu.bootstrap.server import DeployServer  # noqa: F401
