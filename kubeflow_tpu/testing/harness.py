"""CI trigger config, the E2E DAG, and junit output.

Reference pieces: ``prow_config.yaml`` (path → workflow mapping with
``include_dirs``), the ``kfTests`` Argo DAG (``testing/workflows/
components/workflows.libsonnet:58-330``: build → deploy → parallel test
tasks → teardown), and junit XML artifacts via
``kubeflow.testing.test_helper``. The DAG here renders onto the native
Workflow engine so the same controller that runs kubebench runs CI.
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Sequence
import re
from xml.sax.saxutils import escape, quoteattr

from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.workflows.workflow import container_step, workflow


@dataclass
class CiConfig:
    """path-glob → workflow-name mapping (prow_config.yaml equivalent)."""

    # e.g. [{"name": "e2e-full", "include": ["kubeflow_tpu/**", "tests/**"]},
    #       {"name": "e2e-serving", "include": ["kubeflow_tpu/serving/**"]}]
    workflows: List[Dict[str, Any]] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "CiConfig":
        return cls(workflows=list(d.get("workflows", []) or []))


def triggered_workflows(config: CiConfig,
                        changed_files: Sequence[str]) -> List[str]:
    """Workflow names whose include globs match any changed file; a
    workflow with no include list always triggers (prow semantics)."""
    out = []
    for wf in config.workflows:
        globs = wf.get("include", []) or []
        if not globs or any(
            fnmatch.fnmatch(f, g) for f in changed_files for g in globs
        ):
            out.append(wf["name"])
    return out


def e2e_workflow(
    name: str,
    ns: str,
    *,
    image: str = "kubeflow-tpu/platform:v1alpha1",
    tests: Sequence[str] = ("tests/",),
    include_multiprocess: bool = True,
    processes: int = 4,
) -> o.Obj:
    """The kfTests DAG on the native engine: checkout/setup → deploy the
    platform to the in-cluster fake → parallel test tasks → teardown."""
    steps: List[Dict[str, Any]] = [
        container_step(
            "setup", image,
            command=["python", "-m", "kubeflow_tpu.cli", "init", "/app",
                     "--preset", "standard"],
        ),
        container_step(
            "deploy", image,
            command=["python", "-m", "kubeflow_tpu.cli", "apply", "/app",
                     "--provision"],
            dependencies=["setup"],
        ),
    ]
    test_names = []
    for i, target in enumerate(tests):
        # step names feed pod names: DNS-1123 only
        safe = re.sub(r"[^a-z0-9-]", "-",
                      target.strip("/").replace("/", "-").lower()).strip("-")
        tname = f"test-{i}-{safe}"
        test_names.append(tname)
        steps.append(container_step(
            tname, image,
            command=["python", "-m", "pytest", target, "-x", "-q"],
            dependencies=["deploy"],
            retries=1,  # the reference retries flaky E2E tasks too
        ))
    if include_multiprocess:
        test_names.append("test-collectives")
        steps.append(container_step(
            "test-collectives", image,
            command=["python", "-m",
                     "kubeflow_tpu.testing.run_collective_check",
                     "--processes", str(processes)],
            dependencies=["deploy"],
        ))
    steps.append(container_step(
        "teardown", image,
        command=["python", "-m", "kubeflow_tpu.cli", "delete", "/app",
                 "--provision"],
        # with no test steps, teardown must still wait for deploy or it
        # races the platform apply
        dependencies=test_names or ["deploy"],
    ))
    return workflow(name, ns, steps)


def junit_xml(suite: str, results: Sequence[Mapping[str, Any]]) -> str:
    """results: [{"name", "time_s", "failure": optional str}] → junit XML
    (the artifact shape testgrid consumes)."""
    failures = sum(1 for r in results if r.get("failure"))
    lines = [
        '<?xml version="1.0" encoding="UTF-8"?>',
        f'<testsuite name={quoteattr(suite)} tests="{len(results)}" '
        f'failures="{failures}">',
    ]
    for r in results:
        t = float(r.get("time_s", 0.0))
        lines.append(f'  <testcase name={quoteattr(r["name"])} '
                     f'time="{t:.3f}"'
                     + ("/>" if not r.get("failure") else ">"))
        if r.get("failure"):
            lines.append(f'    <failure>{escape(str(r["failure"]))}'
                         "</failure>")
            lines.append("  </testcase>")
    lines.append("</testsuite>")
    return "\n".join(lines) + "\n"
