"""CLI driver for the multi-process collective check.

``python -m kubeflow_tpu.testing.run_collective_check --processes 4``
spawns the coordinated subprocesses and exits non-zero if any rank fails
— the command the E2E DAG's ``test-collectives`` step runs.
"""

from __future__ import annotations

import argparse
import json
import sys

from kubeflow_tpu.testing.multiprocess import run_multiprocess


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--processes", type=int, default=4)
    p.add_argument("--timeout", type=float, default=180.0)
    args = p.parse_args(argv)
    results = run_multiprocess(
        ["-m", "kubeflow_tpu.testing.collective_check"],
        args.processes, timeout_s=args.timeout)
    ok = all(r.returncode == 0 for r in results)
    for r in results:
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        print(f"rank {r.process_id}: rc={r.returncode} {line}")
        if r.returncode != 0 and r.stderr:
            print(r.stderr[-500:], file=sys.stderr)
    print(json.dumps({"processes": args.processes, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
