"""Test/CI harness: multi-process collective tier + E2E trigger config.

Reference: the Argo-on-Prow system — ``prow_config.yaml`` maps changed
paths to E2E workflow components (``/root/reference/prow_config.yaml:
1-140``), ``testing/workflows/components/workflows.libsonnet:58-330``
builds the DAG, and ``kubeflow.testing.test_helper`` emits junit XML.
This package adds the tier the reference lacks (SURVEY.md §4): a
multi-process CPU ``jax.distributed`` simulation that exercises the
operator's exact env contract without a cluster.
"""

from kubeflow_tpu.testing.multiprocess import (  # noqa: F401
    ProcResult,
    run_multiprocess,
)
from kubeflow_tpu.testing.harness import (  # noqa: F401
    CiConfig,
    e2e_workflow,
    junit_xml,
    triggered_workflows,
)
