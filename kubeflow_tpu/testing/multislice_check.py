"""Multi-process multislice workload: the DCN mesh under REAL
``jax.distributed``.

Each process is one slice's host (the TpuJob operator's ``slices: N``
deployment: per-pod ``MEGASCALE_SLICE_ID`` + the coordinator env
contract, ``kubeflow_tpu/operators/tpujob.py``). The single-process
``dryrun_multislice`` (``__graft_entry__.py``) proves the mesh math;
this proves the *cross-process* half the operator actually ships:
coordinator bootstrap, slice-major global device order
(``kubeflow_tpu/parallel/mesh.py`` dcn axis contract), and a compiled
train step whose collectives span processes.

Prints one JSON line with the per-step losses; the harness asserts all
ranks agree and that the loss matches the single-process oracle.
"""

from __future__ import annotations

import json
import sys


def main() -> int:
    import jax

    # TPU-attached interpreters pin their platform via sitecustomize
    # before env is read; each rank must expose only its virtual CPUs
    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models import Transformer, TransformerConfig
    from kubeflow_tpu.parallel import distributed as dist
    from kubeflow_tpu.train import (
        TrainState,
        create_sharded_state,
        make_lm_train_step,
        make_optimizer,
    )

    penv = dist.from_env()
    dist.initialize()  # the operator's env contract

    n_procs = jax.process_count()
    devs = jax.devices()
    # the operator assigns ranks slice-major, so jax's process-major
    # global device order IS slice-major — multislice_mesh's contract
    mesh = dist.multislice_mesh(penv, tp=2)
    dcn, dp, pp, tp = mesh.devices.shape

    config = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq_len=32, dtype=jnp.float32, remat=False)
    model = Transformer(config)
    # identical on every rank: jit treats host-local numpy as replicated
    tokens = np.asarray(jax.random.randint(
        jax.random.key(7), (2 * dcn * dp, 16), 0, config.vocab_size))
    tx = make_optimizer(1e-3, warmup_steps=1, decay_steps=10)

    def init_fn(rng):
        params = model.init(rng, tokens)["params"]
        return TrainState.create(apply_fn=model.apply, params=params,
                                 tx=tx)

    state, _ = create_sharded_state(init_fn, jax.random.key(0), mesh)
    step = make_lm_train_step(mesh)
    losses = []
    for _ in range(2):
        state, metrics = step(state, tokens)
        # the loss is replicated; every process can read it
        losses.append(float(metrics["loss"]))
    ok = all(l == l for l in losses)  # NaN guard
    print(json.dumps({
        "process_id": penv.process_id,
        "slice_id": penv.slice_id,
        "processes": n_procs,
        "devices": len(devs),
        "mesh": {"dcn": dcn, "dp": dp, "pp": pp, "tp": tp},
        "losses": [round(l, 6) for l in losses],
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
