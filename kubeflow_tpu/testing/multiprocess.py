"""Multi-process ``jax.distributed`` simulation on CPU.

Spawns N subprocesses wired with the SAME env contract the TpuJob
operator injects into worker pods (:mod:`kubeflow_tpu.parallel.
distributed`: coordinator address, process count/id), so cross-process
collectives are exercised end-to-end on localhost — the test tier the
reference punts to real CI clusters (SURVEY.md §4). Process 0 hosts the
coordinator, exactly like worker-0 behind the headless Service.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from kubeflow_tpu.parallel import distributed as dist


@dataclass
class ProcResult:
    process_id: int
    returncode: int
    stdout: str
    stderr: str


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_multiprocess(
    workload: Sequence[str],
    num_processes: int,
    *,
    env: Optional[Dict[str, str]] = None,
    env_per_process: Optional[Sequence[Dict[str, str]]] = None,
    timeout_s: float = 180.0,
    job_name: str = "mp-test",
) -> List[ProcResult]:
    """Run ``workload`` (argv after the interpreter) in N coordinated
    processes; returns per-process results (caller asserts).
    ``env_per_process[i]`` adds rank-specific vars (e.g. the operator's
    per-slice ``MEGASCALE_SLICE_ID`` injection)."""
    if env_per_process is not None and len(env_per_process) != num_processes:
        raise ValueError(
            f"env_per_process has {len(env_per_process)} entries for "
            f"{num_processes} processes")
    port = _free_port()
    procs = []
    for pid in range(num_processes):
        penv = dict(os.environ)
        penv.update({
            # each process defaults to exactly one virtual CPU device so
            # the global device count equals the process count, like one
            # TPU host per pod; callers override XLA_FLAGS for fatter
            # hosts (e.g. 4 devices/process for the multislice tier)
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
        penv.update(env or {})
        if env_per_process is not None:
            penv.update(env_per_process[pid])
        penv.update({
            dist.ENV_COORDINATOR: f"127.0.0.1:{port}",
            dist.ENV_NUM_PROCESSES: str(num_processes),
            dist.ENV_PROCESS_ID: str(pid),
            dist.ENV_JOB_NAME: job_name,
        })
        procs.append(subprocess.Popen(
            [sys.executable, *workload],
            env=penv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    out: List[ProcResult] = []
    for pid, proc in enumerate(procs):
        try:
            stdout, stderr = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            stdout, stderr = proc.communicate()
            out.append(ProcResult(pid, -9, stdout, stderr))
            continue
        out.append(ProcResult(pid, proc.returncode, stdout, stderr))
    return out
