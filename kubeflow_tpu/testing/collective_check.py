"""Workload for the multi-process tier: initialize from the operator env
contract, run a psum over all processes, verify, print one JSON line.

This is the ``simple_tfjob_tests`` analogue (the smoke workload the
reference's E2E DAG runs, ``testing/workflows/components/workflows.
libsonnet:187-330``) for the SPMD path: success means the coordinator
bootstrap (hard part (c)) and cross-process collectives both work.
"""

from __future__ import annotations

import json
import sys


def main() -> int:
    import jax

    # a TPU-attached interpreter may pin its platform via sitecustomize
    # before env vars are read; force the CPU backend explicitly so each
    # rank contributes exactly its one virtual CPU device
    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from kubeflow_tpu.parallel import distributed as dist

    penv = dist.from_env()
    dist.initialize()  # reads the same env the operator injects

    n = jax.process_count()
    assert n == penv.num_processes, (n, penv.num_processes)
    devices = jax.devices()

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(devices, ("dp",))
    # each process contributes (process_id + 1); psum must see them all
    local = jnp.asarray([float(penv.process_id + 1)])
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local, (n,))

    @jax.jit
    def total(x):
        return x.sum()

    got = float(total(arr))
    want = n * (n + 1) / 2.0
    ok = abs(got - want) < 1e-6
    print(json.dumps({
        "process_id": penv.process_id,
        "processes": n,
        "devices": len(devices),
        "psum": got,
        "expected": want,
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
