"""Distributed tracing core: spans, W3C propagation, in-process collection.

The reference platform's observability stops at Prometheus scrape
annotations on operator pods (``tf-job-operator.libsonnet:180-184``) —
a counter can say *that* p99 regressed, never *where*. This module is
the missing tier SURVEY §5 names: a request entering the edge proxy
carries one ``trace_id`` through every hop (HTTP header, gRPC metadata,
engine queue, decode batch), so "p99 regressed" is answered by reading
one span tree instead of correlating five components' logs.

Design points, in the platform's house style:

- **Injectable clock** (:mod:`kubeflow_tpu.utils.clock`): span
  timestamps come from the tracer's clock, defaulted by reference to
  ``time.monotonic`` — tests drive a fake clock and get bit-stable
  span trees (tpulint TPU003 contract).
- **W3C ``traceparent``** (``00-<trace>-<span>-<flags>``) is the wire
  format for both HTTP headers and gRPC metadata; :func:`extract`
  accepts either shape (a header mapping or an iterable of key/value
  pairs, the ``grpc.ServicerContext.invocation_metadata()`` contract).
- **ContextVar current span**: nested instrumentation composes without
  threading a span through every signature — ``tracer.span(...)``
  parents onto whatever span is active in this context. Cross-thread
  hand-offs (the decode engine's admission queue) capture
  :func:`current_context` at submit time and parent explicitly.
- **Bounded ring buffer**: the :class:`SpanCollector` holds the last N
  spans and nothing else — no export pipeline required to debug a live
  incident; exporters (:mod:`kubeflow_tpu.obs.export`) read snapshots.
- **Profiler bridge**: a tracer constructed with
  :func:`profiler_annotator` mirrors every *live* span onto the XLA
  host timeline (``jax.profiler.TraceAnnotation``), so a platform span
  ("engine.prefill") lands next to the XLA ops it caused during a
  profiler capture — the correlation the Concurrency-on-TPUs paper
  makes the case for.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple, Union

from kubeflow_tpu.utils.clock import Clock

TRACEPARENT_HEADER = "traceparent"
TRACESTATE_HEADER = "tracestate"
REQUEST_ID_HEADER = "X-Request-Id"

_HEXDIGITS = frozenset("0123456789abcdef")


def _rand_hex(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """The propagated identity of a span: what crosses process/thread
    boundaries (everything else about a span stays local)."""

    trace_id: str   # 32 lowercase hex chars
    span_id: str    # 16 lowercase hex chars


@dataclasses.dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start: float
    end: Optional[float] = None
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    status: str = "OK"

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def duration(self) -> float:
        return (self.end - self.start) if self.end is not None else 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration_s": round(self.duration, 9),
            "attrs": dict(self.attrs),
            "status": self.status,
        }


# -- W3C traceparent propagation ---------------------------------------------


def format_traceparent(ctx: SpanContext, sampled: bool = True) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if sampled else '00'}"


def _hexfield(s: str, width: int) -> bool:
    return len(s) == width and set(s) <= _HEXDIGITS


def parse_traceparent(value: str) -> Optional[SpanContext]:
    """``00-<32 hex>-<16 hex>-<2 hex>`` → context, else None.

    Strict on what the W3C spec makes strict: lowercase hex only,
    version ``ff`` invalid, all-zero trace/span ids invalid. Garbage and
    truncation degrade to None (the request simply starts a new trace)
    rather than raising — propagation must never fail a request.
    """
    if not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if not _hexfield(version, 2) or version == "ff":
        return None
    # a version we don't know may append fields; version 00 must not
    if version == "00" and len(parts) != 4:
        return None
    if not _hexfield(trace_id, 32) or trace_id == "0" * 32:
        return None
    if not _hexfield(span_id, 16) or span_id == "0" * 16:
        return None
    if not _hexfield(flags, 2):
        return None
    return SpanContext(trace_id, span_id)


Carrier = Union[Mapping[str, str], Iterable[Tuple[str, str]]]


def extract(carrier: Optional[Carrier]) -> Optional[SpanContext]:
    """Remote parent from an HTTP header mapping (any key casing) or an
    iterable of (key, value) pairs (gRPC invocation metadata)."""
    if carrier is None:
        return None
    items = carrier.items() if hasattr(carrier, "items") else carrier
    for key, value in items:
        if str(key).lower() == TRACEPARENT_HEADER:
            return parse_traceparent(value)
    return None


def inject(headers: Dict[str, str], ctx: SpanContext) -> Dict[str, str]:
    """Stamp ``traceparent`` into an outgoing HTTP header dict."""
    headers[TRACEPARENT_HEADER] = format_traceparent(ctx)
    return headers


def grpc_metadata(ctx: Optional[SpanContext] = None
                  ) -> Tuple[Tuple[str, str], ...]:
    """Outgoing gRPC metadata carrying the given (or current) span
    context; empty when there is nothing to propagate."""
    ctx = ctx if ctx is not None else current_context()
    if ctx is None:
        return ()
    return ((TRACEPARENT_HEADER, format_traceparent(ctx)),)


# -- collection --------------------------------------------------------------


class SpanCollector:
    """Thread-safe bounded ring buffer of finished spans.

    ``capacity`` bounds memory hard: a serving pod under sustained load
    keeps the most recent window and silently evicts the oldest — the
    incident-debugging window, not an archive (exporters snapshot)."""

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._spans: List[Span] = []
        self._next = 0          # ring write cursor
        self._seq = 0           # total records ever (eviction accounting)
        self._lock = threading.Lock()

    def record(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) < self.capacity:
                self._spans.append(span)
            else:
                self._spans[self._next] = span
                self._next = (self._next + 1) % self.capacity
            self._seq += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    @property
    def recorded_total(self) -> int:
        with self._lock:
            return self._seq

    def spans(self) -> List[Span]:
        """Snapshot, oldest first."""
        with self._lock:
            return self._spans[self._next:] + self._spans[:self._next]

    def trace(self, trace_id: str) -> List[Span]:
        """Every retained span of one trace, sorted by (start, record
        order) so parents precede the children they enclose."""
        return sorted((s for s in self.spans() if s.trace_id == trace_id),
                      key=lambda s: (s.start, s.end if s.end is not None
                                     else s.start))

    def roots(self, limit: int = 50) -> List[Span]:
        """Most recent local root spans (no parent), newest first."""
        roots = [s for s in self.spans() if s.parent_id is None]
        return list(reversed(roots))[:limit]

    def summary(self, limit: int = 50) -> List[Dict[str, Any]]:
        """The dashboard's trace list: recent roots + per-trace span
        counts, newest first."""
        spans = self.spans()
        counts: Dict[str, int] = {}
        for s in spans:
            counts[s.trace_id] = counts.get(s.trace_id, 0) + 1
        out = []
        for root in reversed([s for s in spans if s.parent_id is None]):
            if len(out) >= limit:
                break
            d = root.to_dict()
            d["spans"] = counts.get(root.trace_id, 1)
            out.append(d)
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans = []
            self._next = 0


DEFAULT_COLLECTOR = SpanCollector()

# the active span of this execution context (copied across
# threads/tasks by contextvars semantics only when explicitly carried)
_CURRENT: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("kftpu_current_span", default=None)


def current_span() -> Optional[Span]:
    return _CURRENT.get()


def current_context() -> Optional[SpanContext]:
    sp = _CURRENT.get()
    return sp.context() if sp is not None else None


def profiler_annotator():
    """An annotator bridging live spans onto the XLA host timeline via
    :func:`kubeflow_tpu.utils.profiler.annotate`. Resolves jax lazily
    and degrades to a no-op where jax is absent (edge-tier pods), so a
    tracer configured with it is safe everywhere."""
    state: Dict[str, Any] = {}

    def annotate(name: str):
        fn = state.get("fn")
        if fn is None:
            try:
                from kubeflow_tpu.utils.profiler import annotate as fn
            except Exception:  # noqa: BLE001 — no jax: spans still work
                fn = lambda _name: contextlib.nullcontext()  # noqa: E731
            state["fn"] = fn
        return fn(name)

    return annotate


class Tracer:
    """Produces spans into a collector on an injectable clock.

    One module-level :data:`TRACER` (shared collector, real clock)
    serves the common case; components with their own injected clock
    (decode engine, workflow controller) construct a private tracer over
    the same collector so their span timestamps stay deterministic
    under a fake clock.
    """

    def __init__(self, collector: Optional[SpanCollector] = None,
                 clock: Optional[Clock] = None,
                 annotator=None) -> None:
        # None = the module DEFAULT_COLLECTOR, resolved at record time
        # (dynamically, so every default-constructed tracer in the
        # process — proxy, server, engines — shares one buffer, and
        # tests can swap it in one place)
        self._collector = collector
        self.clock: Clock = clock if clock is not None else time.monotonic
        # annotator(name) -> context manager entered for each LIVE span
        # (the profiler bridge); None = spans only
        self.annotator = annotator

    @property
    def collector(self) -> SpanCollector:
        return (self._collector if self._collector is not None
                else DEFAULT_COLLECTOR)

    @collector.setter
    def collector(self, value: Optional[SpanCollector]) -> None:
        self._collector = value

    # -- span lifecycle ----------------------------------------------------

    def start_span(self, name: str, *,
                   attrs: Optional[Dict[str, Any]] = None,
                   parent: Optional[Union[Span, SpanContext]] = None,
                   remote: Optional[SpanContext] = None) -> Span:
        """``remote`` (an extracted wire context) wins over ``parent``
        wins over the context-local current span; no parent anywhere
        starts a new trace."""
        if remote is not None:
            trace_id, parent_id = remote.trace_id, remote.span_id
        elif parent is not None:
            ctx = parent.context() if isinstance(parent, Span) else parent
            trace_id, parent_id = ctx.trace_id, ctx.span_id
        else:
            cur = current_span()
            if cur is not None:
                trace_id, parent_id = cur.trace_id, cur.span_id
            else:
                trace_id, parent_id = _rand_hex(16), None
        return Span(trace_id=trace_id, span_id=_rand_hex(8),
                    parent_id=parent_id, name=name, start=self.clock(),
                    attrs=dict(attrs or {}))

    def end_span(self, span: Span, status: Optional[str] = None) -> None:
        if span.end is None:
            span.end = self.clock()
        if status is not None:
            span.status = status
        self.collector.record(span)

    @contextlib.contextmanager
    def span(self, name: str, *,
             attrs: Optional[Dict[str, Any]] = None,
             parent: Optional[Union[Span, SpanContext]] = None,
             remote: Optional[SpanContext] = None):
        """Context-managed span: activates itself (children parent onto
        it), mirrors to the profiler timeline when bridged, marks
        status ERROR on exception, records on exit."""
        sp = self.start_span(name, attrs=attrs, parent=parent,
                             remote=remote)
        token = _CURRENT.set(sp)
        ann = (self.annotator(name) if self.annotator is not None
               else contextlib.nullcontext())
        try:
            with ann:
                yield sp
        except BaseException as e:
            sp.status = f"ERROR: {type(e).__name__}"
            raise
        finally:
            _CURRENT.reset(token)
            self.end_span(sp)

    def record(self, name: str, *, start: float, end: float,
               parent: Optional[Union[Span, SpanContext]] = None,
               attrs: Optional[Dict[str, Any]] = None,
               status: str = "OK",
               trace_id: Optional[str] = None,
               span_id: Optional[str] = None) -> Span:
        """Record an already-completed span with explicit timestamps —
        the deterministic path for work whose boundaries the caller
        observed itself (engine queue wait, workflow step start/finish
        parsed from CR status). Explicit ``trace_id``/``span_id`` let a
        controller derive stable ids from object identity so spans from
        different reconcile passes land in one trace."""
        if parent is not None:
            ctx = parent.context() if isinstance(parent, Span) else parent
            tid, pid = ctx.trace_id, ctx.span_id
        else:
            tid, pid = trace_id if trace_id else _rand_hex(16), None
        if trace_id:
            tid = trace_id
        sp = Span(trace_id=tid,
                  span_id=span_id if span_id else _rand_hex(8),
                  parent_id=pid, name=name, start=start, end=end,
                  attrs=dict(attrs or {}), status=status)
        self.collector.record(sp)
        return sp


TRACER = Tracer()
