"""Declarative alert engine over the in-process tsdb.

The reference's alerting lived in the prometheus/stackdriver pair the
``monitoring`` component deploys; nothing in-framework could say "the
error budget is burning" or "queue depth has been high for 10 minutes".
This module is that engine, evaluated against
:class:`~kubeflow_tpu.obs.tsdb.TimeSeriesStore` through the same
:func:`~kubeflow_tpu.obs.tsdb.evaluate` path the dashboard's query API
uses — the alert and the panel can never disagree.

Rule kinds (all declarative dataclasses, serializable via
``to_dict``/``rule_from_dict`` — docs/OBSERVABILITY.md has the syntax):

- :class:`ThresholdRule` — a tsdb expression (instant / rate / delta /
  avg / histogram quantile) compared against a bound, with a ``for:``
  duration before firing (Prometheus ``for:`` semantics: the condition
  must hold continuously).
- :class:`AbsenceRule` — fires when a series that should exist has no
  fresh point for ``for_s`` (the dead-exporter alarm ``up`` alone
  can't express for in-process registries).
- :class:`BurnRateRule` — multi-window multi-burn-rate SLO alerting
  (the SRE-workbook shape): the error ratio
  ``rate(numerator)/rate(denominator)`` must exceed
  ``factor × (1 - objective)`` over BOTH the long and the short window
  of any configured pair. The long window makes it meaningful (a real
  budget bite), the short window makes it current (stops firing as
  soon as the bleeding stops).

State machine per rule: ``Inactive → Pending → Firing → Resolved``
(→ ``Inactive``). Every *transition* — never a steady state — emits one
deduplicated k8s Event, one ``alerts.transition`` span, and updates the
``kftpu_alerts_firing{rule=}`` gauge. The engine runs as a
``Controller.periodic`` on the shared workqueue runtime
(:meth:`AlertManager.build_controller`), clock-injectable end to end
(TPU003): the smoke gates walk pending→firing→resolved on a fake clock.

Latency-shaped rules carry trace exemplars: when a quantile rule
fires, the alert state records a recent exemplar trace id from the
offending ``_bucket`` series, so the alert links straight to a trace
of a request that actually landed in the slow bucket.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

from kubeflow_tpu.k8s.client import ApiError, KubeClient
from kubeflow_tpu.obs.tsdb import TimeSeriesStore, evaluate
from kubeflow_tpu.obs.trace import TRACER, Tracer
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

# alert states
INACTIVE = "Inactive"
PENDING = "Pending"
FIRING = "Firing"
RESOLVED = "Resolved"   # transient: one tick, then Inactive

_firing_g = DEFAULT_REGISTRY.gauge(
    "kftpu_alerts_firing", "1 while the named alert rule is firing")
_transitions_c = DEFAULT_REGISTRY.counter(
    "kftpu_alert_transitions_total", "alert state transitions by rule")


_THRESHOLD_OPS: Dict[str, Any] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


@dataclasses.dataclass(frozen=True)
class ThresholdRule:
    """``<func>(metric[window]) <op> threshold`` held for ``for_s``."""

    name: str
    metric: str
    op: str = ">"                       # one of > >= < <=
    threshold: float = 0.0
    for_s: float = 0.0
    func: str = "instant"               # instant|rate|delta|avg|quantile
    window_s: float = 300.0
    quantile: float = 0.99              # func == "quantile" only
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    severity: str = "warning"
    summary: str = ""

    def __post_init__(self) -> None:
        # rules load from data (rule_from_dict): a typo'd op must fail
        # loudly at construction, never evaluate with inverted semantics
        if self.op not in _THRESHOLD_OPS:
            raise ValueError(
                f"rule {self.name!r}: unknown op {self.op!r}; "
                f"known: {', '.join(sorted(_THRESHOLD_OPS))}")

    def evaluate(self, store: TimeSeriesStore, at: float
                 ) -> Tuple[bool, Optional[float], Optional[str]]:
        results = evaluate(store, self.func, self.metric,
                           match=dict(self.labels),
                           window_s=self.window_s, q=self.quantile,
                           at=at)
        breach = _THRESHOLD_OPS[self.op]
        upward = self.op in (">", ">=")
        worst: Optional[float] = None
        for _labels, value in results:
            if breach(value, self.threshold) and (
                    worst is None
                    or (value > worst if upward else value < worst)):
                worst = value
        if worst is None:
            return False, (results[0][1] if results else None), None
        exemplar = None
        if self.func == "quantile":
            recent = store.exemplars(f"{self.metric}_bucket",
                                     dict(self.labels),
                                     since=at - self.window_s)
            if recent:
                # the worst in-window offender, not merely the latest:
                # a latency alert should link to a trace that actually
                # sat in the slow bucket
                exemplar = max(recent, key=lambda e: e.value).trace_id
        return True, worst, exemplar

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": "threshold", **dataclasses.asdict(self),
                "labels": dict(self.labels)}


@dataclasses.dataclass(frozen=True)
class AbsenceRule:
    """Fires when the series has no point younger than ``for_s``."""

    name: str
    metric: str
    for_s: float = 300.0
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    severity: str = "warning"
    summary: str = ""

    def evaluate(self, store: TimeSeriesStore, at: float
                 ) -> Tuple[bool, Optional[float], Optional[str]]:
        pts = store.window(self.metric, dict(self.labels),
                           at - self.for_s, at)
        present = any(p for _labels, p in pts)
        return (not present), None, None

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": "absence", **dataclasses.asdict(self),
                "labels": dict(self.labels)}


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    """One (long, short, factor) burn-rate window pair."""

    long_s: float
    short_s: float
    factor: float


# the SRE-workbook default ladder, scaled to in-process retention:
# page on a fast burn (14.4x over 1h&5m), ticket on a slow one
# (6x over 6h&30m)
DEFAULT_BURN_WINDOWS: Tuple[BurnWindow, ...] = (
    BurnWindow(3600.0, 300.0, 14.4),
    BurnWindow(6 * 3600.0, 1800.0, 6.0),
)


@dataclasses.dataclass(frozen=True)
class BurnRateRule:
    """Multi-window multi-burn-rate SLO rule over two counter series.

    ``error_ratio(w) = sum(rate(numerator[w])) / sum(rate(denominator
    [w]))``; the rule is active when, for ANY window pair, the ratio
    over BOTH the long and short window is ``>= factor × (1 -
    objective)``. No denominator traffic in a window means no verdict
    from that window (absent-never-wrong — an idle service is not
    meeting nor missing its SLO)."""

    name: str
    numerator: str                       # e.g. request count, 5xx only
    denominator: str                     # e.g. request count, all
    objective: float = 0.999             # SLO success target
    numerator_labels: Mapping[str, str] = dataclasses.field(
        default_factory=dict)
    denominator_labels: Mapping[str, str] = dataclasses.field(
        default_factory=dict)
    windows: Sequence[BurnWindow] = DEFAULT_BURN_WINDOWS
    for_s: float = 0.0                   # the short window already gates
    severity: str = "critical"
    summary: str = ""

    def _ratio(self, store: TimeSeriesStore, window_s: float,
               at: float) -> Optional[float]:
        num = sum(v for _l, v in store.rate(
            self.numerator, dict(self.numerator_labels), window_s, at))
        den_rates = store.rate(self.denominator,
                               dict(self.denominator_labels), window_s, at)
        den = sum(v for _l, v in den_rates)
        if not den_rates or den <= 0:
            return None
        return num / den

    def evaluate(self, store: TimeSeriesStore, at: float
                 ) -> Tuple[bool, Optional[float], Optional[str]]:
        budget = 1.0 - self.objective
        worst: Optional[float] = None
        active = False
        for w in self.windows:
            long_r = self._ratio(store, w.long_s, at)
            short_r = self._ratio(store, w.short_s, at)
            if long_r is None or short_r is None:
                continue
            worst = max(worst if worst is not None else 0.0,
                        long_r, short_r)
            if long_r >= w.factor * budget and short_r >= w.factor * budget:
                active = True
        return active, worst, None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["numerator_labels"] = dict(self.numerator_labels)
        d["denominator_labels"] = dict(self.denominator_labels)
        d["windows"] = [dataclasses.asdict(w) for w in self.windows]
        return {"kind": "burn_rate", **d}


Rule = Union[ThresholdRule, AbsenceRule, BurnRateRule]

_RULE_KINDS = {"threshold": ThresholdRule, "absence": AbsenceRule,
               "burn_rate": BurnRateRule}


def rule_from_dict(d: Mapping[str, Any]) -> Rule:
    """Inverse of ``Rule.to_dict`` — the declarative load path (rule
    packs shipped as data, e.g. a ConfigMap)."""
    spec = dict(d)
    kind = spec.pop("kind", "threshold")
    cls = _RULE_KINDS.get(kind)
    if cls is None:
        raise ValueError(f"unknown rule kind {kind!r}; "
                         f"known: {sorted(_RULE_KINDS)}")
    if cls is BurnRateRule and "windows" in spec:
        spec["windows"] = tuple(
            w if isinstance(w, BurnWindow) else BurnWindow(**w)
            for w in spec["windows"])
    return cls(**spec)


@dataclasses.dataclass
class AlertState:
    """One rule's live state + the last evaluation's evidence."""

    rule: Rule
    state: str = INACTIVE
    since: Optional[float] = None        # entered current state at
    active_since: Optional[float] = None  # condition first true at
    value: Optional[float] = None
    exemplar_trace_id: Optional[str] = None
    transitions: int = 0
    last_resolved_at: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule.name,
            "severity": self.rule.severity,
            "state": self.state,
            "since": self.since,
            "value": self.value,
            "exemplarTraceId": self.exemplar_trace_id,
            "transitions": self.transitions,
            "summary": getattr(self.rule, "summary", ""),
            "spec": self.rule.to_dict(),
        }


class AlertManager:
    """Evaluates rules each tick; owns the FSM + Events + gauge + spans.

    ``client`` is optional: without one, transitions still trace and
    meter (the dev/in-process shape); with one, each transition emits
    exactly one k8s Event in ``namespace`` (deduped by construction —
    Events are created only inside the transition branch, and a steady
    state is not a transition)."""

    def __init__(self, store: TimeSeriesStore,
                 rules: Optional[Sequence[Rule]] = None, *,
                 client: Optional[KubeClient] = None,
                 namespace: str = "kubeflow",
                 clock: Optional[Clock] = None,
                 tracer: Optional[Tracer] = None,
                 interval_s: float = 15.0) -> None:
        self.store = store
        self.client = client
        self.namespace = namespace
        self.clock: Clock = clock if clock is not None else store.clock
        self.tracer = tracer if tracer is not None else TRACER
        self.interval_s = float(interval_s)
        self._states: Dict[str, AlertState] = {}
        self._event_seq = 0
        self._lock = threading.Lock()
        for rule in (rules if rules is not None else default_rules()):
            self.add_rule(rule)

    def add_rule(self, rule: Rule) -> None:
        with self._lock:
            if rule.name in self._states:
                raise ValueError(f"alert rule {rule.name!r} already exists")
            self._states[rule.name] = AlertState(rule=rule)
            _firing_g.set(0.0, rule=rule.name)

    def remove_rule(self, name: str) -> None:
        with self._lock:
            self._states.pop(name, None)
            _firing_g.remove(rule=name)

    # -- evaluation --------------------------------------------------------

    def evaluate(self, at: Optional[float] = None) -> List[AlertState]:
        """One evaluation pass over every rule; returns the states that
        transitioned this pass (the smoke gates assert on it)."""
        now = at if at is not None else self.clock()
        with self._lock:
            states = list(self._states.values())
        transitioned: List[AlertState] = []
        for st in states:
            if self._step(st, now):
                transitioned.append(st)
        return transitioned

    def _step(self, st: AlertState, now: float) -> bool:
        rule = st.rule
        try:
            active, value, exemplar = rule.evaluate(self.store, now)
        except Exception:  # noqa: BLE001 — one bad rule never kills the loop
            log.exception("alert rule %s evaluation failed", rule.name)
            return False
        st.value = value
        if active:
            # fresh evidence only: THIS activation's exemplar (possibly
            # none), never a previous incident's trace id
            st.exemplar_trace_id = exemplar
        # an AbsenceRule's for_s IS the silence window its evaluate()
        # already waited out — applying it again as a pending duration
        # would double the time-to-fire
        for_s = (0.0 if isinstance(rule, AbsenceRule)
                 else getattr(rule, "for_s", 0.0))
        if st.state in (INACTIVE, RESOLVED):
            if active:
                st.active_since = now
                if for_s > 0:
                    self._transition(st, PENDING, now)
                else:
                    self._transition(st, FIRING, now)
                return True
            if st.state == RESOLVED:
                # Resolved is transient: visible for one tick, then
                # idle — and the incident's exemplar goes with it (an
                # Inactive rule must not link to an old incident)
                st.state = INACTIVE
                st.since = now
                st.exemplar_trace_id = None
            return False
        if st.state == PENDING:
            if not active:
                st.active_since = None
                self._transition(st, INACTIVE, now)
                st.exemplar_trace_id = None  # the near-incident is over
                return True
            if now - (st.active_since if st.active_since is not None
                      else now) >= for_s:
                self._transition(st, FIRING, now)
                return True
            return False
        if st.state == FIRING:
            if not active:
                st.active_since = None
                st.last_resolved_at = now
                self._transition(st, RESOLVED, now)
                return True
            return False
        return False

    def _transition(self, st: AlertState, to: str, now: float) -> None:
        frm = st.state
        st.state = to
        st.since = now
        st.transitions += 1
        _transitions_c.inc(rule=st.rule.name, to=to)
        _firing_g.set(1.0 if to == FIRING else 0.0, rule=st.rule.name)
        # the alert-evaluation span: one per transition, so an incident
        # trace shows exactly when the rule walked its states
        with self.tracer.span("alerts.transition", attrs={
                "rule": st.rule.name, "from": frm, "to": to,
                "value": st.value, "severity": st.rule.severity,
                **({"exemplarTraceId": st.exemplar_trace_id}
                   if st.exemplar_trace_id else {})}):
            pass
        self._emit_event(st, frm, to, now)
        log.info("alert %s: %s -> %s (value=%s)",
                 st.rule.name, frm, to, st.value)

    def _emit_event(self, st: AlertState, frm: str, to: str,
                    now: float) -> None:
        if self.client is None:
            return
        with self._lock:
            self._event_seq += 1
            seq = self._event_seq
        summary = getattr(st.rule, "summary", "") or st.rule.name
        value = ("" if st.value is None
                 else f" (value={round(st.value, 6)})")
        event = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                # seq-suffixed name: every transition is its OWN Event
                # (create, never patch), and re-evaluations of a steady
                # state create nothing — exactly one Event per transition
                "name": f"alert-{st.rule.name}-{seq}",
                "namespace": self.namespace,
            },
            "type": ("Warning" if to in (PENDING, FIRING) else "Normal"),
            "reason": f"Alert{to}",
            "message": f"alert {st.rule.name}: {frm} -> {to}: "
                       f"{summary}{value}",
            "involvedObject": {"kind": "AlertRule", "name": st.rule.name,
                               "namespace": self.namespace},
        }
        if st.exemplar_trace_id:
            event["message"] += f" traceId={st.exemplar_trace_id}"
        try:
            self.client.create(event)
        except ApiError as e:
            log.warning("alert event for %s not recorded: %s",
                        st.rule.name, e)

    # -- views -------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The dashboard's ``GET /api/alerts`` payload."""
        with self._lock:
            states = [st.to_dict() for st in self._states.values()]
        states.sort(key=lambda s: (s["state"] == INACTIVE, s["rule"]))
        return {"rules": states,
                "firing": sum(1 for s in states if s["state"] == FIRING)}

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(name for name, st in self._states.items()
                          if st.state == FIRING)

    # -- runtime -----------------------------------------------------------

    def build_controller(self, interval_s: Optional[float] = None):
        """Run evaluation on the shared reconciler runtime
        (``Controller.periodic``) — uniform ``controller.reconcile``
        spans + counter, like every other control loop."""
        from kubeflow_tpu.operators.controller import Controller

        interval = interval_s if interval_s is not None else self.interval_s

        def reconcile(_ns: str, _name: str) -> float:
            self.evaluate()
            return interval

        return Controller.periodic(reconcile, name="alerts",
                                   tracer=self.tracer)


# -- the starter rule pack ---------------------------------------------------


def default_rules() -> List[Rule]:
    """Rules over series the platform actually emits (names are pinned
    by tests against their emitting modules — docs/OBSERVABILITY.md):

    - **proxy-5xx-burn-rate** — the serving SLO: 5xx ratio of the edge
      proxy's ``request_latency_seconds_count`` (PR 3) burning the
      99.9% error budget at the SRE-workbook window ladder.
    - **proxy-p99-latency** — p99 over the same histogram's buckets;
      carries an exemplar trace id when it fires.
    - **engine-pages-exhausted** — the paged decode engine is about to
      stall admissions: ``kftpu_engine_kv_pages_free`` (PR 6) pinned
      near zero for a minute.
    - **queue-depth-sustained** — gangs waiting in the scheduler queue
      (``kftpu_queue_depth{state="Queued"}``, PR 8) for 10 minutes.
    - **recompile-storm** — real XLA compile events
      (``kftpu_compile_seconds_count``, the xprof ledger) arriving at
      a sustained rate: startup compiles age out of the 5m window, so
      an elevated rate two minutes running IS cache churn — rebased
      from the old ``train_recompiles_total`` inference now that the
      ledger records actual backend compiles.
    - **straggler-flagged** — a TpuJob has had a flagged straggler
      (``kftpu_job_stragglers``, PR 5) for 5 minutes.
    - **hbm-headroom** — ``kftpu_hbm_utilization`` (the xprof
      watermark sampler's in_use/limit) above 92% for 2 minutes: the
      job or engine is about to OOM or fragment; shed, shrink, or
      repack before the allocator does it for you.
    - **job-badput-burn** — the goodput ledger's chips-weighted badput
      ratio (``kftpu_fleet_badput_chip_seconds_total`` over
      ``kftpu_fleet_chip_seconds_total``, docs/OBSERVABILITY.md
      "Goodput") burning the fleet's 10% non-productive budget —
      badput IS an error budget, so this reuses ``BurnRateRule``
      unchanged; the window factors are scaled down from the 5xx
      ladder because a 10% budget caps the expressible burn ratio at
      10× (a 14.4× factor could never fire).
    - **ttft-slo-burn-{interactive,standard,batch}** — the request
      ledger's TTFT-breach ratio per SLO class
      (``kftpu_request_ttft_breach_total`` over
      ``kftpu_request_finished_total``, both labeled ``slo_class`` —
      docs/OBSERVABILITY.md "Request lifecycle") burning that class's
      latency budget. Objectives mirror the class's criticality:
      interactive 98%, standard 90%, batch 70% of requests inside
      their TTFT target — and each class's window factors are capped
      by its budget (batch's 30% budget means a 6× factor could never
      fire, so it burns at 3×/1.5×).
    """
    return [
        BurnRateRule(
            name="proxy-5xx-burn-rate",
            numerator="request_latency_seconds_count",
            numerator_labels={"code": "5*"},
            denominator="request_latency_seconds_count",
            objective=0.999,
            # a short for: makes the Pending state visible (one tick of
            # "about to page") without delaying the page meaningfully
            for_s=60.0,
            severity="critical",
            summary="edge proxy 5xx ratio is burning the 99.9% SLO "
                    "error budget"),
        ThresholdRule(
            name="proxy-p99-latency",
            metric="request_latency_seconds",
            func="quantile", quantile=0.99, window_s=300.0,
            op=">", threshold=2.0, for_s=60.0,
            severity="warning",
            summary="edge proxy p99 latency above 2s over 5m"),
        ThresholdRule(
            name="engine-pages-exhausted",
            metric="kftpu_engine_kv_pages_free",
            func="instant", op="<", threshold=2.0, for_s=60.0,
            severity="critical",
            summary="decode engine KV page pool nearly exhausted — "
                    "admissions will stall"),
        ThresholdRule(
            name="queue-depth-sustained",
            metric="kftpu_queue_depth",
            labels={"state": "Queued"},
            func="instant", op=">", threshold=4.0, for_s=600.0,
            severity="warning",
            summary="scheduler gang queue depth high for 10m"),
        ThresholdRule(
            name="recompile-storm",
            metric="kftpu_compile_seconds_count",
            func="rate", window_s=300.0,
            op=">", threshold=0.02, for_s=120.0,
            severity="warning",
            summary="XLA compile events arriving at a sustained rate "
                    "(jit cache churn eating step time)"),
        ThresholdRule(
            name="straggler-flagged",
            metric="kftpu_job_stragglers",
            func="instant", op=">", threshold=0.0, for_s=300.0,
            severity="warning",
            summary="a TpuJob gang has a straggling worker flagged "
                    "for 5m"),
        ThresholdRule(
            name="hbm-headroom",
            metric="kftpu_hbm_utilization",
            func="instant", op=">", threshold=0.92, for_s=120.0,
            severity="critical",
            summary="device HBM in_use above 92% of limit for 2m — "
                    "headroom nearly exhausted (OOM/fragmentation "
                    "imminent)"),
        BurnRateRule(
            name="job-badput-burn",
            numerator="kftpu_fleet_badput_chip_seconds_total",
            denominator="kftpu_fleet_chip_seconds_total",
            # 90% of fleet chip-time productive; page when badput
            # burns ≥6× the 10% budget (≥60% of chip-time wasted) over
            # 1h&5m, ticket at 3× over 6h&30m
            objective=0.90,
            windows=(BurnWindow(3600.0, 300.0, 6.0),
                     BurnWindow(6 * 3600.0, 1800.0, 3.0)),
            for_s=60.0,
            severity="warning",
            summary="fleet badput (non-productive chip-seconds from "
                    "the goodput ledger) is burning the 10% "
                    "efficiency budget"),
        # one burn rule per SLO class: a batch backlog blowing its lax
        # TTFT target must not page the interactive on-call, and an
        # interactive breach must not hide inside a batch-dominated
        # fleet ratio. (objective, factors) per class keep each ladder
        # expressible within its budget (factor × budget < 1).
        *(BurnRateRule(
            name=f"ttft-slo-burn-{cls}",
            numerator="kftpu_request_ttft_breach_total",
            numerator_labels={"slo_class": cls},
            denominator="kftpu_request_finished_total",
            denominator_labels={"slo_class": cls},
            objective=objective,
            windows=(BurnWindow(3600.0, 300.0, fast),
                     BurnWindow(6 * 3600.0, 1800.0, slow)),
            for_s=60.0,
            severity="critical" if cls == "interactive" else "warning",
            summary=f"{cls!r}-class requests are missing their TTFT "
                    f"target, burning the {100 * (1 - objective):.0f}% "
                    "latency budget")
          for cls, objective, fast, slow in (
              ("interactive", 0.98, 6.0, 3.0),
              ("standard", 0.90, 6.0, 3.0),
              ("batch", 0.70, 3.0, 1.5))),
    ]
