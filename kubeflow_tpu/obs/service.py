"""Trace-collector service: the fleet's span sink + trace query API.

Deployed by the ``trace-collector`` manifest component. Components push
span batches (:func:`kubeflow_tpu.obs.export.push_spans`) or operators
query a pod's own in-process collector through the identical routes the
dashboard serves — one API shape everywhere:

- ``GET  /api/traces``               recent root spans (+ span counts)
- ``GET  /api/traces/<trace_id>``    the full span tree, start-ordered
- ``GET  /api/traces/<trace_id>:chrome``  Chrome trace_event JSON
- ``POST /api/traces:ingest``        ``{"spans": [otlp-ish records]}``
- ``GET  /metrics`` / ``GET /healthz``
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Tuple

from kubeflow_tpu.obs.export import chrome_trace, span_from_record
from kubeflow_tpu.obs.trace import DEFAULT_COLLECTOR, SpanCollector
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.jsonhttp import RawResponse, serve_json

log = logging.getLogger(__name__)

_ingested = DEFAULT_REGISTRY.counter(
    "kftpu_trace_spans_ingested_total", "spans accepted by the collector")


def trace_detail(collector: SpanCollector,
                 trace_id: str) -> Tuple[int, Any]:
    """The one ``GET /api/traces/<id>`` handler — shared by this
    service and the dashboard so the API shape can never drift."""
    spans = collector.trace(trace_id)
    if not spans:
        return 404, {"error": f"trace {trace_id!r} not found"}
    return 200, {"trace_id": trace_id,
                 "spans": [s.to_dict() for s in spans]}


class TraceCollectorService:
    """Route table over a :class:`SpanCollector` (shared JSON scaffold)."""

    def __init__(self, collector: Optional[SpanCollector] = None,
                 registry=DEFAULT_REGISTRY) -> None:
        self.collector = (collector if collector is not None
                          else DEFAULT_COLLECTOR)
        self.registry = registry

    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str = "",
               headers: Optional[Dict[str, str]] = None) -> Tuple[int, Any]:
        if method == "GET" and path == "/healthz":
            return 200, {"ok": True}
        if method == "GET" and path == "/metrics":
            from kubeflow_tpu.utils.metrics import exposition

            # exemplar suffixes only for a scraper that requested the
            # extension; a classic prometheus gets a clean 0.0.4 body
            payload, ctype = exposition(self.registry, headers or {})
            return 200, RawResponse(ctype, payload)
        if method == "GET" and path == "/api/traces":
            return 200, self.collector.summary()
        if method == "POST" and path == "/api/traces:ingest":
            return self.ingest(body)
        if method == "GET" and path.startswith("/api/traces/"):
            tid = path[len("/api/traces/"):]
            if tid.endswith(":chrome"):
                return self.trace_chrome(tid[:-len(":chrome")])
            return self.trace_detail(tid)
        return 404, {"error": f"no route {path}"}

    # -- handlers ----------------------------------------------------------

    def trace_detail(self, trace_id: str) -> Tuple[int, Any]:
        return trace_detail(self.collector, trace_id)

    def trace_chrome(self, trace_id: str) -> Tuple[int, Any]:
        spans = self.collector.trace(trace_id)
        if not spans:
            return 404, {"error": f"trace {trace_id!r} not found"}
        return 200, chrome_trace(spans)

    def ingest(self, body: Optional[Dict[str, Any]]) -> Tuple[int, Any]:
        records = (body or {}).get("spans")
        if not isinstance(records, list):
            return 400, {"error": "body must carry 'spans' (a list of "
                                  "otlp-ish span records)"}
        accepted = 0
        for rec in records:
            try:
                self.collector.record(span_from_record(rec))
                accepted += 1
            except (KeyError, TypeError, ValueError):
                continue  # one bad record must not drop the batch
        _ingested.inc(accepted)
        return 200, {"accepted": accepted,
                     "rejected": len(records) - accepted}


def main() -> None:
    import os

    logging.basicConfig(level=logging.INFO)
    capacity = int(os.environ.get("KFTPU_TRACE_CAPACITY", "65536"))
    service = TraceCollectorService(SpanCollector(capacity=capacity))
    serve_json(service.handle,
               int(os.environ.get("KFTPU_TRACE_PORT", "8095")))


if __name__ == "__main__":
    main()
