"""In-process time-series store: the monitoring tier's memory.

Every metric the platform emits so far is a point-in-time snapshot in a
per-process :class:`~kubeflow_tpu.utils.metrics.Registry` — nothing can
answer "what was the p99 over the last 5 minutes" or "has queue depth
stayed high for 10 minutes", which is exactly what the reference's
prometheus deployment (``gcp/prometheus.libsonnet``) provided and what
the SLO alerting in :mod:`kubeflow_tpu.obs.alerts` needs. This module
is that store, in the platform's house style:

- **bounded rings** — every series holds at most ``max_points`` raw
  points inside ``retention_s``; points aging out of the raw window
  fold into a coarser downsampled ring (block-last at
  ``downsample_resolution_s``) kept for ``downsample_retention_s``.
  Memory is bounded hard; an idle series costs nothing.
- **injectable clock** (TPU003): sampling ticks, staleness, and every
  window query run off ``clock``; tests drive a fake clock and get
  bit-stable results.
- **counter functions** — :meth:`rate` / :meth:`delta` over a window
  with counter-reset detection (a restarted process's counter drops to
  zero; the reset is absorbed, never a negative rate), and
  :meth:`histogram_quantile` over the cumulative ``_bucket`` series our
  own :class:`~kubeflow_tpu.utils.metrics.Histogram` exposes — the
  Prometheus estimation algorithm (linear interpolation within the
  bucket that crosses the rank; ``+Inf``-resident mass clamps to the
  highest finite bound).
- **staleness** — :meth:`latest` refuses points older than
  ``staleness_s`` (the Prometheus 5-minute rule), so a dead target's
  frozen gauges stop answering instant queries; the scraper's
  per-target ``up`` series says *why*.
- **exemplars** — ingested samples may carry a trace id
  (:class:`Exemplar`); the store keeps a small ring per series so a
  quantile answer can hand back "and here is a trace that landed in
  that bucket" (docs/OBSERVABILITY.md, exemplar format).

Ingestion parses the Prometheus text format the registries already
emit (one path for local sampling and remote scrapes — what round-trips
is what is stored), via :func:`kubeflow_tpu.obs.scrape.parse_exposition`.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import (
    Any,
    Deque,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from kubeflow_tpu.utils.clock import Clock
from kubeflow_tpu.utils.metrics import Registry

_LabelKey = Tuple[Tuple[str, str], ...]


@dataclass(frozen=True)
class Exemplar:
    """A trace reference attached to one observed sample."""

    trace_id: str
    value: float
    ts: float

    def to_dict(self) -> Dict[str, Any]:
        return {"traceId": self.trace_id, "value": self.value,
                "ts": self.ts}


@dataclass(frozen=True)
class Point:
    ts: float
    value: float


class _Series:
    """One (name, labels) series: raw ring + downsampled tier."""

    __slots__ = ("labels", "points", "down", "exemplars", "_down_block")

    def __init__(self, labels: _LabelKey, max_points: int,
                 max_down: int, max_exemplars: int) -> None:
        self.labels = labels
        self.points: Deque[Point] = deque(maxlen=max_points)
        self.down: Deque[Point] = deque(maxlen=max_down)
        self.exemplars: Deque[Exemplar] = deque(maxlen=max_exemplars)
        self._down_block: Optional[int] = None  # last folded block id


def _label_key(labels: Optional[Mapping[str, str]]) -> _LabelKey:
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


def match_labels(labels: Mapping[str, str],
                 match: Optional[Mapping[str, str]]) -> bool:
    """Subset equality match; a match value ending in ``*`` is a prefix
    match (``code="5*"`` selects every 5xx row — the alert rules' only
    concession to regexes)."""
    if not match:
        return True
    for k, want in match.items():
        got = labels.get(k)
        if got is None:
            return False
        if want.endswith("*"):
            if not got.startswith(want[:-1]):
                return False
        elif got != want:
            return False
    return True


class TimeSeriesStore:
    """Bounded in-process TSDB over (metric name, label set) series."""

    def __init__(self, *, clock: Optional[Clock] = None,
                 retention_s: float = 3600.0,
                 max_points: int = 2048,
                 downsample_resolution_s: float = 60.0,
                 downsample_retention_s: float = 6 * 3600.0,
                 staleness_s: float = 300.0,
                 max_series: int = 8192,
                 max_exemplars_per_series: int = 8) -> None:
        self.clock: Clock = clock if clock is not None else time.monotonic
        self.retention_s = float(retention_s)
        self.staleness_s = float(staleness_s)
        self.downsample_resolution_s = float(downsample_resolution_s)
        self.downsample_retention_s = float(downsample_retention_s)
        self.max_points = int(max_points)
        self.max_series = int(max_series)
        self.max_exemplars = int(max_exemplars_per_series)
        self._max_down = max(
            int(downsample_retention_s / downsample_resolution_s), 1)
        self._series: Dict[str, Dict[_LabelKey, _Series]] = {}
        self._series_count = 0   # O(1) cap check (series never removed)
        self._dropped_series = 0
        self._lock = threading.Lock()

    # -- ingestion ---------------------------------------------------------

    def ingest(self, name: str, value: float, *,
               labels: Optional[Mapping[str, str]] = None,
               ts: Optional[float] = None,
               exemplar: Optional[Exemplar] = None) -> None:
        """Append one sample. NaN values are dropped (the text format's
        staleness marker shape); the series ring is created on first
        touch, up to ``max_series`` (over budget, new series are counted
        and dropped — bounded memory beats completeness)."""
        if value is None or (isinstance(value, float) and math.isnan(value)):
            return
        at = ts if ts is not None else self.clock()
        key = _label_key(labels)
        with self._lock:
            by_label = self._series.setdefault(name, {})
            series = by_label.get(key)
            if series is None:
                if self._series_count >= self.max_series:
                    self._dropped_series += 1
                    return
                series = by_label[key] = _Series(
                    key, self.max_points, self._max_down,
                    self.max_exemplars)
                self._series_count += 1
            if len(series.points) == series.points.maxlen:
                # count overflow inside the retention window: the
                # evicted head still folds into the downsampled tier
                self._fold_point(series, series.points.popleft())
            # out-of-order within a scrape tick is fine; strictly older
            # than the ring tail is not worth reordering for
            series.points.append(Point(at, float(value)))
            if exemplar is not None:
                series.exemplars.append(exemplar)
            self._fold(series, at)

    def _fold(self, series: _Series, now: float) -> None:
        """Move raw points older than the retention window into the
        downsampled tier (block-last at ``downsample_resolution_s`` —
        right for counters, whose increase across blocks survives, and
        honest for gauges: the freshest value of the block)."""
        cutoff = now - self.retention_s
        while series.points and series.points[0].ts < cutoff:
            self._fold_point(series, series.points.popleft())

    def _fold_point(self, series: _Series, p: Point) -> None:
        block = int(p.ts // self.downsample_resolution_s)
        if series._down_block == block and series.down:
            series.down[-1] = Point(series.down[-1].ts, p.value)
        else:
            series.down.append(Point(p.ts, p.value))
            series._down_block = block

    def sample_registry(self, registry: Registry, *,
                        labels: Optional[Mapping[str, str]] = None,
                        ts: Optional[float] = None) -> int:
        """Sample every series a :class:`Registry` exposes, through the
        same text-format parser the remote scraper uses (one ingestion
        path; what round-trips is what is stored). Returns the number of
        samples ingested. ``labels`` (e.g. ``target=local``) merge into
        every sample's labels, sample-side values winning."""
        from kubeflow_tpu.obs.scrape import parse_exposition

        at = ts if ts is not None else self.clock()
        n = 0
        for s in parse_exposition(registry.expose()):
            merged = dict(labels or {})
            merged.update(s.labels)
            ex = None
            if s.exemplar_trace_id is not None:
                ex = Exemplar(s.exemplar_trace_id,
                              s.exemplar_value if s.exemplar_value
                              is not None else s.value, at)
            self.ingest(s.name, s.value, labels=merged, ts=at, exemplar=ex)
            n += 1
        return n

    # -- raw reads ---------------------------------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str,
               match: Optional[Mapping[str, str]] = None
               ) -> List[Tuple[Dict[str, str], List[Point]]]:
        """Every matching series: (labels, raw+downsampled points oldest
        first). Snapshot copies — callers can't race the rings."""
        with self._lock:
            by_label = self._series.get(name, {})
            out = []
            for key, s in sorted(by_label.items()):
                labels = dict(key)
                if not match_labels(labels, match):
                    continue
                out.append((labels, list(s.down) + list(s.points)))
            return out

    def window(self, name: str, match: Optional[Mapping[str, str]],
               start: float, end: float
               ) -> List[Tuple[Dict[str, str], List[Point]]]:
        """Matching series restricted to ``start <= ts <= end``."""
        return [(labels, [p for p in pts if start <= p.ts <= end])
                for labels, pts in self.series(name, match)]

    def exemplars(self, name: str,
                  match: Optional[Mapping[str, str]] = None,
                  since: Optional[float] = None) -> List[Exemplar]:
        """Recent exemplars across matching series, newest last."""
        with self._lock:
            out: List[Exemplar] = []
            for key, s in sorted(self._series.get(name, {}).items()):
                if not match_labels(dict(key), match):
                    continue
                out.extend(e for e in s.exemplars
                           if since is None or e.ts >= since)
        out.sort(key=lambda e: e.ts)
        return out

    # -- instant functions -------------------------------------------------

    def latest(self, name: str,
               match: Optional[Mapping[str, str]] = None,
               at: Optional[float] = None
               ) -> List[Tuple[Dict[str, str], Point]]:
        """Per-series newest point no newer than ``at`` and no older
        than the staleness window (dead targets go silent, not frozen)."""
        now = at if at is not None else self.clock()
        out = []
        for labels, pts in self.series(name, match):
            last = None
            for p in pts:
                if p.ts <= now:
                    last = p
            if last is not None and now - last.ts <= self.staleness_s:
                out.append((labels, last))
        return out

    def _windowed(self, name: str, match: Optional[Mapping[str, str]],
                  window_s: float, at: Optional[float]
                  ) -> List[Tuple[Dict[str, str], List[Point]]]:
        now = at if at is not None else self.clock()
        return [(labels, pts) for labels, pts
                in self.window(name, match, now - float(window_s), now)]

    def rate(self, name: str,
             match: Optional[Mapping[str, str]] = None,
             window_s: float = 300.0,
             at: Optional[float] = None
             ) -> List[Tuple[Dict[str, str], float]]:
        """Per-series counter rate (increase/elapsed) over the trailing
        window, reset-aware: a drop between adjacent points is a counter
        restart, and the post-reset value is the increase since it (the
        Prometheus convention). Series with fewer than two in-window
        points yield nothing — absent, never fabricated."""
        out = []
        for labels, pts in self._windowed(name, match, window_s, at):
            if len(pts) < 2:
                continue
            elapsed = pts[-1].ts - pts[0].ts
            if elapsed <= 0:
                continue
            out.append((labels, _increase(pts) / elapsed))
        return out

    def delta(self, name: str,
              match: Optional[Mapping[str, str]] = None,
              window_s: float = 300.0,
              at: Optional[float] = None
              ) -> List[Tuple[Dict[str, str], float]]:
        """Gauge difference last-first over the window (no reset logic:
        a gauge going down means exactly that)."""
        out = []
        for labels, pts in self._windowed(name, match, window_s, at):
            if len(pts) < 2:
                continue
            out.append((labels, pts[-1].value - pts[0].value))
        return out

    def avg(self, name: str,
            match: Optional[Mapping[str, str]] = None,
            window_s: float = 300.0,
            at: Optional[float] = None
            ) -> List[Tuple[Dict[str, str], float]]:
        """Per-series mean over the window (``avg_over_time``) — the
        smoothing read the scheduler predictor feeds from."""
        out = []
        for labels, pts in self._windowed(name, match, window_s, at):
            if not pts:
                continue
            out.append((labels, sum(p.value for p in pts) / len(pts)))
        return out

    # -- histogram quantile ------------------------------------------------

    def histogram_quantile(self, q: float, base_name: str,
                           match: Optional[Mapping[str, str]] = None,
                           window_s: float = 300.0,
                           at: Optional[float] = None
                           ) -> List[Tuple[Dict[str, str], float]]:
        """Quantile estimate from the cumulative ``<base>_bucket``
        series over the trailing window, grouped by the non-``le``
        labels. Per group: the *increase* of each cumulative bucket over
        the window (reset-aware), then the Prometheus interpolation —
        find the bucket the rank falls in, interpolate linearly inside
        it (from 0 at the first finite bucket); rank in ``+Inf`` clamps
        to the highest finite bound. Groups with zero in-window
        observations yield nothing (absent-never-wrong; the
        single-point case has no increase and stays silent too)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        # group bucket series by their non-le identity
        groups: Dict[_LabelKey, List[Tuple[float, List[Point]]]] = {}
        for labels, pts in self._windowed(f"{base_name}_bucket", match,
                                          window_s, at):
            le = labels.pop("le", None)
            if le is None:
                continue
            bound = float("inf") if le == "+Inf" else float(le)
            groups.setdefault(_label_key(labels), []).append((bound, pts))
        out = []
        for key, buckets in sorted(groups.items()):
            buckets.sort(key=lambda b: b[0])
            increases = []
            for bound, pts in buckets:
                if len(pts) < 2:
                    continue
                increases.append((bound, max(_increase(pts), 0.0)))
            value = _bucket_quantile(q, increases)
            if value is not None:
                out.append((dict(key), value))
        return out


def _increase(pts: Sequence[Point]) -> float:
    """Counter increase across points with reset absorption."""
    total = 0.0
    prev = pts[0].value
    for p in pts[1:]:
        total += p.value if p.value < prev else p.value - prev
        prev = p.value
    return total


def _bucket_quantile(q: float,
                     increases: Sequence[Tuple[float, float]]
                     ) -> Optional[float]:
    """Prometheus ``histogram_quantile`` over (upper bound, in-window
    count) pairs sorted by bound (``+Inf`` last)."""
    if not increases:
        return None
    # cumulative counts are monotone by construction upstream, but each
    # bucket's increase was computed independently — enforce monotone
    cum: List[Tuple[float, float]] = []
    running = 0.0
    for bound, inc in increases:
        running = max(running, inc)
        cum.append((bound, running))
    total = cum[-1][1]
    if total <= 0:
        return None
    if not math.isinf(cum[-1][0]):
        # a histogram exposition always carries +Inf; partial windows
        # may have dropped it — treat the last bound as the ceiling
        cum.append((float("inf"), total))
    rank = q * total
    highest_finite = None
    for bound, _c in cum:
        if not math.isinf(bound):
            highest_finite = bound
    if highest_finite is None:
        return None  # only +Inf observed: no finite estimate exists
    prev_bound, prev_cum = 0.0, 0.0
    for bound, c in cum:
        if c >= rank:
            if math.isinf(bound):
                # the rank lives in +Inf: the estimate clamps to the
                # highest finite bound (Prometheus behavior)
                return highest_finite
            if c == prev_cum:
                return bound
            return prev_bound + (bound - prev_bound) * \
                (rank - prev_cum) / (c - prev_cum)
        prev_bound, prev_cum = bound, c
    return highest_finite


# -- the one query surface ---------------------------------------------------

QUERY_FUNCS = ("instant", "rate", "delta", "avg", "quantile")


def evaluate(store: TimeSeriesStore, func: str, metric: str, *,
             match: Optional[Mapping[str, str]] = None,
             window_s: float = 300.0, q: float = 0.99,
             at: Optional[float] = None
             ) -> List[Tuple[Dict[str, str], float]]:
    """One evaluation path for the alert engine and the dashboard's
    ``/api/metrics/query`` — an alert firing and the panel drawing it
    can never disagree about what the expression means."""
    if func == "instant":
        return [(labels, p.value)
                for labels, p in store.latest(metric, match, at)]
    if func == "rate":
        return store.rate(metric, match, window_s, at)
    if func == "delta":
        return store.delta(metric, match, window_s, at)
    if func == "avg":
        return store.avg(metric, match, window_s, at)
    if func == "quantile":
        return store.histogram_quantile(q, metric, match, window_s, at)
    raise ValueError(f"unknown query func {func!r}; "
                     f"known: {', '.join(QUERY_FUNCS)}")
