"""Request-lifecycle ledger: every serving request's wall clock, attributed.

The serving twin of :mod:`kubeflow_tpu.obs.goodput` (which carves a
TpuJob's life into exclusive states) and of
:class:`kubeflow_tpu.obs.steps.FlightRecorder` (which keeps the last N
training steps in a bounded ring): :class:`RequestLedger` carves each
request's wall clock — from edge admission (or engine submit, when no
edge is in front) to last token — into an exclusive, exhaustive phase
set, and keeps the last N folded records per model in a bounded ring.

**Phases** (:data:`PHASES`):

- ``queue_wait``   — submitted, waiting for an engine slot
- ``admission``    — edge classify/gate work, slot placement, page
  reservation, batch assembly (everything between queue and prefill)
- ``prefill``      — prompt prefill (chunk count recorded for the
  paged engine's chunked-prefill scheduler)
- ``decode``       — first token to last token; per-token emit
  timestamps are recorded, so inter-token latency is derivable
- ``kv_fault``     — paged-pool page-growth stalls carved out of decode
- ``weight_fault`` — multiplex cold-start (weight paging) stalls
- ``stream_stall`` — the client not draining the stream (carved out of
  decode by the streaming writer)
- ``shed``         — the edge's 503 path (the request's whole life is
  admission + shed; it never reaches an engine)

**Measurement discipline** (the goodput invariant, at request
granularity): a finished record's phase intervals tile
``[t_start, t_end]`` EXACTLY — no gaps, no overlaps, seconds sum to the
wall clock. Base phases come from transition marks the serving hot
paths already take timestamps for; ``kv_fault``/``weight_fault``/
``stream_stall`` are *carve-outs*: recorded as stall windows and
subtracted from whatever base phase they overlap at fold time.

**Hot-path contract**: :meth:`RequestLedger.emit` is called once per
token from ``DecodeEngine._emit`` and takes the timestamp the engine
already read for the decode step — the ledger itself never reads a
clock on the emit path (one dict lookup + one list append under the
lock). Folding, histogram observation, and ring insertion all happen
once, at :meth:`finish`.

**Exports** (all labeled ``{model, slo_class}``; registered exactly
once here — the TPU013 metric contract):

- ``kftpu_request_ttft_ms``            — time to first token
- ``kftpu_request_itl_ms``             — inter-token latency (one
  observation per token gap)
- ``kftpu_request_phase_seconds{phase}`` — per-phase wall seconds
- ``kftpu_request_finished_total``     — finished records
- ``kftpu_request_ttft_breach_total``  — finished with TTFT over the
  class target (or no first token at all — shed and failed requests
  burn the budget too); numerator of the ``ttft-slo-burn`` rules

Records join across tiers by trace id: the edge starts the record
under the request's trace, injects the traceparent into the backend
hop, and the engine's ``submit`` (which captures the propagated
context) continues the SAME record — in-process, one request is one
record and one trace tree from edge admission to last token. Across
process boundaries each tier's ledger holds its own partial record;
the trace tree still joins in the collector.

docs/OBSERVABILITY.md "Request lifecycle".
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from kubeflow_tpu.utils.metrics import DEFAULT_REGISTRY

# -- phase taxonomy ----------------------------------------------------------

QUEUE_WAIT = "queue_wait"
ADMISSION = "admission"
PREFILL = "prefill"
DECODE = "decode"
KV_FAULT = "kv_fault"
WEIGHT_FAULT = "weight_fault"
STREAM_STALL = "stream_stall"
SHED = "shed"

#: base phases — set by transition marks, in whatever order the tiers
#: visit them (an edge-fronted request goes admission -> queue_wait ->
#: admission -> prefill -> decode; phases may repeat and their seconds
#: accumulate)
BASE_PHASES = (QUEUE_WAIT, ADMISSION, PREFILL, DECODE, SHED)

#: carve-out phases — recorded as stall windows, subtracted from the
#: base phase they overlap at fold time
STALL_PHASES = (KV_FAULT, WEIGHT_FAULT, STREAM_STALL)

#: the exclusive, exhaustive phase set every record's seconds map over
PHASES = BASE_PHASES + STALL_PHASES

#: unlabeled traffic (an engine driven without an edge in front)
NO_SLO_CLASS = "none"

#: per-class TTFT targets (ms) the ``ttft-slo-burn`` rules and the
#: breach counter price against; keys match the edge's
#: ``DEFAULT_SLO_CLASSES`` (defined here, not imported — obs must not
#: depend on the edge tier)
TTFT_TARGETS_MS: Dict[str, float] = {
    "interactive": 500.0,
    "standard": 2000.0,
    "batch": 10000.0,
}
DEFAULT_TTFT_TARGET_MS = 2000.0

#: bounded per-model ring capacity (the FlightRecorder stance: recent
#: evidence, bounded memory)
DEFAULT_RING_CAPACITY = 256

#: live (unfinished) record bound — an edge whose backend hop crosses a
#: process boundary starts records its own process never finishes;
#: oldest-first eviction keeps the map from growing forever
DEFAULT_MAX_LIVE = 4096

# ms-scale buckets: TTFT spans "one prefill" (tens of ms on-chip) to
# "queued behind a burst" (tens of seconds); ITL is per decode step
TTFT_MS_BUCKETS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                   500.0, 1000.0, 2000.0, 5000.0, 10000.0, 30000.0)
ITL_MS_BUCKETS = (0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                  500.0, 1000.0)
PHASE_SECONDS_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                         10.0, 30.0, 60.0, 300.0)

_ttft_h = DEFAULT_REGISTRY.histogram(
    "kftpu_request_ttft_ms",
    "Time to first token per request (ms)", buckets=TTFT_MS_BUCKETS)
_itl_h = DEFAULT_REGISTRY.histogram(
    "kftpu_request_itl_ms",
    "Inter-token latency per decode-token gap (ms)",
    buckets=ITL_MS_BUCKETS)
_phase_h = DEFAULT_REGISTRY.histogram(
    "kftpu_request_phase_seconds",
    "Per-request wall seconds attributed to one lifecycle phase",
    buckets=PHASE_SECONDS_BUCKETS)
_finished_c = DEFAULT_REGISTRY.counter(
    "kftpu_request_finished_total",
    "Requests whose lifecycle record folded (served, shed, or failed)")
_breach_c = DEFAULT_REGISTRY.counter(
    "kftpu_request_ttft_breach_total",
    "Requests finishing over their SLO class's TTFT target (or "
    "without a first token at all)")


# -- records -----------------------------------------------------------------


@dataclasses.dataclass
class _LiveRequest:
    """One in-flight request's raw evidence (pre-fold)."""

    rid: str
    model: str
    slo_class: str
    t_start: float
    # transition marks, monotone by construction (mark() clamps): the
    # interval [marks[i].t, marks[i+1].t) carries marks[i]'s phase
    marks: List[Tuple[float, str]]
    stalls: List[Tuple[float, float, str]] = dataclasses.field(
        default_factory=list)
    emits: List[float] = dataclasses.field(default_factory=list)
    chunks: int = 0

    @property
    def last_t(self) -> float:
        return self.marks[-1][0]


@dataclasses.dataclass
class RequestRecord:
    """One finished request, folded: intervals tile [t_start, t_end]."""

    rid: str
    model: str
    slo_class: str
    t_start: float
    t_end: float
    intervals: List[Tuple[float, float, str]]
    seconds: Dict[str, float]
    emits: List[float]
    chunks: int
    ttft_ms: Optional[float]
    itl_ms: List[float]
    shed: bool
    breach: bool

    @property
    def wall_s(self) -> float:
        return self.t_end - self.t_start

    @property
    def tokens(self) -> int:
        return len(self.emits)

    @property
    def t_first_token(self) -> Optional[float]:
        return self.emits[0] if self.emits else None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rid": self.rid,
            "model": self.model,
            "sloClass": self.slo_class,
            "start": self.t_start,
            "end": self.t_end,
            "wallSeconds": round(self.wall_s, 9),
            "seconds": {p: round(s, 9) for p, s in
                        sorted(self.seconds.items())},
            "intervals": [
                {"phase": p, "start": a, "end": b,
                 "seconds": round(b - a, 9)}
                for a, b, p in self.intervals],
            "tokens": self.tokens,
            "chunks": self.chunks,
            "ttftMs": self.ttft_ms,
            "itlMs": [round(v, 6) for v in self.itl_ms],
            "shed": self.shed,
            "breach": self.breach,
        }


def _clip_merge_stalls(stalls: List[Tuple[float, float, str]],
                       t0: float, t1: float
                       ) -> List[Tuple[float, float, str]]:
    """Clip stall windows to [t0, t1], order them, and resolve overlaps
    (earlier-started stall wins the contested span) so the carve set is
    itself disjoint — a precondition for exact tiling."""
    out: List[Tuple[float, float, str]] = []
    for a, b, phase in sorted(stalls):
        a, b = max(a, t0), min(b, t1)
        if out:
            a = max(a, out[-1][1])  # truncate against the previous stall
        if b > a:
            out.append((a, b, phase))
    return out


def fold_record(live: _LiveRequest, t_end: float) -> RequestRecord:
    """Fold raw marks + stalls + emits into a tiling interval set.

    Base intervals come from consecutive transition marks (the last
    mark's phase runs to ``t_end``); each disjoint stall window splits
    whatever base interval(s) it overlaps. The result tiles
    ``[t_start, t_end]`` exactly: interval bounds are reused verbatim
    (never re-derived through arithmetic), so there are no gaps, no
    overlaps, and seconds sum to the wall clock to float precision.
    """
    t0 = live.t_start
    t_end = max(t_end, live.last_t, live.emits[-1] if live.emits else t0)
    # base edges: mark times + the terminal edge, zero-length runs kept
    # out (a mark at the same instant as its predecessor replaces
    # nothing — the later phase simply starts there)
    base: List[Tuple[float, float, str]] = []
    for i, (t, phase) in enumerate(live.marks):
        nxt = (live.marks[i + 1][0] if i + 1 < len(live.marks)
               else t_end)
        if nxt > t:
            base.append((t, nxt, phase))
    stalls = _clip_merge_stalls(live.stalls, t0, t_end)
    intervals: List[Tuple[float, float, str]] = []
    si = 0
    for a, b, phase in base:
        cur = a
        while si < len(stalls) and stalls[si][0] < b:
            sa, sb, sphase = stalls[si]
            if sb <= cur:
                si += 1
                continue
            sa = max(sa, cur)
            if sa > cur:
                intervals.append((cur, sa, phase))
            cut = min(sb, b)
            intervals.append((sa, cut, sphase))
            cur = cut
            if sb <= b:
                si += 1
            else:
                # the stall outlives this base interval: keep it for
                # the next one (its consumed head is tracked by cur)
                stalls[si] = (cut, sb, sphase)
                break
        if cur < b:
            intervals.append((cur, b, phase))
    # merge adjacent same-phase pieces (contiguity preserved: the merge
    # only ever joins intervals sharing an edge)
    merged: List[Tuple[float, float, str]] = []
    for iv in intervals:
        if merged and merged[-1][2] == iv[2] and merged[-1][1] == iv[0]:
            merged[-1] = (merged[-1][0], iv[1], iv[2])
        else:
            merged.append(iv)
    intervals = merged
    seconds: Dict[str, float] = {}
    for a, b, phase in intervals:
        seconds[phase] = seconds.get(phase, 0.0) + (b - a)
    ttft_ms = ((live.emits[0] - t0) * 1000.0 if live.emits else None)
    itl_ms = [(b - a) * 1000.0
              for a, b in zip(live.emits, live.emits[1:])]
    shed = any(p == SHED for _t, p in live.marks)
    target = TTFT_TARGETS_MS.get(live.slo_class, DEFAULT_TTFT_TARGET_MS)
    breach = ttft_ms is None or ttft_ms > target
    return RequestRecord(
        rid=live.rid, model=live.model, slo_class=live.slo_class,
        t_start=t0, t_end=t_end, intervals=intervals, seconds=seconds,
        emits=list(live.emits), chunks=live.chunks, ttft_ms=ttft_ms,
        itl_ms=itl_ms, shed=shed, breach=breach)


# -- the ledger --------------------------------------------------------------


class RequestLedger:
    """Thread-safe request-lifecycle recorder + bounded flight rings.

    One module-level :data:`DEFAULT_LEDGER` serves the common case
    (edge, engines, and multiplexer in one process join records by
    trace id through it — the :data:`~kubeflow_tpu.obs.trace
    .DEFAULT_COLLECTOR` pattern); components take an injectable
    instance for fake-clock tests.

    Unknown/finished rids are DROPPED silently by every mutator except
    :meth:`start` — a late stall from a stream writer, or an emit
    replayed after cache recovery closed the record, must never corrupt
    another request's evidence or raise on a hot path.
    """

    def __init__(self, *, capacity: int = DEFAULT_RING_CAPACITY,
                 max_live: int = DEFAULT_MAX_LIVE) -> None:
        self.capacity = int(capacity)
        self.max_live = int(max_live)
        self._live: "Dict[str, _LiveRequest]" = {}
        # per-model bounded rings of folded records (FlightRecorder
        # twin): dict-ordered oldest-first, trimmed on append
        self._done: Dict[str, List[RequestRecord]] = {}
        self._lock = threading.Lock()
        self.started_total = 0
        self.finished_total = 0
        self.dropped_live = 0  # live evictions (records nobody finished)

    # -- write path --------------------------------------------------------

    def start(self, rid: Optional[str], *, t: float, model: str = "",
              slo_class: str = "", phase: str = QUEUE_WAIT) -> None:
        """Open (or join) the record for ``rid`` at ``t``.

        Idempotent by design: the edge starts the record, then the
        engine's ``submit`` calls start() again for the same trace —
        the second call only back-fills ``model``/``slo_class`` it
        didn't know. ``rid=None`` (no trace context and no synthetic
        id) is a no-op."""
        if not rid:
            return
        with self._lock:
            live = self._live.get(rid)
            if live is not None:
                if model and not live.model:
                    live.model = model
                if slo_class and not live.slo_class:
                    live.slo_class = slo_class
                return
            self.started_total += 1
            self._live[rid] = _LiveRequest(
                rid=rid, model=model, slo_class=slo_class, t_start=t,
                marks=[(t, phase)])
            while len(self._live) > self.max_live:
                # oldest-first eviction: insertion-ordered dict
                self._live.pop(next(iter(self._live)))
                self.dropped_live += 1

    def annotate(self, rid: Optional[str], *, model: str = "",
                 slo_class: str = "") -> None:
        """Back-fill labels on a live record (drop if unknown)."""
        if not rid:
            return
        with self._lock:
            live = self._live.get(rid)
            if live is None:
                return
            if model:
                live.model = model
            if slo_class:
                live.slo_class = slo_class

    def mark(self, rid: Optional[str], phase: str, t: float) -> None:
        """Transition the record's base phase at ``t`` (clamped to be
        monotone against earlier marks)."""
        if not rid:
            return
        with self._lock:
            live = self._live.get(rid)
            if live is None:
                return
            live.marks.append((max(t, live.last_t), phase))

    def stall(self, rid: Optional[str], phase: str, t0: float,
              t1: float) -> None:
        """Record a carve-out window (kv_fault / weight_fault /
        stream_stall); clipped to the record's life at fold time."""
        if not rid or t1 <= t0:
            return
        with self._lock:
            live = self._live.get(rid)
            if live is None:
                return
            live.stalls.append((t0, t1, phase))

    def emit(self, rid: Optional[str], t: float) -> None:
        """One token emitted at ``t`` — the engine-emit hot path.

        ``t`` is the timestamp the engine ALREADY read for the decode
        step (run_once reads the clock once per step, not per token);
        the ledger never reads a clock here. The first emit is the
        first token: it also transitions the base phase to ``decode``,
        so TTFT and the decode interval share one timestamp."""
        if not rid:
            return
        with self._lock:
            live = self._live.get(rid)
            if live is None:
                return
            if not live.emits:
                live.marks.append((max(t, live.last_t), DECODE))
            elif t < live.emits[-1]:
                t = live.emits[-1]
            live.emits.append(max(t, live.t_start))

    def note_chunk(self, rid: Optional[str]) -> None:
        """Count one prefill chunk (the chunked-prefill scheduler)."""
        if not rid:
            return
        with self._lock:
            live = self._live.get(rid)
            if live is not None:
                live.chunks += 1

    def finish(self, rid: Optional[str],
               t: float) -> Optional[RequestRecord]:
        """Close the record at ``t``: fold, observe the histograms +
        counters (exemplared by the request's trace), and push the
        folded record into the model's bounded ring. Idempotent —
        finishing an unknown/already-finished rid returns None."""
        if not rid:
            return None
        with self._lock:
            live = self._live.pop(rid, None)
        if live is None:
            return None
        rec = fold_record(live, t)
        model = rec.model or "unknown"
        slo = rec.slo_class or NO_SLO_CLASS
        if rec.ttft_ms is not None:
            _ttft_h.observe(rec.ttft_ms, exemplar_trace_id=rec.rid,
                            model=model, slo_class=slo)
        for gap in rec.itl_ms:
            _itl_h.observe(gap, exemplar_trace_id=rec.rid, model=model,
                           slo_class=slo)
        for phase, s in rec.seconds.items():
            _phase_h.observe(s, exemplar_trace_id=rec.rid, model=model,
                             slo_class=slo, phase=phase)
        _finished_c.inc(model=model, slo_class=slo)
        if rec.breach:
            _breach_c.inc(model=model, slo_class=slo)
        with self._lock:
            self.finished_total += 1
            ring = self._done.setdefault(model, [])
            ring.append(rec)
            if len(ring) > self.capacity:
                del ring[:len(ring) - self.capacity]
        return rec

    def shed(self, rid: Optional[str], *, t_start: float, t_shed: float,
             t_end: float, model: str = "",
             slo_class: str = "") -> Optional[RequestRecord]:
        """Convenience for the edge's 503 path: one call records the
        whole (short) life of a shed request — admission from
        ``t_start``, shed from ``t_shed``, closed at ``t_end``."""
        self.start(rid, t=t_start, model=model, slo_class=slo_class,
                   phase=ADMISSION)
        self.mark(rid, SHED, t_shed)
        return self.finish(rid, t_end)

    def clear(self) -> None:
        with self._lock:
            self._live.clear()
            self._done.clear()

    # -- read path ---------------------------------------------------------

    def live_count(self) -> int:
        with self._lock:
            return len(self._live)

    def ttft_ms(self, rid: Optional[str]) -> Optional[float]:
        """TTFT for a live OR finished record (bench reads the wave's
        TTFT before the streams drain)."""
        if not rid:
            return None
        with self._lock:
            live = self._live.get(rid)
            if live is not None:
                return ((live.emits[0] - live.t_start) * 1000.0
                        if live.emits else None)
            for ring in self._done.values():
                for rec in reversed(ring):
                    if rec.rid == rid:
                        return rec.ttft_ms
        return None

    def records(self, model: Optional[str] = None
                ) -> List[RequestRecord]:
        """Finished records oldest-first (one model, or all)."""
        with self._lock:
            if model is not None:
                return list(self._done.get(model, ()))
            return [rec for m in sorted(self._done)
                    for rec in self._done[m]]

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._done)

    def worst_ttft(self, model: Optional[str] = None
                   ) -> Optional[RequestRecord]:
        """The finished record with the worst TTFT (requests that never
        produced a token rank worst of all, by wall; ties earliest) —
        the dashboard's tail exemplar."""
        recs = self.records(model)
        worst: Optional[RequestRecord] = None

        def key(r: RequestRecord) -> Tuple[int, float]:
            if r.ttft_ms is None:
                return (1, r.wall_s * 1000.0)
            return (0, r.ttft_ms)

        for rec in recs:
            if worst is None or key(rec) > key(worst):
                worst = rec
        return worst

    def view(self, model: str) -> Dict[str, Any]:
        """One model's phase-breakdown percentiles — the dashboard's
        ``GET /api/models/<model>/requests`` payload body."""
        recs = self.records(model)
        ttfts = [r.ttft_ms for r in recs if r.ttft_ms is not None]
        itls = [g for r in recs for g in r.itl_ms]
        phases: Dict[str, List[float]] = {}
        for r in recs:
            for p, s in r.seconds.items():
                phases.setdefault(p, []).append(s)
        return {
            "model": model,
            "count": len(recs),
            "shed": sum(1 for r in recs if r.shed),
            "breaches": sum(1 for r in recs if r.breach),
            "tokens": sum(r.tokens for r in recs),
            "ttftMs": _percentiles(ttfts),
            "itlMs": _percentiles(itls),
            "phaseSeconds": {p: _percentiles(v, total=True)
                             for p, v in sorted(phases.items())},
        }

    def rollup(self) -> Dict[str, Any]:
        """Fleet rollup across models (``GET /api/metrics/requests``)."""
        models = self.models()
        rows = {m: self.view(m) for m in models}
        all_recs = self.records()
        fleet_phases: Dict[str, float] = {}
        for r in all_recs:
            for p, s in r.seconds.items():
                fleet_phases[p] = fleet_phases.get(p, 0.0) + s
        total = sum(fleet_phases.values())
        return {
            "models": rows,
            "fleet": {
                "count": len(all_recs),
                "shed": sum(1 for r in all_recs if r.shed),
                "breaches": sum(1 for r in all_recs if r.breach),
                "tokens": sum(r.tokens for r in all_recs),
                "phaseSeconds": {p: round(s, 9) for p, s in
                                 sorted(fleet_phases.items())},
                "phaseFractions": {
                    p: round(s / total, 6) for p, s in
                    sorted(fleet_phases.items())} if total > 0 else {},
                "ttftMs": _percentiles(
                    [r.ttft_ms for r in all_recs
                     if r.ttft_ms is not None]),
            },
            "liveRequests": self.live_count(),
            "droppedLive": self.dropped_live,
        }

    def bench_block(self, model: Optional[str] = None) -> Dict[str, Any]:
        """The bench artifact's ``requests`` block: the run's request
        distribution, from the same ledger production reads."""
        recs = self.records(model)
        ttfts = [r.ttft_ms for r in recs if r.ttft_ms is not None]
        itls = [g for r in recs for g in r.itl_ms]
        phases: Dict[str, float] = {}
        for r in recs:
            for p, s in r.seconds.items():
                phases[p] = phases.get(p, 0.0) + s
        return {
            "count": len(recs),
            "tokens": sum(r.tokens for r in recs),
            "chunks": sum(r.chunks for r in recs),
            "ttft_ms": _percentiles(ttfts),
            "itl_ms": _percentiles(itls),
            "phase_seconds": {p: round(s, 6) for p, s in
                              sorted(phases.items())},
        }


def _percentiles(values: Iterable[float], *,
                 total: bool = False) -> Dict[str, float]:
    vals = sorted(values)
    if not vals:
        return {}
    def q(p: float) -> float:
        # nearest-rank on the sorted sample — stable for tiny n
        i = min(len(vals) - 1, max(0, round(p * (len(vals) - 1))))
        return round(vals[int(i)], 6)
    out = {"p50": q(0.50), "p90": q(0.90), "p99": q(0.99),
           "max": round(vals[-1], 6), "count": len(vals)}
    if total:
        out["total"] = round(sum(vals), 6)
    return out


def synthetic_rid() -> str:
    """A 32-hex request id for requests with no propagated trace (the
    bench driver, direct engine callers) — same shape as a trace id so
    ledger keys stay uniform; not derived from any clock."""
    return os.urandom(16).hex()


def check_tiling(rec: RequestRecord, *, tol: float = 1e-9) -> None:
    """Assert the goodput invariant at request granularity: intervals
    tile [t_start, t_end] exactly (no gaps, no overlaps) and seconds
    sum to the wall clock. Raises AssertionError — test/smoke helper."""
    ivs = rec.intervals
    if rec.t_end == rec.t_start:
        assert not ivs or sum(b - a for a, b, _ in ivs) == 0.0
        return
    assert ivs, f"no intervals for wall {rec.wall_s}"
    assert ivs[0][0] == rec.t_start, (ivs[0], rec.t_start)
    assert ivs[-1][1] == rec.t_end, (ivs[-1], rec.t_end)
    for (a0, b0, _p0), (a1, _b1, _p1) in zip(ivs, ivs[1:]):
        assert b0 == a1, f"gap/overlap at {b0} vs {a1}"
        assert b0 > a0
    assert abs(sum(rec.seconds.values()) - rec.wall_s) <= tol, (
        rec.seconds, rec.wall_s)
    assert set(rec.seconds) <= set(PHASES), rec.seconds


#: process-wide ledger: edge, engines, and the multiplexer in one
#: process join per-request records through it (the DEFAULT_COLLECTOR
#: pattern); tests inject fresh instances
DEFAULT_LEDGER = RequestLedger()
