"""Span exporters: Chrome ``trace_event`` JSON and an OTLP-ish ndjson.

Two formats, two audiences:

- :func:`chrome_trace` renders a collector snapshot as the Trace Event
  Format that ``chrome://tracing`` / Perfetto load directly — the same
  viewer the XLA profiler's own dumps open in, so a platform trace and
  a device trace are inspected with one tool.
- :func:`otlp_lines` / :func:`parse_otlp_lines` round-trip spans as
  newline-delimited JSON in OTLP field names (``traceId``/``spanId``/
  ``startTimeUnixNano``) — greppable on disk, and close enough to OTLP
  that a real collector adapter is a field-rename away.

:func:`push_spans` ships a batch to the ``trace-collector`` service's
ingest endpoint (JSON body — the ndjson shape is the *file* format).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional

from kubeflow_tpu.obs.trace import Span

# the trace-collector component's Service + ingest route; tpulint TPU004
# cross-checks host/port against manifests/components/trace_collector.py
# DEFAULTS and the path against the routes obs/service.py serves
DEFAULT_COLLECTOR_URL = "http://trace-collector:8095/api/traces:ingest"
ENV_COLLECTOR_URL = "KFTPU_TRACE_COLLECTOR_URL"


def chrome_trace(spans: Iterable[Span]) -> Dict[str, Any]:
    """Complete-event (``ph: "X"``) trace; one tid per trace_id so
    concurrent requests stack on separate tracks."""
    tids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for s in spans:
        tid = tids.setdefault(s.trace_id, len(tids) + 1)
        events.append({
            "ph": "X",
            "name": s.name,
            "cat": "kftpu",
            "pid": 1,
            "tid": tid,
            "ts": round(s.start * 1e6, 3),
            "dur": round(s.duration * 1e6, 3),
            "args": {**s.attrs,
                     "trace_id": s.trace_id,
                     "span_id": s.span_id,
                     "parent_id": s.parent_id or "",
                     "status": s.status},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _span_record(s: Span) -> Dict[str, Any]:
    return {
        "traceId": s.trace_id,
        "spanId": s.span_id,
        "parentSpanId": s.parent_id or "",
        "name": s.name,
        "startTimeUnixNano": int(s.start * 1e9),
        "endTimeUnixNano": int((s.end if s.end is not None
                                else s.start) * 1e9),
        "attributes": dict(s.attrs),
        "status": s.status,
    }


def otlp_lines(spans: Iterable[Span]) -> str:
    """Newline-delimited OTLP-ish dump; one span per line."""
    return "".join(json.dumps(_span_record(s), sort_keys=True) + "\n"
                   for s in spans)


def span_from_record(rec: Dict[str, Any]) -> Span:
    return Span(
        trace_id=str(rec["traceId"]),
        span_id=str(rec["spanId"]),
        parent_id=str(rec.get("parentSpanId") or "") or None,
        name=str(rec.get("name", "")),
        start=float(rec["startTimeUnixNano"]) / 1e9,
        end=float(rec["endTimeUnixNano"]) / 1e9,
        attrs=dict(rec.get("attributes") or {}),
        status=str(rec.get("status", "OK")),
    )


def parse_otlp_lines(text: str) -> List[Span]:
    """Inverse of :func:`otlp_lines`; blank/garbage lines are skipped
    (a truncated dump must still load its intact prefix)."""
    out: List[Span] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            out.append(span_from_record(json.loads(line)))
        except (ValueError, KeyError, TypeError):
            continue
    return out


def push_spans(spans: Iterable[Span], url: Optional[str] = None,
               timeout: float = 5.0) -> bool:
    """POST a span batch to the trace-collector ingest endpoint.

    Best-effort by contract: telemetry shipping must never fail the
    workload, so any transport error returns False."""
    import os
    import urllib.request

    url = url or os.environ.get(ENV_COLLECTOR_URL) or DEFAULT_COLLECTOR_URL
    body = json.dumps(
        {"spans": [_span_record(s) for s in spans]}).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return 200 <= resp.status < 300
    except OSError:
        return False
