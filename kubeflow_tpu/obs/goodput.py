"""Goodput/badput accounting: the per-job wall-clock ledger.

Twelve PRs of instrumentation can say *what happened* (spans, step
telemetry, a tsdb, alerting) and two of them *cause* downtime on
purpose (checkpoint-preempt-requeue, elastic snapshot→teardown→reshard)
— but nothing measured whether those trades pay. This module is the
denominator the ROADMAP's north star ("as fast as the hardware allows")
needs: every second of a TpuJob's life attributed to exactly one state
of an exclusive, exhaustive set, derived ONLY from signals the platform
already emits — never from new worker-side clocks.

The state set (:data:`STATES`):

========================  ====================================================
``queue_wait``            no pods; admitted/blocked in the scheduler queue or
                          held Unschedulable (the queue's admit→place spans
                          are the trace-side twin of these intervals)
``startup_compile``       pods up, no step completed yet on a fresh run (the
                          first-program XLA compile window)
``productive_step``       the gang's beacon step advanced — the ONLY goodput
                          state; everything else is badput
``checkpoint_save``       worker snapshot wall time, carved from the
                          ``kftpu_checkpoint_save_seconds`` histogram the
                          :class:`~kubeflow_tpu.elastic.snapshot.
                          ElasticSnapshotter` observes
``restore``               pods up after a preemption/resize re-gang, beacon
                          step still at/behind the checkpoint it resumes from
``preempted``             torn down for a higher-priority gang; covers the
                          whole teardown→requeue→re-place gap
``resizing``              elastic resize in flight (nudge, teardown, re-gang)
``straggler_stall``       gang running but a straggler is flagged — throughput
                          is gated by the lagging worker
``recompile``             the gang's recompile count grew during the window
``unattributed``          running, steps not advancing, no better explanation
========================  ====================================================

The TpuJob operator folds one observation per reconcile into CR
``status.goodput`` (:func:`fold`). The fold is **idempotent under
reconcile replay**: an observation at or before ``asOf`` is a no-op, so
replaying the same fake-clock reconcile sequence — or crash-restarting
the operator mid-resize (all ledger state lives in the CR) — produces
byte-identical status. Intervals tile ``[start, asOf]`` exactly: no
overlaps, no gaps, and ``sum(seconds) == asOf - start`` by
construction. Attribution is observation-lagged by at most one
reconcile (the window since the last fold is attributed to the state
observed *now*); reconciles are seconds apart, the intervals that
matter are minutes.

Exported series (docs/OBSERVABILITY.md "Goodput"):

- ``kftpu_job_goodput_seconds_total{namespace,job,state}`` — per-job
  counter, so the PR 9 tsdb answers ``goodput_fraction =
  rate(productive)/rate(all)`` over any window;
- ``kftpu_fleet_chip_seconds_total`` / ``kftpu_fleet_badput_chip_
  seconds_total`` — chips-weighted fleet counters (one idle 256-chip
  gang outweighs fifty busy singles), the ``job-badput-burn``
  :class:`~kubeflow_tpu.obs.alerts.BurnRateRule`'s numerator and
  denominator — badput *is* an error budget;
- ``kftpu_checkpoint_save_seconds{source,...}`` — save wall-time
  histogram (``source="worker"`` = the actual snapshot,
  ``source="operator"`` = the ensure/read on the control-plane side);
  the measurement ROADMAP item 4's snapshot-deadline question needs.

Surfaces: dashboard ``GET /api/jobs/<ns>/<name>/goodput`` (interval
timeline + fractions + the worst badput interval's trace exemplar) and
``GET /api/metrics/goodput`` (the chips×seconds fleet rollup), the
``goodput.fraction`` summary on the job-telemetry route, and the bench
artifact's ``goodput`` block (:func:`from_step_records`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from kubeflow_tpu.utils import DEFAULT_REGISTRY

# -- the state set -----------------------------------------------------------

QUEUE_WAIT = "queue_wait"
STARTUP_COMPILE = "startup_compile"
PRODUCTIVE = "productive_step"
CHECKPOINT_SAVE = "checkpoint_save"
RESTORE = "restore"
PREEMPTED = "preempted"
RESIZING = "resizing"
STRAGGLER_STALL = "straggler_stall"
RECOMPILE = "recompile"
UNATTRIBUTED = "unattributed"

STATES: Tuple[str, ...] = (
    QUEUE_WAIT, STARTUP_COMPILE, PRODUCTIVE, CHECKPOINT_SAVE, RESTORE,
    PREEMPTED, RESIZING, STRAGGLER_STALL, RECOMPILE, UNATTRIBUTED,
)
BADPUT_STATES: Tuple[str, ...] = tuple(s for s in STATES
                                       if s != PRODUCTIVE)

# the interval timeline is display/debugging; totals live in "seconds"
# and survive trimming, so a week-long job cannot grow its CR unbounded
MAX_INTERVALS = 256

# -- exported series ---------------------------------------------------------

_job_seconds_c = DEFAULT_REGISTRY.counter(
    "kftpu_job_goodput_seconds_total",
    "per-job wall-clock seconds attributed by the goodput ledger, "
    "by state")
_fleet_chip_seconds_c = DEFAULT_REGISTRY.counter(
    "kftpu_fleet_chip_seconds_total",
    "chip-weighted wall-clock seconds across every ledgered TpuJob")
_fleet_badput_c = DEFAULT_REGISTRY.counter(
    "kftpu_fleet_badput_chip_seconds_total",
    "chip-weighted NON-productive seconds across every ledgered TpuJob")

CKPT_SAVE_BUCKETS = (0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 300.0, 900.0)
_ckpt_save_h = DEFAULT_REGISTRY.histogram(
    "kftpu_checkpoint_save_seconds",
    "checkpoint save wall time (source=worker: the snapshot itself; "
    "source=operator: the control-plane ensure/read)",
    buckets=CKPT_SAVE_BUCKETS)


def observe_checkpoint_save(seconds: float, *, namespace: str = "",
                            job: str = "",
                            source: str = "worker") -> None:
    """Record one save's wall time. Job identity labels the series the
    ledger carves ``checkpoint_save`` from; an unlabeled observation
    (no job context) still lands in the fleet histogram."""
    labels = {"source": source}
    if job:
        labels.update({"namespace": namespace, "job": job})
    _ckpt_save_h.observe(max(float(seconds), 0.0), **labels)


def checkpoint_save_seconds(namespace: str, job: str,
                            source: str = "worker") -> float:
    """Cumulative worker save seconds for one job, from the in-process
    registry (the all-in-one-process tier; a deployed operator reads
    the scraped ``_sum`` series through the tsdb instead)."""
    return _ckpt_save_h.sum(namespace=namespace, job=job, source=source)


# -- observation signals -----------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GoodputSignals:
    """One reconcile's observation — everything already emitted
    elsewhere (CR conditions, queue state, beacon telemetry, the save
    histogram); the ledger adds no clock of its own."""

    now: float
    has_pods: bool = False
    resize_requested: bool = False      # status.resize.requested
    preemption_requested: bool = False  # status.preemption.requested
    preemptions: int = 0                # status.preemption.count
    last_step: int = 0                  # telemetry lastStep (gang max)
    recompiles: int = 0                 # telemetry gang total
    stragglers: bool = False            # telemetry straggler flags
    restore_step: Optional[int] = None  # most recent lastCheckpointStep
    ckpt_save_seconds: float = 0.0      # cumulative worker save seconds
    # cumulative event-sourced XLA compile seconds (the xprof ledger's
    # kftpu_compile_seconds sum). None = no ground-truth source for
    # this job — compile states stay beacon-INFERRED; a float (even
    # 0.0) means measured, and inference is suppressed in its favor
    compile_seconds: Optional[float] = None


def _coarse(markers: Mapping[str, Any], s: GoodputSignals) -> str:
    """The window's exclusive state, before the checkpoint-save carve."""
    if not s.has_pods:
        if s.resize_requested:
            return RESIZING             # snapshot→teardown→re-gang gap
        if int(s.preemptions) > int(markers.get("preemptions", 0)):
            # evicted and not yet re-placed: the whole requeue wait is
            # the preemption's cost, not generic queue time
            return PREEMPTED
        return QUEUE_WAIT
    if s.resize_requested:
        return RESIZING                 # nudge window: live gang saving
    return _running(markers, s)


def _running(markers: Mapping[str, Any], s: GoodputSignals) -> str:
    # a ground-truth compile source (the xprof ledger) means compile
    # seconds were already carved EXACTLY from the window before this
    # coarse attribution runs — inferring STARTUP_COMPILE/RECOMPILE
    # here on top would double-bill the same seconds, so both
    # inferences yield when measurement exists
    measured = s.compile_seconds is not None
    if int(s.last_step) <= 0:
        return UNATTRIBUTED if measured else STARTUP_COMPILE
    if (s.restore_step is not None
            and int(s.last_step) <= int(s.restore_step)):
        # re-ganged after a preemption/resize and the beacons have not
        # passed the checkpoint step yet: restoring into the new
        # topology (telemetry.lastStep survives the teardown, so this
        # reads the STALE pre-teardown step until the resume beacons)
        return RESTORE
    if (not measured
            and int(s.recompiles) > int(markers.get("recompiles", 0))):
        return RECOMPILE
    if s.stragglers:
        return STRAGGLER_STALL
    if int(s.last_step) > int(markers.get("lastStep", 0)):
        return PRODUCTIVE
    return UNATTRIBUTED


# -- the fold ----------------------------------------------------------------


def fold(prev: Optional[Mapping[str, Any]],
         s: GoodputSignals) -> Dict[str, Any]:
    """Fold one observation into the ledger; returns the new
    ``status.goodput`` value (or ``prev`` unchanged on replay).

    The first fold only opens the ledger (``start == asOf``, no
    intervals) and baselines the markers — notably
    ``ckptSaveSeconds``, so a pre-existing histogram sum (operator
    restart, shared-process tests) is never mis-attributed as a save
    that happened inside the first window. Every later fold attributes
    ``(asOf, now]`` exactly once: replays (``now <= asOf``) are
    no-ops, which is the whole idempotence story — all state lives in
    the CR, none in the operator process."""
    now = float(s.now)
    if not prev:
        return {
            "start": now,
            "asOf": now,
            "intervals": [],
            "seconds": {},
            "markers": {
                "lastStep": int(s.last_step),
                "recompiles": int(s.recompiles),
                "preemptions": int(s.preemptions),
                "ckptSaveSeconds": float(s.ckpt_save_seconds),
                "compileSeconds": float(s.compile_seconds or 0.0),
                "hadPods": bool(s.has_pods),
            },
        }
    if now <= float(prev.get("asOf", now)):
        return dict(prev)               # replay: byte-identical
    g: Dict[str, Any] = {
        "start": float(prev["start"]),
        "asOf": float(prev["asOf"]),
        "intervals": [dict(i) for i in prev.get("intervals", [])],
        "seconds": dict(prev.get("seconds", {})),
        "markers": dict(prev.get("markers", {})),
    }
    m = g["markers"]
    window = now - g["asOf"]

    # carve: worker checkpoint-save seconds first (the histogram is the
    # source of truth for how much of the window the snapshot ate; a
    # save longer than one window spills its remainder into the next),
    # then the coarse state for the rest
    carve: List[Tuple[str, float]] = []
    save = 0.0
    save_seen = float(m.get("ckptSaveSeconds", 0.0))
    if s.has_pods:
        observed = float(s.ckpt_save_seconds)
        if observed < save_seen:
            # counter reset: a re-ganged gang's worker processes start
            # fresh histograms, so the scraped _sum drops below the
            # marker — re-baseline (the prometheus rate() stance) or
            # every future save would hide under the old cumulative
            save_seen = observed
        delta = max(observed - save_seen, 0.0)
        save = min(delta, window)

    # carve second: event-sourced compile seconds (the xprof ledger's
    # cumulative total). This is MEASUREMENT, not inference — when the
    # signal is present it is attributed exactly and _running's
    # beacon-gap inference of the compile states stands down
    comp = 0.0
    comp_seen = float(m.get("compileSeconds", 0.0))
    measured = s.compile_seconds is not None
    if s.has_pods and measured:
        observed_c = float(s.compile_seconds)
        if "compileSeconds" not in m:
            # the source appeared mid-life (operator upgrade, ledger
            # attach): baseline without attributing its history —
            # those compiles happened in windows already closed
            comp_seen = observed_c
        if observed_c < comp_seen:
            comp_seen = observed_c  # counter reset: re-ganged workers
        delta_c = max(observed_c - comp_seen, 0.0)
        comp = min(delta_c, window - save)
    state = _coarse(m, s)
    if save > 0:
        carve.append((CHECKPOINT_SAVE, save))
    if comp > 0:
        # before any step the compile IS the startup tax; afterwards
        # it is a recompile eating into productive time
        comp_state = (STARTUP_COMPILE if int(s.last_step) <= 0
                      else RECOMPILE)
        if carve and carve[-1][0] == comp_state:
            carve[-1] = (comp_state, carve[-1][1] + comp)
        else:
            carve.append((comp_state, comp))
    rest = window - save - comp
    if rest > 0:
        if carve and carve[-1][0] == state:
            carve[-1] = (state, carve[-1][1] + rest)
        else:
            carve.append((state, rest))

    t = g["asOf"]
    for st, dur in carve:
        last = g["intervals"][-1] if g["intervals"] else None
        if last is not None and last["state"] == st:
            last["end"] = t + dur       # contiguous same-state: extend
        else:
            g["intervals"].append({"state": st, "start": t,
                                   "end": t + dur})
        g["seconds"][st] = g["seconds"].get(st, 0.0) + dur
        t += dur
    if len(g["intervals"]) > MAX_INTERVALS:
        g["intervals"] = g["intervals"][-MAX_INTERVALS:]
    g["asOf"] = now

    # markers AFTER attribution: every window compares against the
    # PREVIOUS observation
    if s.has_pods and not bool(m.get("hadPods")):
        # a (re-)ganged observation stream starts fresh: beacon
        # counters may legitimately sit BELOW the historical max — a
        # rollback restore re-does steps, and restarted worker
        # processes reset their recompile counters — so tracking the
        # old max here would misattribute all redone progress and
        # mask every post-re-gang recompile
        m["lastStep"] = int(s.last_step)
        m["recompiles"] = int(s.recompiles)
    else:
        m["lastStep"] = max(int(m.get("lastStep", 0)),
                            int(s.last_step))
        m["recompiles"] = max(int(m.get("recompiles", 0)),
                              int(s.recompiles))
    m["hadPods"] = bool(s.has_pods)
    m["ckptSaveSeconds"] = save_seen + save
    if measured:
        # advance only by what was attributed: a compile longer than
        # one window spills its remainder into the next (the
        # checkpoint-save stance)
        m["compileSeconds"] = comp_seen + comp
    if s.has_pods and not s.preemption_requested:
        # re-placed (and no eviction being signaled right now): future
        # no-pod gaps are fresh queue waits, not this preemption's
        # tail — but while the signal is pending, the count must stay
        # ahead of the marker so the coming teardown gap reads
        # ``preempted``
        m["preemptions"] = max(int(m.get("preemptions", 0)),
                               int(s.preemptions))
    return g


# -- derived views -----------------------------------------------------------


def fractions(g: Optional[Mapping[str, Any]]) -> Dict[str, float]:
    """Per-state fraction of attributed wall time; all states present,
    sums to 1.0 whenever any time is attributed (the denominator is
    the attributed total itself, which tiles ``asOf - start``)."""
    secs = (g or {}).get("seconds") or {}
    total = sum(secs.values())
    if total <= 0:
        return {st: 0.0 for st in STATES}
    return {st: secs.get(st, 0.0) / total for st in STATES}


def goodput_fraction(g: Optional[Mapping[str, Any]]) -> float:
    return fractions(g)[PRODUCTIVE]


def worst_badput_interval(g: Optional[Mapping[str, Any]]
                          ) -> Optional[Dict[str, Any]]:
    """The single longest non-productive interval (ties: earliest) —
    the one the dashboard links to a trace exemplar."""
    worst: Optional[Dict[str, Any]] = None
    for iv in (g or {}).get("intervals") or []:
        if iv.get("state") == PRODUCTIVE:
            continue
        dur = float(iv.get("end", 0.0)) - float(iv.get("start", 0.0))
        if dur <= 0:
            continue
        if worst is None or dur > (worst["end"] - worst["start"]):
            worst = {"state": iv["state"], "start": float(iv["start"]),
                     "end": float(iv["end"])}
    return worst


def view(g: Optional[Mapping[str, Any]]) -> Dict[str, Any]:
    """The dashboard/job-route payload: timeline + fractions."""
    g = g or {}
    fr = fractions(g)
    secs = dict(g.get("seconds") or {})
    return {
        "start": g.get("start"),
        "asOf": g.get("asOf"),
        "wallSeconds": round(float(g.get("asOf", 0.0) or 0.0)
                             - float(g.get("start", 0.0) or 0.0), 6),
        "seconds": {st: round(secs[st], 6)
                    for st in STATES if st in secs},
        "fractions": {st: round(fr[st], 6) for st in STATES},
        "goodputFraction": round(fr[PRODUCTIVE], 6),
        "badputFraction": round(sum(fr[st] for st in BADPUT_STATES), 6),
        "intervals": [dict(i) for i in g.get("intervals") or []],
    }


def fleet_rollup(rows: Iterable[Tuple[int, Mapping[str, Any]]]
                 ) -> Dict[str, Any]:
    """chips × seconds weighted rollup across jobs: one idle 256-chip
    gang outweighs fifty busy singles. ``rows`` is ``(chips,
    status.goodput)`` per job."""
    weighted: Dict[str, float] = {}
    n = 0
    for chips, g in rows:
        secs = (g or {}).get("seconds") or {}
        if not secs:
            continue
        n += 1
        for st, v in secs.items():
            weighted[st] = weighted.get(st, 0.0) + float(chips) * v
    total = sum(weighted.values())
    fr = ({st: weighted.get(st, 0.0) / total for st in STATES}
          if total > 0 else {st: 0.0 for st in STATES})
    return {
        "jobs": n,
        "chipSeconds": round(total, 6),
        "chipSecondsByState": {st: round(weighted[st], 6)
                               for st in STATES if st in weighted},
        "fractions": {st: round(fr[st], 6) for st in STATES},
        "goodputFraction": round(fr[PRODUCTIVE], 6),
        "badputFraction": round(sum(fr[st] for st in BADPUT_STATES), 6),
    }


# -- metric export -----------------------------------------------------------


class GoodputExporter:
    """Turns ledger totals into monotone counters.

    Process-local delta cache: a replayed fold changes no totals, so a
    replay exports nothing; a fresh process restarts the counters,
    which the tsdb's reset-aware ``rate()`` absorbs like any other
    counter restart."""

    def __init__(self) -> None:
        self._exported: Dict[Tuple[str, str], Dict[str, float]] = {}

    def export(self, namespace: str, job: str, chips: int,
               g: Optional[Mapping[str, Any]]) -> None:
        secs = (g or {}).get("seconds") or {}
        prev = self._exported.setdefault((namespace, job), {})
        for st, total in secs.items():
            delta = float(total) - prev.get(st, 0.0)
            if delta <= 0:
                continue
            _job_seconds_c.inc(delta, namespace=namespace, job=job,
                               state=st)
            _fleet_chip_seconds_c.inc(delta * max(int(chips), 1))
            if st != PRODUCTIVE:
                _fleet_badput_c.inc(delta * max(int(chips), 1))
            prev[st] = float(total)

    def clear(self, namespace: str, job: str) -> None:
        """Deleted job: its per-job counter rows go with it (the
        per-job gauge staleness discipline); the fleet totals — plain
        unlabeled counters — stay monotone."""
        self._exported.pop((namespace, job), None)
        for st in STATES:
            _job_seconds_c.remove(namespace=namespace, job=job, state=st)


# -- the bench-artifact block ------------------------------------------------


def from_step_records(records: Iterable[Any]) -> Dict[str, Any]:
    """The BENCH artifact's ``goodput`` block, from a
    :class:`~kubeflow_tpu.obs.steps.FlightRecorder` ring: productive
    fraction (OK non-recompile step time over pass wall time) next to
    img/s, so a round that *looks* fast but recompiles or stalls
    between steps reads as the badput it is."""
    recs = list(records)
    if not recs:
        return {}
    wall = max(r.end for r in recs) - min(r.start for r in recs)
    if wall <= 0:
        return {}
    productive = sum(r.duration for r in recs
                     if r.status == "OK" and not r.recompile)
    recompile = sum(r.duration for r in recs if r.recompile)
    unattributed = max(wall - productive - recompile, 0.0)
    return {
        "wall_s": round(wall, 6),
        "productive_fraction": round(productive / wall, 4),
        "recompile_fraction": round(recompile / wall, 4),
        "unattributed_fraction": round(unattributed / wall, 4),
    }
