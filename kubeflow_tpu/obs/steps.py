"""Training-plane step telemetry: step clock, MFU/recompile accounting,
flight recorder, and per-host health beacons.

PR 3 lit up the serving and workflow planes; this module is the same
treatment for the half of the platform the TpuJob operator exists for.
TPU-scale training lives and dies on step-time *regularity* — a single
straggling host stalls every collective in the mesh (the
Concurrency-on-TPUs paper, PAPERS.md), and every scheduling/prediction
system in the related work assumes throughput telemetry exists. The
reference platform has none: its operators know pod phases, never
whether step 4 971 took 40× longer than step 4 970.

Pieces, bottom-up:

- :class:`StepRecord` / :class:`FlightRecorder` — one record per train
  step in a thread-safe bounded ring (the black-box recorder: always on,
  memory bounded hard, the last N steps survive to be dumped when
  something goes wrong).
- :class:`StepTelemetry` — wraps any trainer-built ``run`` callable
  (:mod:`kubeflow_tpu.train.trainer` step factories, or any callable)
  on the injectable-Clock contract. Per step it records wall time,
  tokens/s / examples/s, MFU (FLOPs from XLA compiled cost-analysis via
  the step's AOT ``.jitted`` handle, or an analytic override), and
  recompile events (jit-cache-size delta where the runtime exposes it,
  step-time-outlier fallback where it does not). Feeds the
  ``train_step_seconds`` Histogram + gauges/counters into a
  :class:`~kubeflow_tpu.utils.metrics.Registry`, emits per-host
  beacons, and dumps the flight ring through the existing
  :mod:`kubeflow_tpu.obs.export` Chrome-trace/ndjson exporters on step
  failure or a slow-step trigger.
- identity-derived trace ids (:func:`tpujob_trace_ids`) — the workflow
  controller's trick applied to training jobs: the job's root span and
  per-N-step child spans land in ONE trace computable from ``kubectl
  get`` output, across workers and operator restarts.
- beacons over ConfigMaps (:func:`publish_beacon` /
  :func:`read_beacons`) — one ConfigMap per worker (no read-modify-write
  races across the gang), labeled for one-call listing; the operator
  aggregates them into CR status, the dashboard serves them at
  ``GET /api/jobs/<ns>/<name>/telemetry``.
- straggler policy (:func:`flag_stragglers` / :func:`telemetry_view`)
  — a worker ≥K steps behind the gang's median step is flagged; the
  shared view builder keeps operator status and the dashboard route
  from drifting.

Telemetry is best-effort BY CONTRACT: no code path here may fail a
training step — beacon sinks, dumps, and cost-analysis probes all
degrade silently.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from kubeflow_tpu.obs.export import chrome_trace, otlp_lines
from kubeflow_tpu.obs.trace import Span, SpanContext, Tracer
from kubeflow_tpu.utils.clock import Clock
from kubeflow_tpu.utils.metrics import (
    DEFAULT_REGISTRY,
    Registry,
    STEP_TIME_BUCKETS,
)

log = logging.getLogger(__name__)

# cross-component contract strings: must match the TpuJob operator's
# JOB_LABEL (kubeflow_tpu/operators/tpujob.py imports THIS module, so
# the literal lives here too; tests pin the two equal)
JOB_NAME_LABEL = "kubeflow-tpu.org/job-name"
TELEMETRY_LABEL = "kubeflow-tpu.org/telemetry"
WORKER_KEY = "worker"
BEACON_KEY = "beacon"

ENV_FLIGHT_DIR = "KFTPU_FLIGHT_DIR"
ENV_JOB_UID = "KFTPU_JOB_UID"

DEFAULT_STRAGGLER_STEPS = 10


# -- identity-derived trace ids ----------------------------------------------


def tpujob_trace_ids(ns: str, name: str, uid: str = "") -> Tuple[str, str]:
    """Deterministic ``(trace_id, root span_id)`` for a TpuJob CR —
    the :func:`~kubeflow_tpu.workflows.controller.workflow_trace_ids`
    scheme for the training plane: every worker and every operator
    reconcile derives the SAME trace from object identity (the operator
    injects the uid as ``KFTPU_JOB_UID``), so per-step spans from eight
    hosts and the operator's root span assemble into one tree."""
    h = hashlib.sha256(f"tpujob/{ns}/{name}/{uid}".encode()).hexdigest()
    return h[:32], h[32:48]


def step_span_id(trace_id: str, worker: int, step: int) -> str:
    """Stable span id for one worker's step-window span, so a replayed
    emission re-records the identical span instead of forking."""
    h = hashlib.sha256(f"{trace_id}/w{worker}/step/{step}".encode())
    return h.hexdigest()[:16]


# -- flight recorder ---------------------------------------------------------


@dataclasses.dataclass
class StepRecord:
    """One training step as the flight recorder keeps it."""

    step: int
    start: float
    end: float
    tokens: int = 0
    examples: int = 0
    recompile: bool = False
    status: str = "OK"
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def duration(self) -> float:
        return self.end - self.start

    def to_span(self, trace_id: str, parent_id: Optional[str],
                worker: int = 0) -> Span:
        attrs: Dict[str, Any] = {"step": self.step, "worker": worker}
        if self.tokens:
            attrs["tokens"] = self.tokens
        if self.examples:
            attrs["examples"] = self.examples
        if self.recompile:
            attrs["recompile"] = True
        attrs.update(self.metrics)
        return Span(trace_id=trace_id,
                    span_id=step_span_id(trace_id, worker, self.step),
                    parent_id=parent_id, name=f"train.step/{self.step}",
                    start=self.start, end=self.end, attrs=attrs,
                    status=self.status)


class FlightRecorder:
    """Thread-safe bounded ring of recent :class:`StepRecord`.

    The black-box-recorder contract: always on, memory bounded hard
    (a week-long job keeps the last ``capacity`` steps, not an archive),
    snapshot-dumped when a step fails or goes slow."""

    def __init__(self, capacity: int = 512) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._records: List[StepRecord] = []
        self._next = 0          # ring write cursor
        self._seq = 0           # total records ever (eviction accounting)
        self._lock = threading.Lock()

    def record(self, rec: StepRecord) -> None:
        with self._lock:
            if len(self._records) < self.capacity:
                self._records.append(rec)
            else:
                self._records[self._next] = rec
                self._next = (self._next + 1) % self.capacity
            self._seq += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    @property
    def recorded_total(self) -> int:
        with self._lock:
            return self._seq

    def records(self) -> List[StepRecord]:
        """Snapshot, oldest first."""
        with self._lock:
            return self._records[self._next:] + self._records[:self._next]

    def clear(self) -> None:
        with self._lock:
            self._records = []
            self._next = 0


# -- helpers -----------------------------------------------------------------


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    if not s:
        return 0.0
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else (s[mid - 1] + s[mid]) / 2.0


def _jit_cache_size(fn: Any) -> Optional[int]:
    """Compiled-executable cache size of a jitted callable, where the
    runtime exposes one (``_cache_size`` on jax's jit wrappers); None
    means the recompile detector falls back to step-time outliers."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001 — accounting only, never fails a step
        return None


def cost_analysis_flops(fn: Any, *args: Any) -> Optional[float]:
    """Per-step FLOPs from XLA compiled cost analysis via a jitted
    callable's AOT surface (``fn.lower(*args).compile()``), the same
    read the bench roofline does. None when the callable has no AOT
    surface or the backend declines — MFU then needs an analytic
    ``flops_per_step``."""
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    try:
        ca = lower(*args).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:  # noqa: BLE001 — telemetry must not fail the step
        return None


def _detect_peak_flops() -> float:
    """bf16 peak FLOP/s of one attached chip (0.0 = unknown/CPU)."""
    try:
        from kubeflow_tpu.bench.suite import peak_flops_per_chip

        return float(peak_flops_per_chip())
    except Exception:  # noqa: BLE001 — no jax / no device: MFU just absent
        return 0.0


# -- the step telemetry layer ------------------------------------------------


class StepTelemetry:
    """Wraps a trainer-built ``run`` callable and accounts every step.

    >>> telem = StepTelemetry(job="lm", namespace="default", worker=0,
    ...                       tokens_per_step=batch * seq)
    >>> step_fn = telem.wrap(make_lm_train_step(mesh))
    >>> for _ in range(steps):
    ...     state, metrics = step_fn(state, tokens)

    Everything is injectable (clock, registry, tracer, recorder, beacon
    sink) and everything degrades: telemetry never fails a train step.

    ``sync=True`` blocks on the step's outputs before reading the end
    timestamp (and extracts float-able outputs into the record's
    metrics) — right for tests and log-cadence loops; leave False on
    the hot path so async dispatch keeps pipelining.
    """

    def __init__(
        self,
        *,
        job: str = "",
        namespace: str = "default",
        uid: str = "",
        worker: int = 0,
        clock: Optional[Clock] = None,
        registry: Optional[Registry] = None,
        tracer: Optional[Tracer] = None,
        recorder: Optional[FlightRecorder] = None,
        capacity: int = 512,
        tokens_per_step: int = 0,
        examples_per_step: int = 0,
        flops_per_step: Optional[float] = None,
        peak_flops_per_chip: Optional[float] = None,
        n_chips: int = 1,
        use_cost_analysis: bool = True,
        sync: bool = False,
        slow_step_factor: float = 3.0,
        min_slow_history: int = 5,
        dump_cooldown_steps: int = 50,
        span_every: int = 0,
        beacon_sink: Optional[Callable[[Dict[str, Any]], None]] = None,
        beacon_every: int = 1,
        dump_dir: Optional[str] = None,
        rate_window: int = 20,
        hbm_sampler: Optional[Any] = None,
    ) -> None:
        self.job = job
        self.namespace = namespace
        self.worker = worker
        # wall clock, not monotonic (the workflow controller's reasoning,
        # applied to training): the per-step spans this clock stamps join
        # the operator's terminal root span — recorded on ITS epoch
        # clock — in one identity-derived trace, and beacon ``ts`` values
        # are compared across hosts; monotonic is host-uptime-relative
        # and would scramble both
        self.clock: Clock = clock if clock is not None else time.time
        self.registry = registry if registry is not None else DEFAULT_REGISTRY
        self.recorder = (recorder if recorder is not None
                         else FlightRecorder(capacity))
        self.trace_id, self.root_span_id = tpujob_trace_ids(
            namespace, job, uid)
        # span timestamps share THIS clock (fake-clock determinism)
        self.tracer = tracer if tracer is not None else Tracer(
            clock=self.clock)
        self.tokens_per_step = tokens_per_step
        self.examples_per_step = examples_per_step
        self.flops_per_step = flops_per_step
        self._peak = peak_flops_per_chip  # None = detect lazily
        self.n_chips = max(1, n_chips)
        self.use_cost_analysis = use_cost_analysis
        self.sync = sync
        self.slow_step_factor = slow_step_factor
        self.min_slow_history = min_slow_history
        self.dump_cooldown_steps = dump_cooldown_steps
        self.span_every = span_every
        self.beacon_sink = beacon_sink
        self.beacon_every = max(1, beacon_every)
        self.dump_dir = (dump_dir if dump_dir is not None
                         else os.environ.get(ENV_FLIGHT_DIR) or None)
        # an obs.xprof.HbmSampler (or anything with sample()/
        # beacon_fields()); sampled once per step so the beacon
        # carries live device-memory watermarks. None — and every
        # CPU backend, whose sampler returns None — degrades to no
        # hbm block at all (telemetry contract: never fails a step)
        self.hbm_sampler = hbm_sampler

        self.step = 0
        self.recompiles = 0
        self.dumps = 0
        self.last_dump: Optional[Tuple[str, Dict[str, Any]]] = None
        self._durations: List[float] = []   # rolling, rate_window-bounded
        self._rate_window = max(2, rate_window)
        self._last_dump_step = -(10 ** 9)
        self._probed_cost = False

        lbl = {"job": job} if job else {}
        self._labels = lbl
        self._h_step = self.registry.histogram(
            "train_step_seconds", "train step wall time",
            buckets=STEP_TIME_BUCKETS)
        self._c_steps = self.registry.counter(
            "train_steps_total", "train steps completed")
        self._c_recompiles = self.registry.counter(
            "train_recompiles_total", "train step recompile events")
        self._g_last_step = self.registry.gauge(
            "train_last_step", "last completed train step")
        self._g_steps_per_sec = self.registry.gauge(
            "train_steps_per_sec", "rolling steps/sec")
        self._g_tokens_per_sec = self.registry.gauge(
            "train_tokens_per_sec", "rolling tokens/sec")
        self._g_examples_per_sec = self.registry.gauge(
            "train_examples_per_sec", "rolling examples/sec")
        self._g_mfu = self.registry.gauge(
            "train_mfu", "model FLOPs utilization (0..1)")

    # -- wrapping ----------------------------------------------------------

    def wrap(self, run: Callable[..., Any]) -> Callable[..., Any]:
        """The instrumented step: times ``run``, accounts, re-raises."""
        jitted = getattr(run, "jitted", run)

        def instrumented(*args: Any, **kwargs: Any) -> Any:
            cache_before = _jit_cache_size(jitted)
            start = self.clock()
            try:
                out = run(*args, **kwargs)
                if self.sync:
                    out = _block(out)
            except BaseException as e:
                end = self.clock()
                self._on_step(start, end, cache_before, jitted,
                              status=f"ERROR: {type(e).__name__}",
                              out=None)
                raise
            end = self.clock()
            # probe AFTER the step (and after taking ``end``): the step
            # just compiled this exact program, so the AOT re-lower hits
            # the backend compile cache instead of doubling a minutes-
            # long startup compile — the bench roofline's pattern
            self._maybe_probe_flops(jitted, args)
            self._on_step(start, end, cache_before, jitted, status="OK",
                          out=out)
            return out

        instrumented.telemetry = self  # introspection/bench handle
        if jitted is not run:
            instrumented.jitted = jitted  # keep the AOT surface reachable
        return instrumented

    # -- per-step accounting ----------------------------------------------

    def _maybe_probe_flops(self, jitted: Any, args: Tuple[Any, ...]) -> None:
        if (self._probed_cost or not self.use_cost_analysis
                or self.flops_per_step is not None):
            return
        self._probed_cost = True
        self.flops_per_step = cost_analysis_flops(jitted, *args)

    def _on_step(self, start: float, end: float,
                 cache_before: Optional[int], jitted: Any, *,
                 status: str, out: Any) -> None:
        self.step += 1
        dur = max(end - start, 0.0)
        recompile = self._detect_recompile(cache_before, jitted, dur)
        if recompile:
            self.recompiles += 1
            self._c_recompiles.inc(**self._labels)
        rec = StepRecord(step=self.step, start=start, end=end,
                         tokens=self.tokens_per_step,
                         examples=self.examples_per_step,
                         recompile=recompile, status=status,
                         metrics=_extract_metrics(out) if self.sync else {})
        self.recorder.record(rec)
        self._durations.append(dur)
        if len(self._durations) > self._rate_window:
            self._durations.pop(0)

        self._h_step.observe(dur, **self._labels)
        self._c_steps.inc(**self._labels)
        self._g_last_step.set(self.step, **self._labels)
        rates = self._rates()
        self._g_steps_per_sec.set(rates["steps_per_sec"], **self._labels)
        if self.tokens_per_step:
            self._g_tokens_per_sec.set(rates["tokens_per_sec"],
                                       **self._labels)
        if self.examples_per_step:
            self._g_examples_per_sec.set(rates["examples_per_sec"],
                                         **self._labels)
        mfu = self.mfu()
        if mfu is not None:
            self._g_mfu.set(mfu, **self._labels)

        if self.hbm_sampler is not None:
            try:
                self.hbm_sampler.sample()
            except Exception:  # noqa: BLE001 — watermarks never fail a step
                log.debug("hbm sample failed (continuing)", exc_info=True)
        if self.span_every and (self.step % self.span_every == 0
                                or status != "OK"):
            self._record_step_span(rec)
        if status != "OK":
            self.dump("failure")
        elif self._is_slow(dur):
            self.dump("slow_step")
        if self.beacon_sink is not None and (
                self.step % self.beacon_every == 0 or status != "OK"):
            try:
                self.beacon_sink(self.beacon())
            except Exception:  # noqa: BLE001 — beacons never fail a step
                log.debug("beacon sink failed (continuing)", exc_info=True)

    def _detect_recompile(self, cache_before: Optional[int], jitted: Any,
                          dur: float) -> bool:
        cache_after = _jit_cache_size(jitted)
        if cache_before is not None and cache_after is not None:
            # includes the first fill (0 -> 1): the initial compile is a
            # compile — the flight record for step 1 should say so
            return cache_after > cache_before
        # fallback: a step-time outlier against the rolling median —
        # recompiles stall the host for seconds while neighbors take ms
        history = self._durations
        if len(history) < self.min_slow_history:
            return False
        return dur > self.slow_step_factor * _median(history)

    def _is_slow(self, dur: float) -> bool:
        prior = self._durations[:-1]  # exclude the step under test
        if len(prior) < self.min_slow_history:
            return False
        if dur <= self.slow_step_factor * _median(prior):
            return False
        if self.step - self._last_dump_step < self.dump_cooldown_steps:
            return False  # cooldown: one dump per incident, not per step
        return True

    def _record_step_span(self, rec: StepRecord) -> None:
        try:
            self.tracer.record(
                f"train.step/{rec.step}", start=rec.start, end=rec.end,
                parent=SpanContext(self.trace_id, self.root_span_id),
                span_id=step_span_id(self.trace_id, self.worker, rec.step),
                attrs={"worker": self.worker, "step": rec.step,
                       "recompile": rec.recompile},
                status=rec.status)
        except Exception:  # noqa: BLE001
            log.debug("step span record failed (continuing)", exc_info=True)

    # -- derived views -----------------------------------------------------

    def _rates(self) -> Dict[str, float]:
        total = sum(self._durations)
        n = len(self._durations)
        sps = (n / total) if total > 0 else 0.0
        return {
            "steps_per_sec": sps,
            "tokens_per_sec": sps * self.tokens_per_step,
            "examples_per_sec": sps * self.examples_per_step,
        }

    def mfu(self) -> Optional[float]:
        """Rolling-window MFU; None when FLOPs or peak are unknown."""
        if not self.flops_per_step:
            return None
        if self._peak is None:
            self._peak = _detect_peak_flops()
        if not self._peak or not self._durations:
            return None
        sec = _median(self._durations)
        if sec <= 0:
            return None
        return (self.flops_per_step / sec) / (self._peak * self.n_chips)

    def beacon(self) -> Dict[str, Any]:
        """The per-host health beacon the operator aggregates."""
        rates = self._rates()
        mfu = self.mfu()
        hbm: Dict[str, Any] = {}
        if self.hbm_sampler is not None:
            try:
                hbm = self.hbm_sampler.beacon_fields() or {}
            except Exception:  # noqa: BLE001
                hbm = {}
        return {
            "worker": self.worker,
            "job": self.job,
            "step": self.step,
            "stepsPerSec": round(rates["steps_per_sec"], 4),
            "tokensPerSec": round(rates["tokens_per_sec"], 2),
            "examplesPerSec": round(rates["examples_per_sec"], 2),
            "mfu": round(mfu, 4) if mfu is not None else None,
            "recompiles": self.recompiles,
            "lastStepSeconds": round(self._durations[-1], 6)
            if self._durations else None,
            "hbm": hbm,
            "ts": self.clock(),
        }

    def summary(self) -> Dict[str, Any]:
        """Step-regularity summary (the BENCH-artifact shape): p50/p99
        step time, recompile count, MFU."""
        durs = sorted(r.duration for r in self.recorder.records())
        out: Dict[str, Any] = {
            "steps": self.step,
            "p50_step_s": round(_percentile(durs, 0.50), 6),
            "p99_step_s": round(_percentile(durs, 0.99), 6),
            "recompiles": self.recompiles,
        }
        mfu = self.mfu()
        if mfu is not None:
            out["mfu"] = round(mfu, 4)
        return out

    def objective_series(self, metric: str) -> List[Tuple[int, float]]:
        """Per-step ``(step, value)`` series for a named metric — what
        :func:`kubeflow_tpu.tuning.study.append_history_from_telemetry`
        feeds the tuning plane. Resolves recorded step metrics (e.g.
        ``loss`` under ``sync=True``) first, then the derived series
        ``step_seconds`` / ``steps_per_sec`` / ``tokens_per_sec`` /
        ``examples_per_sec`` / ``mfu``."""
        out: List[Tuple[int, float]] = []
        peak_mfu_ready = bool(self.flops_per_step) and bool(
            self._peak if self._peak is not None else _detect_peak_flops())
        for rec in self.recorder.records():
            if rec.status != "OK":
                continue
            if metric in rec.metrics:
                out.append((rec.step, float(rec.metrics[metric])))
                continue
            dur = rec.duration
            if dur <= 0:
                continue
            if metric == "step_seconds":
                out.append((rec.step, dur))
            elif metric == "steps_per_sec":
                out.append((rec.step, 1.0 / dur))
            elif metric == "tokens_per_sec" and rec.tokens:
                out.append((rec.step, rec.tokens / dur))
            elif metric == "examples_per_sec" and rec.examples:
                out.append((rec.step, rec.examples / dur))
            elif metric == "mfu" and peak_mfu_ready:
                if self._peak is None:
                    self._peak = _detect_peak_flops()
                out.append((rec.step, (self.flops_per_step / dur)
                            / (self._peak * self.n_chips)))
        return out

    # -- flight-recorder dump ----------------------------------------------

    def dump(self, reason: str) -> Dict[str, Any]:
        """Dump the flight ring through the Chrome-trace exporter (and
        ndjson when a dump dir is configured). Returns the Chrome trace
        dict; failures degrade to an empty dict — a broken disk must
        never fail the training step that triggered the dump."""
        try:
            spans = [r.to_span(self.trace_id, self.root_span_id,
                               worker=self.worker)
                     for r in self.recorder.records()]
            chrome = chrome_trace(spans)
            self.dumps += 1
            self._last_dump_step = self.step
            self.last_dump = (reason, chrome)
            if self.dump_dir:
                os.makedirs(self.dump_dir, exist_ok=True)
                stem = f"flight-w{self.worker}-{reason}-step{self.step}"
                path = os.path.join(self.dump_dir, stem + ".trace.json")
                with open(path, "w") as f:
                    json.dump(chrome, f)
                with open(os.path.join(self.dump_dir,
                                       stem + ".ndjson"), "w") as f:
                    f.write(otlp_lines(spans))
                log.warning("flight recorder dumped (%s) to %s",
                            reason, path)
            return chrome
        except Exception:  # noqa: BLE001 — never fail the step
            log.warning("flight-recorder dump failed (continuing)",
                        exc_info=True)
            return {}


def _block(out: Any) -> Any:
    """Force device completion of a step's outputs (sync mode)."""
    try:
        import jax

        return jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 — pure-python callables in tests
        return out


def _extract_metrics(out: Any) -> Dict[str, float]:
    """Float-able scalars from a ``(state, metrics)`` step result (the
    trainer contract) — only under ``sync=True``, where reading them
    cannot stall async dispatch."""
    metrics: Any = None
    if isinstance(out, tuple) and len(out) == 2 and isinstance(
            out[1], Mapping):
        metrics = out[1]
    elif isinstance(out, Mapping):
        metrics = out
    if metrics is None:
        return {}
    extracted: Dict[str, float] = {}
    for k, v in metrics.items():
        try:
            if hasattr(v, "__float__") or isinstance(v, (int, float)):
                f = float(v)
                if f == f:  # drop NaN — it poisons downstream medians
                    extracted[str(k)] = f
        except (TypeError, ValueError):
            continue
    return extracted


# -- beacons over ConfigMaps -------------------------------------------------


def beacon_configmap_name(job: str, worker: int) -> str:
    return f"{job}-telemetry-w{worker}"


def publish_beacon(client: Any, ns: str, job: str, worker: int,
                   beacon: Mapping[str, Any], job_uid: str = "") -> None:
    """Write one worker's beacon into its own ConfigMap. One ConfigMap
    per worker: the gang's hosts never read-modify-write a shared
    object, so there is no lost-update race at any world size.
    ``job_uid`` (the operator-injected CR uid) stamps an ownerReference
    so beacons are garbage-collected with the TpuJob instead of
    accumulating across job churn."""
    from kubeflow_tpu.k8s import objects as o

    cm = o.config_map(beacon_configmap_name(job, worker), ns,
                      {BEACON_KEY: json.dumps(dict(beacon)),
                       WORKER_KEY: str(worker)})
    cm["metadata"]["labels"] = {JOB_NAME_LABEL: job,
                                TELEMETRY_LABEL: "beacon"}
    if job_uid:
        from kubeflow_tpu.manifests.components.tpujob_operator import (
            API_VERSION,
            TPUJOB_KIND,
        )

        cm["metadata"]["ownerReferences"] = [{
            "apiVersion": API_VERSION, "kind": TPUJOB_KIND,
            "name": job, "uid": job_uid, "controller": True}]
    client.apply(cm)


def read_beacons(client: Any, ns: str, job: str,
                 max_workers: Optional[int] = None
                 ) -> Dict[int, Dict[str, Any]]:
    """worker index -> latest beacon, from the labeled ConfigMaps.

    ``max_workers`` filters out beacons beyond the CURRENT world size —
    after an elastic downsize, the departed workers' last beacons would
    otherwise drag the gang median and flag every live worker as a
    straggler."""
    out: Dict[int, Dict[str, Any]] = {}
    for cm in client.list("v1", "ConfigMap", ns,
                          label_selector={JOB_NAME_LABEL: job,
                                          TELEMETRY_LABEL: "beacon"}):
        data = cm.get("data") or {}
        try:
            worker = int(data.get(WORKER_KEY, ""))
            if max_workers is not None and worker >= max_workers:
                continue
            out[worker] = json.loads(data.get(BEACON_KEY, "{}"))
        except (TypeError, ValueError):
            continue  # a garbled beacon must not hide the others
    return out


def kube_beacon_sink(client: Any, ns: str, job: str, worker: int,
                     job_uid: str = "") -> Callable[[Dict[str, Any]], None]:
    """A :class:`StepTelemetry` ``beacon_sink`` publishing to the
    cluster. Transport errors are swallowed (telemetry contract)."""

    def sink(beacon: Dict[str, Any]) -> None:
        try:
            publish_beacon(client, ns, job, worker, beacon,
                           job_uid=job_uid)
        except Exception:  # noqa: BLE001
            log.debug("beacon publish failed (continuing)", exc_info=True)

    return sink


# -- straggler policy + the aggregated view ----------------------------------


def flag_stragglers(
    steps_by_worker: Mapping[Any, int], k: int = DEFAULT_STRAGGLER_STEPS,
) -> Tuple[float, Dict[Any, int], List[Any]]:
    """``(median_step, lag_by_worker, stragglers)``: a worker ≥``k``
    steps behind the gang's median step is a straggler. Median, not max:
    one runaway-ahead worker (clock skew, restarted counter) must not
    flag the whole healthy gang."""
    if not steps_by_worker:
        return 0.0, {}, []
    k = max(1, int(k))
    median = _median([float(s) for s in steps_by_worker.values()])
    lags = {w: max(0, int(median - s)) for w, s in steps_by_worker.items()}
    stragglers = sorted((w for w, lag in lags.items() if lag >= k),
                        key=str)
    return median, lags, stragglers


def _hbm_view(beacons: Mapping[int, Mapping[str, Any]]) -> Dict[str, Any]:
    """Gang-level HBM watermark from the per-worker beacon ``hbm``
    blocks: MAX across workers (the fullest device gates the gang —
    it OOMs first), same shape whether zero or all workers report."""
    blocks = [b.get("hbm") for b in beacons.values()
              if isinstance(b.get("hbm"), Mapping) and b.get("hbm")]
    if not blocks:
        return {"inUseBytes": 0, "peakBytes": 0, "limitBytes": 0,
                "workersReporting": 0}
    return {
        "inUseBytes": max(int(b.get("inUseBytes", 0) or 0)
                          for b in blocks),
        "peakBytes": max(int(b.get("peakBytes", 0) or 0)
                         for b in blocks),
        "limitBytes": max(int(b.get("limitBytes", 0) or 0)
                          for b in blocks),
        "workersReporting": len(blocks),
    }


def telemetry_view(beacons: Mapping[int, Mapping[str, Any]],
                   straggler_k: int = DEFAULT_STRAGGLER_STEPS
                   ) -> Dict[str, Any]:
    """Aggregate per-worker beacons into the job-level telemetry shape
    served in CR status AND by the dashboard route — one builder so the
    two surfaces cannot drift.

    ``stepsPerSec`` is the gang's MEDIAN worker rate (SPMD throughput is
    gated by the slowest collective participant; the median is the
    honest central figure next to the per-worker lags), ``lastStep`` the
    max observed step, ``recompiles`` the gang total."""
    if not beacons:
        # SAME keys as the populated branch — consumers must never have
        # to guess which shape they got
        return {"lastStep": 0, "medianStep": 0.0, "stepsPerSec": 0.0,
                "tokensPerSec": 0.0, "mfu": None, "recompiles": 0,
                "hbm": _hbm_view(beacons),
                "workers": {}, "stragglers": [],
                "stragglerThreshold": max(1, int(straggler_k))}
    steps_by = {w: int(b.get("step", 0)) for w, b in beacons.items()}
    median, lags, stragglers = flag_stragglers(steps_by, straggler_k)
    rates = [float(b.get("stepsPerSec") or 0.0) for b in beacons.values()]
    mfus = [float(b["mfu"]) for b in beacons.values()
            if b.get("mfu") is not None]
    workers = {
        str(w): {
            "step": steps_by[w],
            "stepsPerSec": float(beacons[w].get("stepsPerSec") or 0.0),
            "lag": lags[w],
            "recompiles": int(beacons[w].get("recompiles") or 0),
        }
        for w in sorted(beacons)
    }
    return {
        "lastStep": max(steps_by.values()),
        "medianStep": median,
        "stepsPerSec": round(_median(rates), 4),
        "tokensPerSec": round(sum(
            float(b.get("tokensPerSec") or 0.0)
            for b in beacons.values()), 2),
        "mfu": round(_median(mfus), 4) if mfus else None,
        "recompiles": sum(int(b.get("recompiles") or 0)
                          for b in beacons.values()),
        "hbm": _hbm_view(beacons),
        "workers": workers,
        "stragglers": [str(w) for w in stragglers],
        "stragglerThreshold": max(1, int(straggler_k)),
    }
