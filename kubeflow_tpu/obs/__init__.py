"""Observability tier: distributed tracing + exporters (docs/OBSERVABILITY.md)."""

from kubeflow_tpu.obs.trace import (  # noqa: F401
    DEFAULT_COLLECTOR,
    REQUEST_ID_HEADER,
    TRACEPARENT_HEADER,
    TRACER,
    TRACESTATE_HEADER,
    Span,
    SpanCollector,
    SpanContext,
    Tracer,
    current_context,
    current_span,
    extract,
    format_traceparent,
    grpc_metadata,
    inject,
    parse_traceparent,
    profiler_annotator,
)
from kubeflow_tpu.obs.export import (  # noqa: F401
    chrome_trace,
    otlp_lines,
    parse_otlp_lines,
    push_spans,
)
from kubeflow_tpu.obs.tsdb import (  # noqa: F401
    Exemplar,
    Point,
    TimeSeriesStore,
)
from kubeflow_tpu.obs.scrape import (  # noqa: F401
    ParsedSample,
    Scraper,
    parse_exposition,
)
from kubeflow_tpu.obs.alerts import (  # noqa: F401
    AbsenceRule,
    AlertManager,
    BurnRateRule,
    BurnWindow,
    ThresholdRule,
    default_rules,
    rule_from_dict,
)
from kubeflow_tpu.obs.goodput import (  # noqa: F401
    BADPUT_STATES,
    GoodputExporter,
    GoodputSignals,
    STATES as GOODPUT_STATES,
    fleet_rollup,
    fold as fold_goodput,
    goodput_fraction,
    observe_checkpoint_save,
    worst_badput_interval,
)
from kubeflow_tpu.obs.xprof import (  # noqa: F401
    CompileEvent,
    CompileLedger,
    HbmSampler,
    hlo_fingerprint,
    job_compile_seconds,
    memory_budget,
    observe_compile,
    record_memory_budget,
    shape_class_of,
)
from kubeflow_tpu.obs.requests import (  # noqa: F401
    DEFAULT_LEDGER,
    PHASES as REQUEST_PHASES,
    RequestLedger,
    RequestRecord,
    check_tiling as check_request_tiling,
    fold_record as fold_request_record,
    synthetic_rid,
)
from kubeflow_tpu.obs.steps import (  # noqa: F401
    FlightRecorder,
    StepRecord,
    StepTelemetry,
    flag_stragglers,
    kube_beacon_sink,
    publish_beacon,
    read_beacons,
    step_span_id,
    telemetry_view,
    tpujob_trace_ids,
)
