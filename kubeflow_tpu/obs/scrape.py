"""Metrics scraper: pull ``/metrics`` expositions into the tsdb.

The reference runs a Prometheus Deployment whose kubernetes service
discovery scrapes every component Service annotated
``prometheus.io/scrape`` (``gcp/prometheus.libsonnet``). This module is
the in-process half of that loop: a :class:`Scraper` pulls the same
component endpoints' text expositions into one
:class:`~kubeflow_tpu.obs.tsdb.TimeSeriesStore` — plus any in-process
:class:`~kubeflow_tpu.utils.metrics.Registry` (the component's own
metrics, sampled without HTTP).

Design points:

- **one parser for everything** — :func:`parse_exposition` reads back
  exactly the text format :mod:`kubeflow_tpu.utils.metrics` emits,
  including escaped label values (``\\``, ``\"``, ``\\n``) and the
  OpenMetrics exemplar suffix (``# {trace_id="..."} v``); local
  registry sampling goes through it too, so an exposition that can't
  round-trip is a test failure, not silent data loss.
- **targets from the manifest** — the default target set is
  :func:`kubeflow_tpu.manifests.components.monitoring.scrape_targets`,
  derived by rendering the registered components and reading the
  ``prometheus.io/*`` annotations off their Services. The deployed
  prometheus config and this scraper consume the same source, so they
  cannot drift (the TPU004 stance applied to scrape wiring).
- **per-target ``up`` + staleness** — every tick writes
  ``up{target=}`` 1/0 into the store; a failing target's other series
  simply stop getting points and age out of the store's staleness
  window, so instant queries go silent instead of reporting a dead
  pod's frozen gauges.
- **injectable everything** — ``clock`` (TPU003), ``fetch`` (url →
  text) for tests; ticks run on the shared reconciler runtime via
  :meth:`Scraper.build_controller` (``Controller.periodic``).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from kubeflow_tpu.obs.tsdb import Exemplar, TimeSeriesStore
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.clock import Clock
from kubeflow_tpu.utils.metrics import Registry

log = logging.getLogger(__name__)

# url -> exposition text; raises on unreachable/garbled
Fetch = Callable[[str], str]

_scrapes_total = DEFAULT_REGISTRY.counter(
    "kftpu_scrape_attempts_total", "scrape attempts per target by outcome")


@dataclass(frozen=True)
class ParsedSample:
    """One exposition line: series + value + optional exemplar."""

    name: str
    labels: Dict[str, str]
    value: float
    exemplar_trace_id: Optional[str] = None
    exemplar_value: Optional[float] = None


def _unescape(value: str) -> str:
    """Invert the text-format label-value escaping."""
    out: List[str] = []
    i, n = 0, len(value)
    while i < n:
        c = value[i]
        if c == "\\" and i + 1 < n:
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ("\\", '"'):
                out.append(nxt)
            else:  # unknown escape: keep verbatim (lenient read side)
                out.append(c)
                out.append(nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(text: str, start: int) -> Tuple[Dict[str, str], int]:
    """Parse ``{k="v",...}`` starting at ``text[start] == '{'``;
    returns (labels, index just past the closing brace). Escape-aware:
    a ``"`` or ``}`` inside a quoted value never terminates it."""
    labels: Dict[str, str] = {}
    i = start + 1
    n = len(text)
    while i < n:
        while i < n and text[i] in ", ":
            i += 1
        if i < n and text[i] == "}":
            return labels, i + 1
        eq = text.find("=", i)
        if eq < 0:
            raise ValueError(f"label without '=' at {i}")
        key = text[i:eq].strip()
        i = eq + 1
        if i >= n or text[i] != '"':
            raise ValueError(f"unquoted label value for {key!r}")
        i += 1
        buf: List[str] = []
        while i < n:
            c = text[i]
            if c == "\\" and i + 1 < n:
                buf.append(c)
                buf.append(text[i + 1])
                i += 2
                continue
            if c == '"':
                break
            buf.append(c)
            i += 1
        if i >= n:
            raise ValueError(f"unterminated label value for {key!r}")
        labels[key] = _unescape("".join(buf))
        i += 1  # past the closing quote
    raise ValueError("unterminated label set")


def parse_exposition(text: str) -> List[ParsedSample]:
    """Parse a Prometheus text exposition (the format
    :meth:`Registry.expose` emits). Comment/blank lines are skipped;
    a malformed line is dropped (logged at debug), never fatal — one
    bad series must not lose a target's whole scrape."""
    out: List[ParsedSample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            out.append(_parse_line(line))
        except (ValueError, IndexError) as e:
            log.debug("dropped exposition line %r: %s", line, e)
    return out


def _parse_line(line: str) -> ParsedSample:
    i = 0
    n = len(line)
    while i < n and line[i] not in "{ ":
        i += 1
    name = line[:i]
    if not name:
        raise ValueError("empty metric name")
    labels: Dict[str, str] = {}
    if i < n and line[i] == "{":
        labels, i = _parse_labels(line, i)
    rest = line[i:].strip()
    # optional OpenMetrics exemplar suffix: `value # {labels} exemplar`
    value_part, _, exemplar_part = rest.partition(" # ")
    tokens = value_part.split()
    if not tokens:
        raise ValueError("missing sample value")
    value = float(tokens[0])  # a trailing timestamp token is ignored
    trace_id: Optional[str] = None
    ex_value: Optional[float] = None
    exemplar_part = exemplar_part.strip()
    if exemplar_part.startswith("{"):
        ex_labels, j = _parse_labels(exemplar_part, 0)
        trace_id = ex_labels.get("trace_id")
        ex_tokens = exemplar_part[j:].split()
        if ex_tokens:
            ex_value = float(ex_tokens[0])
    return ParsedSample(name=name, labels=labels, value=value,
                        exemplar_trace_id=trace_id, exemplar_value=ex_value)


def _default_fetch(timeout_s: float) -> Fetch:
    def fetch(url: str) -> str:
        import urllib.request

        from kubeflow_tpu.utils.metrics import EXEMPLARS_HEADER

        # request the exemplar extension: exposition endpoints suffix
        # bucket lines with exemplars only for a scraper that opted in
        # (a classic 0.0.4 parser would choke on them; ours round-trips
        # them into the store)
        req = urllib.request.Request(
            url, headers={EXEMPLARS_HEADER: "1"})
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.read().decode("utf-8", "replace")

    return fetch


class Scraper:
    """Pulls remote expositions + samples local registries each tick.

    ``targets`` maps target name → metrics URL (default: the manifest's
    :func:`scrape_targets`); ``registries`` maps target name → an
    in-process :class:`Registry` sampled without HTTP (the common
    dev/test shape, and how a component monitors itself). Every sample
    is stamped with a ``target`` label — same-named series from two
    components stay distinguishable — and every tick writes the
    per-target ``up`` series."""

    def __init__(self, store: TimeSeriesStore, *,
                 targets: Optional[Mapping[str, str]] = None,
                 registries: Optional[Mapping[str, Registry]] = None,
                 clock: Optional[Clock] = None,
                 fetch: Optional[Fetch] = None,
                 timeout_s: float = 5.0,
                 interval_s: float = 30.0) -> None:
        if targets is None:
            from kubeflow_tpu.manifests.components.monitoring import (
                scrape_targets,
            )

            targets = scrape_targets()
        self.store = store
        self.targets: Dict[str, str] = dict(targets)
        self.registries: Dict[str, Registry] = dict(registries or {})
        self.clock: Clock = clock if clock is not None else store.clock
        self.fetch: Fetch = (fetch if fetch is not None
                             else _default_fetch(timeout_s))
        self.interval_s = float(interval_s)
        self.last_success: Dict[str, float] = {}

    # -- one tick ----------------------------------------------------------

    def tick(self) -> Dict[str, bool]:
        """Scrape every target + sample every registry once; returns
        per-target up/down (the smoke gates assert on it)."""
        results: Dict[str, bool] = {}
        now = self.clock()
        for name, registry in sorted(self.registries.items()):
            try:
                self.store.sample_registry(registry,
                                           labels={"target": name},
                                           ts=now)
            except Exception:  # noqa: BLE001 — one bad registry must
                # not starve every remote target of scrapes forever;
                # it reads as down (and loudly, unlike a dead pod)
                log.exception("sampling in-process registry %r failed",
                              name)
                self._mark(name, False, now)
                results[name] = False
                continue
            self._mark(name, True, now)
            results[name] = True
        for name, url in sorted(self.targets.items()):
            try:
                text = self.fetch(url)
            except Exception as e:  # noqa: BLE001 — any failure = down
                log.debug("scrape %s (%s) failed: %s", name, url, e)
                self._mark(name, False, now)
                results[name] = False
                continue
            self._ingest(name, text, now)
            self._mark(name, True, now)
            results[name] = True
        return results

    def _ingest(self, target: str, text: str, now: float) -> None:
        for s in parse_exposition(text):
            labels = dict(s.labels)
            labels["target"] = target
            ex = None
            if s.exemplar_trace_id is not None:
                ex = Exemplar(s.exemplar_trace_id,
                              s.exemplar_value if s.exemplar_value
                              is not None else s.value, now)
            self.store.ingest(s.name, s.value, labels=labels, ts=now,
                              exemplar=ex)

    def _mark(self, target: str, up: bool, now: float) -> None:
        self.store.ingest("up", 1.0 if up else 0.0,
                          labels={"target": target}, ts=now)
        _scrapes_total.inc(target=target, outcome="ok" if up else "fail")
        if up:
            self.last_success[target] = now

    def stale_targets(self, staleness_s: Optional[float] = None
                      ) -> List[str]:
        """Targets with no successful scrape inside the staleness
        window (never-scraped targets included) — the scrape-health
        view the dashboard's query API surfaces via ``up``."""
        limit = (staleness_s if staleness_s is not None
                 else self.store.staleness_s)
        now = self.clock()
        names = sorted(set(self.targets) | set(self.registries))
        out = []
        for t in names:
            last = self.last_success.get(t)
            if last is None or now - last > limit:
                out.append(t)
        return out

    # -- runtime -----------------------------------------------------------

    def build_controller(self, interval_s: Optional[float] = None):
        """Run the scrape tick on the shared reconciler runtime
        (``Controller.periodic`` — uniform ``controller.reconcile``
        spans + counter, like the autoscaler tick and queue cycle)."""
        from kubeflow_tpu.operators.controller import Controller

        interval = interval_s if interval_s is not None else self.interval_s

        def reconcile(_ns: str, _name: str) -> float:
            self.tick()
            return interval

        return Controller.periodic(reconcile, name="metrics-scraper")
