"""Compile-event ledger + device-memory watermarks: the profile plane.

The goodput ledger (PR 13) prices ``startup_compile``/``recompile``
per second — but until now those seconds were *inferred* from
step-beacon gaps, no ``kftpu_*`` series recorded an actual XLA compile
event, and HBM occupancy was metered only for KV pages. This module
closes the platform's last accounting blind spot with two pieces:

- :class:`CompileLedger` — subscribes to ``jax.monitoring`` duration
  events (filtered to the single ``backend_compile_duration`` event
  per compilation; jax also emits jaxpr-trace and MLIR-lowering
  durations for the same program, which must NOT triple-count) with a
  wrapper fallback (:meth:`CompileLedger.timed_compile`) for backends
  that don't emit them. Every compilation becomes one
  ``kftpu_compile_seconds{module,shape_class,generation}``
  observation, a ``compile`` span in the job's identity-derived trace
  tree, and an HLO fingerprint keyed with the tile table's vocabulary
  (:func:`~kubeflow_tpu.ops.autotune.seq_bucket` ×
  :func:`~kubeflow_tpu.ops.autotune.backend_generation`) — the same
  key the fleet-shared compile cache will be adjudicated against.
  Per-job cumulative totals feed the goodput fold a *ground-truth*
  attribution source (:func:`job_compile_seconds`) that takes
  precedence over beacon inference.
- :class:`HbmSampler` — per-step / per-admit sampling of
  ``device.memory_stats()`` into ``kftpu_hbm_bytes{kind}``
  (``in_use``/``peak``/``limit``) and ``kftpu_hbm_utilization``,
  wired into the trainer's :class:`~kubeflow_tpu.obs.steps.
  StepTelemetry` beacon and the serving engine's admit path. Static
  budgets from ``compiled.memory_analysis()`` (temp/argument/output
  bytes) land in ``kftpu_hbm_budget_bytes{kind}`` beside the
  fingerprint at compile time — every executable carries its
  predicted footprint, every job its live watermark.

Both degrade by contract: CPU backends return ``memory_stats() is
None`` and the sampler goes silent; a backend without monitoring
events simply never fires the listener (the wrapper fallback still
works); nothing here may fail a training step or an admit.

Exported series (docs/OBSERVABILITY.md "Compile & memory"):

- ``kftpu_compile_seconds{module,shape_class,generation[,namespace,
  job]}`` — histogram, one observation per backend compile;
- ``kftpu_hbm_bytes{kind[,identity...]}`` — live watermark gauges;
- ``kftpu_hbm_utilization{[identity...]}`` — ``in_use/limit``, the
  ``hbm-headroom`` alert's input (absent when the backend reports no
  limit);
- ``kftpu_hbm_budget_bytes{kind,module,shape_class,generation}`` —
  the static ``memory_analysis`` prediction per executable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from kubeflow_tpu.obs.steps import tpujob_trace_ids
from kubeflow_tpu.obs.trace import SpanContext, Tracer
from kubeflow_tpu.ops.autotune import (
    backend_generation,
    dtype_name,
    seq_bucket,
)
from kubeflow_tpu.utils.clock import Clock
from kubeflow_tpu.utils.metrics import DEFAULT_REGISTRY, STEP_TIME_BUCKETS

log = logging.getLogger(__name__)

# jax emits THREE duration events per compilation (jaxpr trace, MLIR
# lowering, backend compile); counting any but the last would
# triple-bill every compile, and only backend_compile is the XLA wall
# time the goodput ledger carves
COMPILE_EVENT_SUFFIX = "backend_compile_duration"

HBM_KINDS = ("in_use", "peak", "limit")
BUDGET_KINDS = ("temp", "argument", "output", "generated_code", "alias")

# -- exported series ---------------------------------------------------------

_compile_h = DEFAULT_REGISTRY.histogram(
    "kftpu_compile_seconds",
    "XLA compilation wall time, one observation per backend compile, "
    "keyed by module / shape class / backend generation",
    buckets=STEP_TIME_BUCKETS)
_hbm_g = DEFAULT_REGISTRY.gauge(
    "kftpu_hbm_bytes",
    "device memory watermark (kind=in_use|peak|limit), sampled from "
    "device.memory_stats()")
_hbm_util_g = DEFAULT_REGISTRY.gauge(
    "kftpu_hbm_utilization",
    "device memory in_use/limit fraction (absent when the backend "
    "reports no limit)")
_hbm_budget_g = DEFAULT_REGISTRY.gauge(
    "kftpu_hbm_budget_bytes",
    "static memory_analysis budget per compiled executable "
    "(kind=temp|argument|output|generated_code|alias)")


def observe_compile(seconds: float, *, module: str, shape_class: str,
                    generation: str, namespace: str = "",
                    job: str = "") -> None:
    """One compile event into the histogram. Job identity labels the
    series the goodput fold reads back through the tsdb; an unlabeled
    observation (no job context) still lands in the fleet series."""
    labels = {"module": module, "shape_class": shape_class,
              "generation": generation}
    if job:
        labels.update({"namespace": namespace, "job": job})
    _compile_h.observe(max(float(seconds), 0.0), **labels)


def set_hbm_bytes(kind: str, value: float, *, namespace: str = "",
                  job: str = "", worker: Optional[int] = None,
                  model: str = "") -> None:
    labels: Dict[str, str] = {"kind": kind}
    if job:
        labels.update({"namespace": namespace, "job": job})
    if worker is not None:
        labels["worker"] = str(worker)
    if model:
        labels["model"] = model
    _hbm_g.set(float(value), **labels)


def set_hbm_utilization(value: float, *, namespace: str = "",
                        job: str = "", worker: Optional[int] = None,
                        model: str = "") -> None:
    labels: Dict[str, str] = {}
    if job:
        labels.update({"namespace": namespace, "job": job})
    if worker is not None:
        labels["worker"] = str(worker)
    if model:
        labels["model"] = model
    _hbm_util_g.set(float(value), **labels)


# -- shape-class / fingerprint vocabulary ------------------------------------


def shape_class_of(*args: Any) -> str:
    """Shape-class slug for a compile's call arguments, in the tile
    table's vocabulary: the pow2 :func:`seq_bucket` of the largest
    dimension seen plus the widest array dtype. Scalar-only calls
    class as ``scalar``."""
    max_dim = 0
    dt = ""
    queue: List[Any] = list(args)
    i = 0
    while i < len(queue):           # FIFO: first arg's dtype wins
        a = queue[i]
        i += 1
        if isinstance(a, (tuple, list)):
            queue.extend(a)
            continue
        if isinstance(a, dict):
            queue.extend(a.values())
            continue
        shape = getattr(a, "shape", None)
        if shape is None:
            continue
        for d in shape:
            try:
                max_dim = max(max_dim, int(d))
            except (TypeError, ValueError):
                continue
        dtype = getattr(a, "dtype", None)
        if dtype is not None and not dt:
            dt = dtype_name(dtype)
    if max_dim <= 0:
        return "scalar"
    return f"seq{seq_bucket(max_dim)}_{dt or 'any'}"


def hlo_fingerprint(lowered: Any) -> str:
    """16-hex HLO module hash from a lowered computation's text — the
    compile-cache key beside shape class × generation. Empty string
    when the backend declines to stringify."""
    try:
        text = lowered.as_text()
    except Exception:  # noqa: BLE001 — fingerprint is best-effort
        return ""
    return hashlib.sha256(str(text).encode()).hexdigest()[:16]


def compile_span_id(trace_id: str, worker: int, module: str,
                    seq: int) -> str:
    """Stable span id for one worker's Nth compile of ``module`` — a
    replayed emission re-records the identical span instead of
    forking (the :func:`~kubeflow_tpu.obs.steps.step_span_id`
    scheme)."""
    h = hashlib.sha256(
        f"{trace_id}/w{worker}/compile/{module}/{seq}".encode())
    return h.hexdigest()[:16]


# -- memory_analysis budgets -------------------------------------------------

_BUDGETS: Dict[str, Dict[str, Any]] = {}
_BUDGETS_LOCK = threading.Lock()


def memory_budget(compiled: Any) -> Dict[str, int]:
    """Static byte budget from a compiled executable's
    ``memory_analysis()``; empty dict when the backend declines
    (budgets are a prediction, never a requirement)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        return {}
    if ma is None:
        return {}
    if isinstance(ma, (list, tuple)):
        ma = ma[0] if ma else None
        if ma is None:
            return {}
    out: Dict[str, int] = {}
    for kind in BUDGET_KINDS:
        v = getattr(ma, f"{kind}_size_in_bytes", None)
        if v is not None:
            try:
                out[kind] = int(v)
            except (TypeError, ValueError):
                continue
    return out


def record_memory_budget(compiled: Any, *, module: str, shape_class: str,
                         generation: str,
                         fingerprint: str = "") -> Dict[str, int]:
    """Record an executable's predicted footprint beside its
    fingerprint: one ``kftpu_hbm_budget_bytes{kind}`` gauge row per
    budget kind, plus the per-fingerprint registry
    :func:`budget_for` serves."""
    budget = memory_budget(compiled)
    for kind, v in budget.items():
        labels = {"kind": kind, "module": module,
                  "shape_class": shape_class, "generation": generation}
        _hbm_budget_g.set(float(v), **labels)
    if fingerprint and budget:
        with _BUDGETS_LOCK:
            _BUDGETS[fingerprint] = {
                "module": module, "shape_class": shape_class,
                "generation": generation, "bytes": dict(budget)}
    return budget


def budget_for(fingerprint: str) -> Optional[Dict[str, Any]]:
    with _BUDGETS_LOCK:
        b = _BUDGETS.get(fingerprint)
        return dict(b) if b else None


def budgets() -> Dict[str, Dict[str, Any]]:
    """Snapshot of every recorded fingerprint → budget."""
    with _BUDGETS_LOCK:
        return {fp: dict(b) for fp, b in _BUDGETS.items()}


# -- per-job ground-truth compile totals -------------------------------------

# (namespace, job) -> {"seconds": float, "count": int}; the in-process
# source the goodput fold prefers over beacon inference when no tsdb
# has scraped the histogram yet (the all-in-one-process tier)
_JOB_COMPILE_TOTALS: Dict[Tuple[str, str], Dict[str, float]] = {}
_TOTALS_LOCK = threading.Lock()


def job_compile_seconds(namespace: str, job: str) -> Optional[float]:
    """Cumulative event-sourced compile seconds for one job; ``None``
    when no ledger has recorded for it (the goodput fold then keeps
    its beacon-inference path — absence of evidence is not zero)."""
    with _TOTALS_LOCK:
        t = _JOB_COMPILE_TOTALS.get((namespace, job))
        return float(t["seconds"]) if t else None


def job_compile_totals(namespace: str, job: str) -> Dict[str, float]:
    with _TOTALS_LOCK:
        t = _JOB_COMPILE_TOTALS.get((namespace, job))
        return (dict(t) if t
                else {"seconds": 0.0, "count": 0})


def _reset_job_totals() -> None:
    """Test/smoke isolation hook."""
    with _TOTALS_LOCK:
        _JOB_COMPILE_TOTALS.clear()


# -- the compile-event ledger ------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CompileEvent:
    """One recorded compilation."""

    module: str
    seconds: float
    shape_class: str
    generation: str
    fingerprint: str
    start: float
    end: float


def _evict_stale_listeners() -> None:
    """Unregister compile listeners left by a PREVIOUS import of this
    module (importlib.reload re-executes the module and orphans its
    registered callback — the double-count path the satellite task
    names). Best-effort: reaches into jax's private listener list,
    degrades silently when the internals move."""
    try:
        from jax._src import monitoring as _mon

        stale = [cb for cb in list(
            getattr(_mon, "_event_duration_secs_listeners", []))
            if getattr(cb, "_kftpu_compile_listener", False)]
        for cb in stale:
            _unregister_listener(cb)
    except Exception:  # noqa: BLE001
        log.debug("stale-listener sweep failed (continuing)",
                  exc_info=True)


def _unregister_listener(cb: Callable[..., None]) -> bool:
    try:
        from jax._src import monitoring as _mon

        unreg = getattr(
            _mon, "_unregister_event_duration_listener_by_callback", None)
        if unreg is not None:
            unreg(cb)
            return True
        listeners = getattr(_mon, "_event_duration_secs_listeners", None)
        if listeners is not None and cb in listeners:
            listeners.remove(cb)
            return True
    except Exception:  # noqa: BLE001
        log.debug("listener unregister failed (continuing)",
                  exc_info=True)
    return False


class CompileLedger:
    """Records every XLA compilation as metric + span + job total.

    >>> ledger = CompileLedger(namespace="default", job="lm", worker=0)
    >>> ledger.install()                 # jax.monitoring subscription
    >>> ...                              # jit compiles are now ledgered
    >>> ledger.uninstall()               # explicit teardown

    Everything is injectable (clock, tracer, generation) per the
    TPU003 contract; the clock is wall time so compile spans join the
    job's identity-derived trace next to the operator's epoch-clock
    root span. ``install`` is idempotent per ledger and sweeps
    listeners orphaned by a module re-import, so one compilation can
    never double-count.
    """

    def __init__(self, *, namespace: str = "", job: str = "",
                 uid: str = "", worker: int = 0,
                 clock: Optional[Clock] = None,
                 tracer: Optional[Tracer] = None,
                 generation: Optional[str] = None,
                 capacity: int = 256) -> None:
        self.namespace = namespace
        self.job = job
        self.worker = worker
        self.clock: Clock = clock if clock is not None else time.time
        self.tracer = (tracer if tracer is not None
                       else Tracer(clock=self.clock))
        self.trace_id, self.root_span_id = tpujob_trace_ids(
            namespace, job, uid)
        # resolved lazily so a ledger constructed before jax init (or
        # with no jax at all on the edge tier) still works
        self._generation = generation
        self.capacity = max(1, int(capacity))
        self.events: List[CompileEvent] = []
        self._seq_by_module: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._listener: Optional[Callable[..., None]] = None
        # constructing with job identity ANNOUNCES the ground-truth
        # source: job_compile_seconds() flips from None to 0.0 and the
        # goodput fold's beacon inference stands down from worker boot
        # — otherwise the window before the first compile event would
        # still be inferred and the measured total could never match
        # the attributed startup_compile exactly
        if self.job:
            with _TOTALS_LOCK:
                _JOB_COMPILE_TOTALS.setdefault(
                    (self.namespace, self.job),
                    {"seconds": 0.0, "count": 0})

    @property
    def generation(self) -> str:
        if self._generation is None:
            try:
                self._generation = backend_generation()
            except Exception:  # noqa: BLE001
                self._generation = "unknown"
        return self._generation

    # -- recording ---------------------------------------------------------

    def record(self, module: str, seconds: float, *,
               shape_class: str = "", generation: str = "",
               fingerprint: str = "",
               end: Optional[float] = None) -> CompileEvent:
        """Ledger one compilation: histogram observation, ``compile``
        span parented on the job's root, per-job total, bounded event
        list. Never raises — a broken tracer must not fail the
        compile it measures."""
        seconds = max(float(seconds), 0.0)
        end_ts = float(end) if end is not None else float(self.clock())
        gen = generation or self.generation
        sc = shape_class or "unknown"
        ev = CompileEvent(module=module, seconds=seconds,
                          shape_class=sc, generation=gen,
                          fingerprint=fingerprint,
                          start=end_ts - seconds, end=end_ts)
        with self._lock:
            seq = self._seq_by_module.get(module, 0)
            self._seq_by_module[module] = seq + 1
            self.events.append(ev)
            if len(self.events) > self.capacity:
                del self.events[:len(self.events) - self.capacity]
        try:
            observe_compile(seconds, module=module, shape_class=sc,
                            generation=gen, namespace=self.namespace,
                            job=self.job)
        except Exception:  # noqa: BLE001
            log.debug("compile metric failed (continuing)", exc_info=True)
        if self.job:
            with _TOTALS_LOCK:
                t = _JOB_COMPILE_TOTALS.setdefault(
                    (self.namespace, self.job),
                    {"seconds": 0.0, "count": 0})
                t["seconds"] += seconds
                t["count"] += 1
        try:
            attrs: Dict[str, Any] = {
                "module": module, "shape_class": sc, "generation": gen,
                "seconds": round(seconds, 6), "worker": self.worker}
            if fingerprint:
                attrs["fingerprint"] = fingerprint
            self.tracer.record(
                f"compile/{module}", start=ev.start, end=ev.end,
                parent=SpanContext(self.trace_id, self.root_span_id),
                span_id=compile_span_id(self.trace_id, self.worker,
                                        module, seq),
                attrs=attrs)
        except Exception:  # noqa: BLE001
            log.debug("compile span failed (continuing)", exc_info=True)
        return ev

    def total_seconds(self) -> float:
        with self._lock:
            return sum(e.seconds for e in self.events)

    def summary(self) -> Dict[str, Any]:
        """The bench-artifact ``compile`` block shape."""
        with self._lock:
            evs = list(self.events)
        out: Dict[str, Any] = {
            "count": len(evs),
            "seconds": round(sum(e.seconds for e in evs), 6),
        }
        if evs:
            by_mod: Dict[str, float] = {}
            for e in evs:
                by_mod[e.module] = by_mod.get(e.module, 0.0) + e.seconds
            out["by_module"] = {m: round(s, 6)
                                for m, s in sorted(by_mod.items())}
            out["generation"] = evs[-1].generation
        return out

    def events_payload(self) -> Dict[str, Any]:
        """The ``--compile-audit`` artifact shape: every ledgered
        event, JSON-serializable, keyed for the static jit-site join
        (``kubeflow_tpu/analysis/compileaudit.py``)."""
        with self._lock:
            evs = list(self.events)
        return {"compile_events": [dataclasses.asdict(e) for e in evs]}

    # -- jax.monitoring subscription ---------------------------------------

    def install(self) -> bool:
        """Subscribe to jax's compile duration events. Idempotent per
        ledger (a second call is a no-op) and sweeps stale listeners
        from a prior module import first, so an event is ledgered at
        most once per process. Returns True when a new listener was
        registered."""
        with self._lock:
            if self._listener is not None:
                return False
        try:
            from jax import monitoring
        except Exception:  # noqa: BLE001 — no jax: wrapper fallback only
            return False

        def _cb(event: str, duration: float, **kwargs: Any) -> None:
            # one compilation fires three duration events; only
            # backend_compile is the XLA wall time (see module doc)
            if not str(event).endswith(COMPILE_EVENT_SUFFIX):
                return
            try:
                self.record(str(kwargs.get("module_name", "") or "xla"),
                            float(duration))
            except Exception:  # noqa: BLE001 — never fail the compile
                log.debug("compile listener failed (continuing)",
                          exc_info=True)

        _cb._kftpu_compile_listener = True  # re-import eviction marker
        _evict_stale_listeners()
        with self._lock:
            if self._listener is not None:  # lost an install race
                return False
            monitoring.register_event_duration_secs_listener(_cb)
            self._listener = _cb
        return True

    def uninstall(self) -> bool:
        """Explicit teardown of the monitoring subscription. Targets
        ONLY this ledger's callback — never jax's global
        clear_event_listeners, which would destroy other subscribers."""
        with self._lock:
            cb, self._listener = self._listener, None
        if cb is None:
            return False
        return _unregister_listener(cb)

    def __enter__(self) -> "CompileLedger":
        self.install()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.uninstall()

    # -- wrapper fallback (AOT path) ---------------------------------------

    def timed_compile(self, fn: Any, *args: Any,
                      module: str = "", **kwargs: Any) -> Any:
        """Lower + compile ``fn`` under the ledger's clock — the
        fallback for backends that emit no monitoring events, and the
        AOT path that ALSO fingerprints the HLO and records the
        ``memory_analysis`` budget beside it. Returns the compiled
        executable (or ``fn`` itself when it has no AOT surface)."""
        lower = getattr(fn, "lower", None)
        if lower is None:
            return fn
        name = module or getattr(fn, "__name__", "") or "xla"
        sc = shape_class_of(*args)
        t0 = self.clock()
        lowered = lower(*args, **kwargs)
        compiled = lowered.compile()
        t1 = self.clock()
        fp = hlo_fingerprint(lowered)
        self.record(name, t1 - t0, shape_class=sc, fingerprint=fp,
                    end=t1)
        try:
            record_memory_budget(compiled, module=name, shape_class=sc,
                                 generation=self.generation,
                                 fingerprint=fp)
        except Exception:  # noqa: BLE001
            log.debug("memory budget failed (continuing)", exc_info=True)
        return compiled


# -- device-memory watermarks ------------------------------------------------


def _device_memory_stats(index: int = 0) -> Optional[Mapping[str, Any]]:
    """``memory_stats()`` of one local device; None on CPU backends
    (which return None) and on any probe failure — the sampler's
    silent-degrade contract."""
    try:
        import jax

        devices = jax.local_devices()
        if not devices:
            return None
        return devices[min(index, len(devices) - 1)].memory_stats()
    except Exception:  # noqa: BLE001
        return None


class HbmSampler:
    """Samples device-memory watermarks into the ``kftpu_hbm_*``
    gauges and a beacon-ready snapshot.

    ``source`` is the injectable stats callable (tests and the CPU
    smoke inject a fake; production defaults to
    ``jax.local_devices()[i].memory_stats()``). A source returning
    None — every CPU backend — degrades silently: no gauges, no
    beacon fields, no errors. ``peak`` is max-seen across samples so
    a between-sample spike the allocator remembers is never lost."""

    def __init__(self, *, namespace: str = "", job: str = "",
                 worker: Optional[int] = None, model: str = "",
                 source: Optional[Callable[[], Optional[
                     Mapping[str, Any]]]] = None,
                 device_index: int = 0) -> None:
        self.namespace = namespace
        self.job = job
        self.worker = worker
        self.model = model
        self.source = source
        self.device_index = device_index
        self.peak_seen = 0.0
        self.last: Dict[str, float] = {}

    def sample(self) -> Optional[Dict[str, float]]:
        """One watermark sample → gauges; returns the kind → bytes
        dict, or None on silent degrade. Never raises."""
        try:
            stats = (self.source() if self.source is not None
                     else _device_memory_stats(self.device_index))
        except Exception:  # noqa: BLE001 — sampling never fails a step
            log.debug("hbm sample failed (continuing)", exc_info=True)
            return None
        if not stats:
            return None
        try:
            in_use = float(stats.get("bytes_in_use", 0) or 0)
            limit = float(stats.get("bytes_limit", 0) or 0)
            peak = float(stats.get("peak_bytes_in_use", 0) or 0)
            self.peak_seen = max(self.peak_seen, peak, in_use)
            out = {"in_use": in_use, "peak": self.peak_seen,
                   "limit": limit}
            ident = {"namespace": self.namespace, "job": self.job,
                     "worker": self.worker, "model": self.model}
            for kind in HBM_KINDS:
                set_hbm_bytes(kind, out[kind], **ident)
            if limit > 0:
                set_hbm_utilization(in_use / limit, **ident)
            self.last = out
            return out
        except Exception:  # noqa: BLE001
            log.debug("hbm sample failed (continuing)", exc_info=True)
            return None

    def beacon_fields(self) -> Dict[str, Any]:
        """The ``hbm`` block a :class:`~kubeflow_tpu.obs.steps.
        StepTelemetry` beacon carries; empty dict before the first
        successful sample (CPU tier: always empty)."""
        if not self.last:
            return {}
        return {"inUseBytes": int(self.last["in_use"]),
                "peakBytes": int(self.last["peak"]),
                "limitBytes": int(self.last["limit"])}
