"""Workflow engine: DAG workflows + cron scheduling on the cluster.

Reference surface: the argo package (Workflow CRD + workflow-controller,
``/root/reference/kubeflow/argo/argo.libsonnet:13-166``) and the pipeline
package's ScheduledWorkflow controller
(``/root/reference/kubeflow/pipeline/*.libsonnet``). The reference's E2E
harness and kubebench are both Argo DAGs (``testing/workflows/components/
workflows.libsonnet:58-330``, ``kubeflow/kubebench/kubebench-job.libsonnet:
250-396``); this engine runs the same shapes natively: container steps
become Pods, resource steps create CRs and poll a success condition.
"""

from kubeflow_tpu.workflows.workflow import (  # noqa: F401
    WORKFLOW_API_VERSION,
    WORKFLOW_KIND,
    WorkflowSpec,
    container_step,
    resource_step,
    workflow,
)
from kubeflow_tpu.workflows.archive import (  # noqa: F401
    ArtifactStore,
    RunArchive,
    store_artifact,
)
from kubeflow_tpu.workflows.controller import WorkflowController  # noqa: F401
from kubeflow_tpu.workflows.cron import (  # noqa: F401
    SCHEDULED_WORKFLOW_KIND,
    CronSchedule,
    ScheduledWorkflowController,
    scheduled_workflow,
)
