"""Run history + artifact store that survive the workflow controller.

KFP-persistence parity: the reference's pipeline package runs an
api-service backed by MySQL plus a MinIO artifact store so run history
outlives both the Argo controller and the Workflow CRs
(``/root/reference/kubeflow/pipeline/pipeline-apiserver.libsonnet``,
``mysql.libsonnet``, ``minio.libsonnet``). The TPU build collapses that
to two small stores on a PVC/GCS-mounted directory — no database pod to
operate, same durability contract:

- :class:`RunArchive` — one JSON document per run (keyed ns/name/uid),
  written on every status transition, so a deleted Workflow CR or a
  restarted controller loses nothing.
- :class:`ArtifactStore` — content-addressed-ish artifact files under
  ``<root>/<ns>/<run>/<step>/<name>``; workloads report artifacts with
  :func:`store_artifact` (the ``KFTPU_ARTIFACT_DIR`` env the operator
  injects plays the role of Argo's sidecar-upload to MinIO).

The dashboard's runs page reads the merge of live CRs and this archive.
"""

from __future__ import annotations

import json
import logging
import os
import re
import tempfile
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)

ENV_ARCHIVE_DIR = "KFTPU_RUN_ARCHIVE_DIR"
ENV_ARTIFACT_DIR = "KFTPU_ARTIFACT_DIR"

_SAFE = re.compile(r"[^A-Za-z0-9._-]")


def _safe(part: str) -> str:
    """One path segment: strip separators/specials, never empty — and
    never a dot segment ("."/".." pass the character filter but would
    walk out of the store)."""
    part = _SAFE.sub("_", part)
    return "_" if part in ("", ".", "..") else part


def _atomic_write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class RunArchive:
    """Append/update store of workflow run records under ``root``."""

    def __init__(self, root: str) -> None:
        self.root = root

    @classmethod
    def from_env(cls, environ=None) -> Optional["RunArchive"]:
        env = os.environ if environ is None else environ
        root = env.get(ENV_ARCHIVE_DIR)
        return cls(root) if root else None

    def _path(self, ns: str, name: str, uid: str) -> str:
        return os.path.join(self.root, _safe(ns),
                            f"{_safe(name)}.{_safe(uid or 'nouid')}.json")

    def record(self, wf: Dict[str, Any]) -> None:
        """Persist the run's current spec+status (idempotent, atomic)."""
        md = wf.get("metadata", {})
        rec = {
            "name": md.get("name", ""),
            "namespace": md.get("namespace", ""),
            "uid": md.get("uid", ""),
            "labels": md.get("labels", {}) or {},
            "spec": wf.get("spec", {}),
            "status": wf.get("status", {}),
        }
        try:
            self._write(rec)
        except OSError:
            # archive unavailability must never wedge reconciliation —
            # the CR still carries the status; log and move on
            log.exception("run archive write failed for %s/%s",
                          rec["namespace"], rec["name"])

    def _write(self, rec: Dict[str, Any]) -> None:
        _atomic_write(
            self._path(rec["namespace"], rec["name"], rec["uid"]),
            json.dumps(rec, sort_keys=True).encode())

    def list(self, ns: str) -> List[Dict[str, Any]]:
        """Run summaries for a namespace, newest start first."""
        d = os.path.join(self.root, _safe(ns))
        out = []
        try:
            files = os.listdir(d)
        except OSError:
            return []
        for fn in files:
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(d, fn)) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            status = rec.get("status", {})
            nodes = status.get("nodes", {}) or {}
            out.append({
                "name": rec.get("name", ""),
                "uid": rec.get("uid", ""),
                "phase": status.get("phase", ""),
                "startedAt": status.get("startedAt", ""),
                "finishedAt": status.get("finishedAt", ""),
                "steps": len(nodes),
                "succeededSteps": sum(
                    1 for n in nodes.values()
                    if n.get("phase") == "Succeeded"),
            })
        out.sort(key=lambda r: r.get("startedAt", ""), reverse=True)
        return out

    def get(self, ns: str, name: str,
            uid: str = "") -> Optional[Dict[str, Any]]:
        """Full record; without ``uid``, the newest run of that name."""
        if uid:
            try:
                with open(self._path(ns, name, uid)) as f:
                    return json.load(f)
            except (OSError, ValueError):
                return None
        candidates = []
        d = os.path.join(self.root, _safe(ns))
        try:
            files = os.listdir(d)
        except OSError:
            return None
        prefix = f"{_safe(name)}."
        for fn in files:
            if fn.startswith(prefix) and fn.endswith(".json"):
                try:
                    with open(os.path.join(d, fn)) as f:
                        candidates.append(json.load(f))
                except (OSError, ValueError):
                    continue
        if not candidates:
            return None
        candidates.sort(
            key=lambda r: r.get("status", {}).get("startedAt", ""))
        return candidates[-1]


class ArtifactStore:
    """File/PVC-backed artifact store (the MinIO role, collapsed)."""

    def __init__(self, root: str) -> None:
        self.root = root

    @classmethod
    def from_env(cls, environ=None) -> Optional["ArtifactStore"]:
        env = os.environ if environ is None else environ
        root = env.get(ENV_ARTIFACT_DIR)
        return cls(root) if root else None

    def _dir(self, ns: str, run: str, step: str) -> str:
        # step may be a NESTED path (list() reports os.walk relpaths like
        # "train/ckpt-1000" when a workload wrote a checkpoint tree);
        # sanitize per segment — _safe neutralizes dot segments, so
        # nesting round-trips but nothing escapes the store
        segs = [_safe(s) for s in step.split("/") if s] or ["_"]
        return os.path.join(self.root, _safe(ns), _safe(run), *segs)

    def put(self, ns: str, run: str, step: str, name: str,
            data: bytes) -> str:
        path = os.path.join(self._dir(ns, run, step), _safe(name))
        _atomic_write(path, data)
        return path

    def path(self, ns: str, run: str, step: str, name: str) -> str:
        """Sanitized on-disk path of one artifact (for streamed serving
        — checkpoints can be multi-GB and must not be buffered)."""
        return os.path.join(self._dir(ns, run, step), _safe(name))

    def get(self, ns: str, run: str, step: str, name: str) -> bytes:
        with open(self.path(ns, run, step, name), "rb") as f:
            return f.read()

    def list(self, ns: str, run: str) -> List[Dict[str, Any]]:
        base = os.path.join(self.root, _safe(ns), _safe(run))
        out = []
        for cur, _dirs, files in os.walk(base):
            for fn in files:
                full = os.path.join(cur, fn)
                out.append({
                    "step": os.path.relpath(cur, base),
                    "name": fn,
                    "bytes": os.path.getsize(full),
                })
        out.sort(key=lambda a: (a["step"], a["name"]))
        return out


def store_artifact(name: str, data: bytes, environ=None) -> Optional[str]:
    """Workload-side artifact report (Argo sidecar-upload equivalent).

    Inside a workflow-step pod the controller injects
    ``KFTPU_ARTIFACT_DIR`` plus the run/step identity; a no-op (returns
    None) outside that context so workloads can call it unconditionally.
    """
    env = os.environ if environ is None else environ
    store = ArtifactStore.from_env(env)
    if store is None:
        return None
    return store.put(
        env.get("KFTPU_NAMESPACE", "default"),
        env.get("KFTPU_WORKFLOW_NAME", env.get("KFTPU_JOB_NAME", "run")),
        env.get("KFTPU_WORKFLOW_STEP", "step"),
        name, data)
