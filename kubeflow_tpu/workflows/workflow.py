"""Workflow CR types: DAG of container and resource steps.

Shape parity with Argo as the reference uses it: an entrypoint DAG whose
tasks have ``dependencies``, container templates with parameterized
images/args, and resource templates with ``successCondition`` /
``failureCondition`` polling (the kubebench launch/wait pattern,
``/root/reference/kubeflow/kubebench/kubebench-job.libsonnet:363-376``).
Parameters use ``{{workflow.parameters.name}}`` substitution like the
reference's workflows.libsonnet prototypes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.k8s.client import register_plural
from kubeflow_tpu.manifests.components.tpujob_operator import GROUP, VERSION

WORKFLOW_API_VERSION = f"{GROUP}/{VERSION}"
WORKFLOW_KIND = "Workflow"
WORKFLOW_PLURAL = "workflows"

register_plural(WORKFLOW_KIND, WORKFLOW_PLURAL)

STEP_CONTAINER = "container"
STEP_RESOURCE = "resource"

NODE_PENDING = "Pending"
NODE_RUNNING = "Running"
NODE_SUCCEEDED = "Succeeded"
NODE_FAILED = "Failed"
NODE_SKIPPED = "Skipped"  # dependency failed


def container_step(
    name: str,
    image: str,
    *,
    command: Optional[List[str]] = None,
    args: Optional[List[str]] = None,
    env: Optional[Mapping[str, str]] = None,
    dependencies: Optional[List[str]] = None,
    retries: int = 0,
    volumes: Optional[List[Dict[str, Any]]] = None,
    volume_mounts: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    step: Dict[str, Any] = {
        "name": name,
        "type": STEP_CONTAINER,
        "image": image,
        "dependencies": list(dependencies or []),
    }
    if command:
        step["command"] = list(command)
    if args:
        step["args"] = list(args)
    if env:
        step["env"] = dict(env)
    if retries:
        step["retries"] = retries
    if volumes:
        step["volumes"] = [dict(v) for v in volumes]
    if volume_mounts:
        step["volumeMounts"] = [dict(m) for m in volume_mounts]
    return step


def resource_step(
    name: str,
    action: str,  # create | delete
    manifest: o.Obj,
    *,
    success_condition: str = "",
    failure_condition: str = "",
    dependencies: Optional[List[str]] = None,
    timeout_seconds: float = 3600.0,
) -> Dict[str, Any]:
    return {
        "name": name,
        "type": STEP_RESOURCE,
        "action": action,
        "manifest": manifest,
        "successCondition": success_condition,
        "failureCondition": failure_condition,
        "dependencies": list(dependencies or []),
        "timeoutSeconds": timeout_seconds,
    }


def workflow(name: str, ns: str, steps: List[Dict[str, Any]],
             parameters: Optional[Mapping[str, str]] = None) -> o.Obj:
    spec = {"steps": steps}
    if parameters:
        spec["parameters"] = dict(parameters)
    WorkflowSpec.from_dict(spec)  # validate
    return {
        "apiVersion": WORKFLOW_API_VERSION,
        "kind": WORKFLOW_KIND,
        "metadata": {"name": name, "namespace": ns},
        "spec": spec,
    }


@dataclass
class WorkflowSpec:
    steps: List[Dict[str, Any]] = field(default_factory=list)
    parameters: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, spec: Mapping[str, Any]) -> "WorkflowSpec":
        out = cls(
            steps=list(spec.get("steps", []) or []),
            parameters=dict(spec.get("parameters", {}) or {}),
        )
        out.validate()
        return out

    def validate(self) -> None:
        if not self.steps:
            raise ValueError("workflow needs at least one step")
        names = [s.get("name", "") for s in self.steps]
        if len(set(names)) != len(names) or "" in names:
            raise ValueError(f"step names must be unique and non-empty: "
                             f"{names}")
        known = set(names)
        for s in self.steps:
            stype = s.get("type")
            if stype not in (STEP_CONTAINER, STEP_RESOURCE):
                raise ValueError(
                    f"step {s.get('name')!r}: unknown type {stype!r}")
            for dep in s.get("dependencies", []) or []:
                if dep not in known:
                    raise ValueError(
                        f"step {s['name']!r} depends on unknown {dep!r}")
        self._check_acyclic()

    def _check_acyclic(self) -> None:
        deps = {s["name"]: set(s.get("dependencies", []) or [])
                for s in self.steps}
        done: set = set()
        while deps:
            ready = [n for n, d in deps.items() if d <= done]
            if not ready:
                raise ValueError(f"dependency cycle among {sorted(deps)}")
            for n in ready:
                done.add(n)
                del deps[n]

    def step(self, name: str) -> Dict[str, Any]:
        for s in self.steps:
            if s["name"] == name:
                return s
        raise KeyError(name)

    def ready_steps(self, node_phases: Mapping[str, str]) -> List[str]:
        """Steps whose dependencies all Succeeded and that haven't started."""
        out = []
        for s in self.steps:
            name = s["name"]
            if node_phases.get(name, NODE_PENDING) != NODE_PENDING:
                continue
            if all(node_phases.get(d) == NODE_SUCCEEDED
                   for d in s.get("dependencies", []) or []):
                out.append(name)
        return out


def substitute_params(value: Any, params: Mapping[str, str]) -> Any:
    """Replace ``{{workflow.parameters.<name>}}`` in strings, deep."""
    if isinstance(value, str):
        out = value
        for k, v in params.items():
            out = out.replace("{{workflow.parameters.%s}}" % k, str(v))
        return out
    if isinstance(value, Mapping):
        return {k: substitute_params(v, params) for k, v in value.items()}
    if isinstance(value, list):
        return [substitute_params(v, params) for v in value]
    return value


def eval_condition(obj: Optional[o.Obj], condition: str) -> bool:
    """Evaluate an Argo-style condition against an object.

    Supported forms (what the reference workflows actually use):
    ``status.startTime`` (field presence), ``status.phase == Succeeded``,
    ``status.phase != Failed``.
    """
    if not condition:
        return False
    if obj is None:
        return False
    cond = condition.strip()
    for op in ("==", "!="):
        if op in cond:
            path, _, want = cond.partition(op)
            got = _lookup(obj, path.strip())
            eq = str(got) == want.strip()
            return eq if op == "==" else (got is not None and not eq)
    return _lookup(obj, cond) not in (None, "", [], {})


def _lookup(obj: Any, dotted: str) -> Any:
    cur = obj
    for part in dotted.split("."):
        if not isinstance(cur, Mapping) or part not in cur:
            return None
        cur = cur[part]
    return cur
