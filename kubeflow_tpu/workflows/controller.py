"""Workflow controller: drives DAG steps to completion.

The reference deploys Argo's workflow-controller for this
(``/root/reference/kubeflow/argo/argo.libsonnet:37-90``); the shapes it
must execute are the E2E DAG (container tasks with shared volumes) and
kubebench's resource create/wait steps. Container steps become Pods;
resource steps create an object then poll its success/failure condition.
Whole-step retries mirror Argo's retryStrategy.
"""

from __future__ import annotations

import calendar
import hashlib
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.k8s.client import ApiError, KubeClient
from kubeflow_tpu.k8s.helpers import (
    create_if_absent,
    delete_ignore_missing,
    update_status_ignore_missing,
)
from kubeflow_tpu.obs import SpanContext, Tracer
from kubeflow_tpu.operators.controller import Controller
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.clock import Clock
from kubeflow_tpu.workflows.workflow import (
    NODE_FAILED,
    NODE_PENDING,
    NODE_RUNNING,
    NODE_SKIPPED,
    NODE_SUCCEEDED,
    STEP_CONTAINER,
    STEP_RESOURCE,
    WORKFLOW_API_VERSION,
    WORKFLOW_KIND,
    WorkflowSpec,
    eval_condition,
    substitute_params,
)

log = logging.getLogger(__name__)

WORKFLOW_LABEL = "kubeflow-tpu.org/workflow-name"
STEP_LABEL = "kubeflow-tpu.org/workflow-step"

PHASE_RUNNING = "Running"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"

_steps_run = DEFAULT_REGISTRY.counter(
    "kftpu_workflow_steps_total", "workflow steps launched")


def workflow_trace_ids(ns: str, name: str, uid: str) -> Tuple[str, str]:
    """Deterministic ``(trace_id, root span_id)`` for a Workflow CR.

    Derived from object identity (not stored in status) so every
    reconcile pass — across controller restarts — lands its step spans
    in the SAME trace, and an operator can compute the trace id from
    ``kubectl get`` output alone."""
    h = hashlib.sha256(f"wf/{ns}/{name}/{uid}".encode()).hexdigest()
    return h[:32], h[32:48]


def _parse_ts(stamp: str) -> Optional[float]:
    try:
        return float(calendar.timegm(
            time.strptime(stamp, "%Y-%m-%dT%H:%M:%SZ")))
    except (TypeError, ValueError):
        return None


class WorkflowController:
    """Reconciles Workflow CRs on any :class:`KubeClient`.

    ``archive`` (a :class:`kubeflow_tpu.workflows.archive.RunArchive`)
    persists every status transition, so run history survives controller
    restarts and CR deletion — the KFP persistence-agent role.

    ``clock`` is the injectable epoch-seconds source used for resource
    step timeouts (wall clock, not monotonic: deadlines are compared
    against ``startedAt`` timestamps persisted in CR status, which must
    survive controller restarts); tests drive a fake clock."""

    def __init__(self, client: KubeClient,
                 namespace: Optional[str] = None,
                 archive=None,
                 clock: Optional[Clock] = None,
                 tracer: Optional[Tracer] = None) -> None:
        self.client = client
        self.namespace = namespace
        self.archive = archive
        self.clock: Clock = clock if clock is not None else time.time
        # step/workflow spans share the controller's (possibly fake)
        # clock, so traces stay deterministic wherever timeouts are
        self.tracer = tracer if tracer is not None else Tracer(
            clock=self.clock)

    def _now(self) -> str:
        """Status timestamps (startedAt/finishedAt) derive from the SAME
        injected clock the deadline check reads — a half-threaded clock
        would make timeouts compare fake time against real timestamps
        and never (or always) fire."""
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(self.clock()))

    # -- reconcile ---------------------------------------------------------

    def reconcile(self, ns: str, name: str) -> Optional[float]:
        wf = self.client.get_or_none(WORKFLOW_API_VERSION, WORKFLOW_KIND,
                                     ns, name)
        if wf is None:
            return None
        if wf.get("status", {}).get("phase") in (PHASE_SUCCEEDED,
                                                 PHASE_FAILED):
            return None
        try:
            spec = WorkflowSpec.from_dict(
                substitute_params(wf["spec"], (wf["spec"].get("parameters")
                                               or {})))
        except (ValueError, KeyError) as e:
            self._set_status(wf, {"phase": PHASE_FAILED,
                                  "message": f"invalid spec: {e}"})
            return None

        import copy

        # deep copy: _advance/_launch mutate node dicts, and a shallow copy
        # would alias wf["status"] so _set_status's no-change check would
        # compare the mutated status against itself and skip the write
        nodes: Dict[str, Dict[str, Any]] = copy.deepcopy(
            wf.get("status", {}).get("nodes", {}))

        # 1. advance running nodes from observed pod/resource state
        for s in spec.steps:
            node = nodes.get(s["name"])
            if node and node.get("phase") == NODE_RUNNING:
                self._advance(ns, wf, s, node)

        # 2. propagate skips from failed/skipped dependencies
        changed = True
        while changed:
            changed = False
            for s in spec.steps:
                node = nodes.setdefault(s["name"], {"phase": NODE_PENDING})
                if node["phase"] != NODE_PENDING:
                    continue
                dep_phases = [nodes.get(d, {}).get("phase", NODE_PENDING)
                              for d in s.get("dependencies", [])]
                if any(p in (NODE_FAILED, NODE_SKIPPED) for p in dep_phases):
                    node.update({"phase": NODE_SKIPPED,
                                 "message": "dependency failed"})
                    changed = True

        # 3. launch ready steps
        phases = {k: v.get("phase", NODE_PENDING) for k, v in nodes.items()}
        for step_name in spec.ready_steps(phases):
            self._launch(ns, wf, spec.step(step_name), nodes[step_name])

        # 4. summarize
        phases = {k: v.get("phase", NODE_PENDING) for k, v in nodes.items()}
        status: Dict[str, Any] = {"nodes": nodes, "phase": PHASE_RUNNING}
        if all(p == NODE_SUCCEEDED for p in phases.values()):
            status["phase"] = PHASE_SUCCEEDED
            status["finishedAt"] = self._now()
        elif (any(p in (NODE_FAILED, NODE_SKIPPED) for p in phases.values())
              and not any(p in (NODE_PENDING, NODE_RUNNING)
                          for p in phases.values())):
            status["phase"] = PHASE_FAILED
            status["finishedAt"] = self._now()
        if "startedAt" not in wf.get("status", {}):
            status["startedAt"] = self._now()
        else:
            status["startedAt"] = wf["status"]["startedAt"]
        self._set_status(wf, status)
        if status["phase"] in (PHASE_SUCCEEDED, PHASE_FAILED):
            self._record_workflow_span(ns, wf, status)
        return None if status["phase"] != PHASE_RUNNING else 1.0

    # -- step execution ----------------------------------------------------

    def _pod_name(self, wf_name: str, step: Dict[str, Any],
                  attempt: int) -> str:
        base = f"{wf_name}-{step['name']}"
        return base if attempt == 0 else f"{base}-r{attempt}"

    def _launch(self, ns: str, wf: o.Obj, step: Dict[str, Any],
                node: Dict[str, Any]) -> None:
        _steps_run.inc()
        wf_name = wf["metadata"]["name"]
        node["startedAt"] = self._now()
        if step["type"] == STEP_CONTAINER:
            attempt = int(node.get("attempt", 0))
            env = dict(step.get("env") or {})
            # artifact-store identity for kubeflow_tpu.workflows.archive.
            # store_artifact (the Argo sidecar-upload contract)
            env.setdefault("KFTPU_WORKFLOW_NAME", wf_name)
            env.setdefault("KFTPU_WORKFLOW_STEP", step["name"])
            env.setdefault("KFTPU_NAMESPACE", ns)
            import os as _os

            if _os.environ.get("KFTPU_ARTIFACT_DIR"):
                env.setdefault("KFTPU_ARTIFACT_DIR",
                               _os.environ["KFTPU_ARTIFACT_DIR"])
            pod = o.pod(
                self._pod_name(wf_name, step, attempt), ns,
                o.pod_spec(
                    [o.container(
                        "main", step["image"],
                        command=step.get("command"),
                        args=step.get("args"),
                        env=env,
                        volume_mounts=step.get("volumeMounts"),
                    )],
                    restart_policy="Never",
                    volumes=step.get("volumes"),
                ),
                labels={WORKFLOW_LABEL: wf_name, STEP_LABEL: step["name"]},
            )
            o.set_owner(pod, wf)
            create_if_absent(self.client, pod)
            node["podName"] = pod["metadata"]["name"]
            node["phase"] = NODE_RUNNING
        else:  # resource step
            manifest = step["manifest"]
            if step.get("action", "create") == "delete":
                md = manifest.get("metadata", {})
                delete_ignore_missing(self.client, manifest["apiVersion"],
                                      manifest["kind"],
                                      md.get("namespace", ns), md["name"])
                node["phase"] = NODE_SUCCEEDED
                node["finishedAt"] = self._now()
                self._record_step_span(ns, wf, step, node)
                return
            manifest = dict(manifest)
            manifest.setdefault("metadata", {}).setdefault("namespace", ns)
            create_if_absent(self.client, manifest)
            node["phase"] = NODE_RUNNING
            if not step.get("successCondition"):
                # fire-and-forget create
                node["phase"] = NODE_SUCCEEDED
                node["finishedAt"] = self._now()
                self._record_step_span(ns, wf, step, node)

    def _advance(self, ns: str, wf: o.Obj, step: Dict[str, Any],
                 node: Dict[str, Any]) -> None:
        if step["type"] == STEP_CONTAINER:
            pod = self.client.get_or_none("v1", "Pod", ns,
                                          node.get("podName", ""))
            phase = (pod or {}).get("status", {}).get("phase")
            if phase == "Succeeded":
                node["phase"] = NODE_SUCCEEDED
                node["finishedAt"] = self._now()
                self._record_step_span(ns, wf, step, node)
            elif phase == "Failed" or pod is None:
                attempt = int(node.get("attempt", 0))
                if attempt < int(step.get("retries", 0)):
                    node["attempt"] = attempt + 1
                    node["phase"] = NODE_PENDING  # relaunched next pass
                    node["message"] = f"retry {attempt + 1}"
                else:
                    node["phase"] = NODE_FAILED
                    node["finishedAt"] = self._now()
                    node["message"] = "pod failed"
                    self._record_step_span(ns, wf, step, node)
            return
        # resource step: poll conditions against the live object
        manifest = step["manifest"]
        md = manifest.get("metadata", {})
        target = self.client.get_or_none(
            manifest["apiVersion"], manifest["kind"],
            md.get("namespace", ns), md["name"])
        if eval_condition(target, step.get("failureCondition", "")):
            node["phase"] = NODE_FAILED
            node["finishedAt"] = self._now()
            node["message"] = f"failureCondition {step['failureCondition']!r}"
            self._record_step_span(ns, wf, step, node)
        elif eval_condition(target, step.get("successCondition", "")):
            node["phase"] = NODE_SUCCEEDED
            node["finishedAt"] = self._now()
            self._record_step_span(ns, wf, step, node)
        else:
            # startedAt was written with gmtime; compare in the same
            # frame. A malformed stamp anchors the deadline at "now"
            # (restarting the timeout) rather than failing reconcile.
            started = _parse_ts(node.get("startedAt", ""))
            if started is None:
                started = self.clock()
            if self.clock() - started > float(
                    step.get("timeoutSeconds", 3600.0)):
                node["phase"] = NODE_FAILED
                node["finishedAt"] = self._now()
                node["message"] = "timeout"
                self._record_step_span(ns, wf, step, node)

    # -- tracing -----------------------------------------------------------

    def _wf_trace(self, ns: str, wf: o.Obj) -> Tuple[str, str]:
        md = wf.get("metadata", {})
        return workflow_trace_ids(ns, md.get("name", ""),
                                  md.get("uid", ""))

    def _record_step_span(self, ns: str, wf: o.Obj, step: Dict[str, Any],
                          node: Dict[str, Any]) -> None:
        """One span per completed step, in the workflow's trace.

        Every reconcile pass derives the SAME trace_id from object
        identity, so a workflow's steps — observed seconds or days
        apart, possibly by different controller processes — assemble
        into one tree. Span ids derive from (step, attempt): a restart
        replaying a transition re-records the identical span instead of
        forking the tree."""
        start = _parse_ts(node.get("startedAt", ""))
        end = _parse_ts(node.get("finishedAt", ""))
        if start is None or end is None:
            return
        trace_id, root_id = self._wf_trace(ns, wf)
        attempt = int(node.get("attempt", 0))
        span_id = hashlib.sha256(
            f"{trace_id}/{step['name']}/{attempt}".encode()
        ).hexdigest()[:16]
        phase = node.get("phase", "")
        self.tracer.record(
            f"workflow.step/{step['name']}",
            start=start, end=end,
            parent=SpanContext(trace_id, root_id), span_id=span_id,
            attrs={"workflow": wf["metadata"]["name"],
                   "step": step["name"], "type": step["type"],
                   "attempt": attempt, "phase": phase,
                   "message": node.get("message", "")},
            status="OK" if phase == NODE_SUCCEEDED else f"ERROR: {phase}")

    def _record_workflow_span(self, ns: str, wf: o.Obj,
                              status: Dict[str, Any]) -> None:
        """The root span, recorded once when the workflow reaches a
        terminal phase (reconcile early-returns on terminal CRs, so
        this transition happens exactly once per run)."""
        start = _parse_ts(status.get("startedAt", ""))
        end = _parse_ts(status.get("finishedAt", ""))
        if start is None or end is None:
            return
        trace_id, root_id = self._wf_trace(ns, wf)
        nodes = status.get("nodes", {})
        phase = status.get("phase", "")
        self.tracer.record(
            f"workflow/{wf['metadata']['name']}",
            start=start, end=end, trace_id=trace_id, span_id=root_id,
            attrs={"workflow": wf["metadata"]["name"],
                   "namespace": ns, "phase": phase,
                   "steps": len(nodes)},
            status="OK" if phase == PHASE_SUCCEEDED
            else f"ERROR: {phase}")

    # -- helpers -----------------------------------------------------------

    def _set_status(self, wf: o.Obj, status: Dict[str, Any]) -> None:
        merged = {**wf.get("status", {}), **status}
        if wf.get("status") == merged:
            return
        wf = dict(wf)
        wf["status"] = merged
        update_status_ignore_missing(self.client, wf)
        if self.archive is not None:
            self.archive.record(wf)

    # -- runtime -----------------------------------------------------------

    def build_controller(self) -> Controller:
        ctrl = Controller(
            self.client, WORKFLOW_API_VERSION, WORKFLOW_KIND, self.reconcile,
            namespace=self.namespace, name="workflow-controller",
            resync_period_s=5.0, tracer=self.tracer,
        )

        def pod_to_wf(pod: o.Obj):
            labels = pod.get("metadata", {}).get("labels", {}) or {}
            wf = labels.get(WORKFLOW_LABEL)
            if wf:
                return (pod["metadata"].get("namespace", ""), wf)
            return None

        ctrl.watch_owned("v1", "Pod", pod_to_wf)
        return ctrl


def main() -> None:
    import os

    from kubeflow_tpu.k8s.client import HttpKubeClient

    from kubeflow_tpu.workflows.archive import RunArchive

    logging.basicConfig(level=logging.INFO)
    ns = os.environ.get("KFTPU_WORKFLOW_NAMESPACE") or None
    WorkflowController(
        HttpKubeClient(), namespace=ns,
        archive=RunArchive.from_env()).build_controller().run_forever()


if __name__ == "__main__":
    main()
