"""ScheduledWorkflow: cron-triggered Workflow creation.

Reference: the pipeline package's scheduledworkflow CRD controller
(``/root/reference/kubeflow/pipeline/*.libsonnet``, parts list
``parts.yaml:38-39``) — a schedule spec periodically stamps out Workflow
CRs from a template. Supports 5-field cron expressions (minute hour dom
month dow) with ``*``, lists, ranges, and ``*/n`` steps, plus a simple
``intervalSeconds`` mode.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.k8s.client import ApiError, KubeClient, register_plural
from kubeflow_tpu.k8s.helpers import (
    create_if_absent,
    delete_ignore_missing,
    update_status_ignore_missing,
)
from kubeflow_tpu.manifests.components.tpujob_operator import GROUP, VERSION
from kubeflow_tpu.operators.controller import Controller
from kubeflow_tpu.workflows.workflow import (
    WORKFLOW_API_VERSION,
    WORKFLOW_KIND,
    WorkflowSpec,
)

log = logging.getLogger(__name__)

SCHEDULED_WORKFLOW_API_VERSION = f"{GROUP}/{VERSION}"
SCHEDULED_WORKFLOW_KIND = "ScheduledWorkflow"
SCHEDULED_WORKFLOW_PLURAL = "scheduledworkflows"

register_plural(SCHEDULED_WORKFLOW_KIND, SCHEDULED_WORKFLOW_PLURAL)


class CronField:
    """One field of a cron expression: ``*``, ``*/n``, ``a-b``, ``a,b,c``."""

    def __init__(self, expr: str, lo: int, hi: int) -> None:
        self.values = self._parse(expr, lo, hi)

    @staticmethod
    def _parse(expr: str, lo: int, hi: int) -> frozenset:
        out: set = set()
        for part in expr.split(","):
            step = 1
            if "/" in part:
                part, _, step_s = part.partition("/")
                step = int(step_s)
            if part == "*":
                rng = range(lo, hi + 1)
            elif "-" in part:
                a, _, b = part.partition("-")
                rng = range(int(a), int(b) + 1)
            else:
                rng = range(int(part), int(part) + 1)
            for v in rng:
                if v < lo or v > hi:
                    raise ValueError(f"cron value {v} outside [{lo},{hi}]")
                if (v - rng.start) % step == 0:
                    out.add(v)
        return frozenset(out)

    def matches(self, v: int) -> bool:
        return v in self.values


@dataclass(frozen=True)
class CronSchedule:
    minute: CronField
    hour: CronField
    dom: CronField
    month: CronField
    dow: CronField

    @classmethod
    def parse(cls, expr: str) -> "CronSchedule":
        parts = expr.split()
        if len(parts) != 5:
            raise ValueError(f"cron needs 5 fields, got {expr!r}")
        return cls(
            minute=CronField(parts[0], 0, 59),
            hour=CronField(parts[1], 0, 23),
            dom=CronField(parts[2], 1, 31),
            month=CronField(parts[3], 1, 12),
            dow=CronField(parts[4], 0, 6),
        )

    def matches(self, t: float) -> bool:
        tm = time.gmtime(t)
        # struct_time: Monday=0..Sunday=6; cron: Sunday=0..Saturday=6
        return (self.minute.matches(tm.tm_min)
                and self.hour.matches(tm.tm_hour)
                and self.dom.matches(tm.tm_mday)
                and self.month.matches(tm.tm_mon)
                and self.dow.matches((tm.tm_wday + 1) % 7))

    def next_after(self, t: float, horizon_s: float = 366 * 86400) -> float:
        """Next matching minute strictly after t."""
        # scan minute boundaries; cron resolution is one minute
        start = (int(t) // 60 + 1) * 60
        for m in range(int(horizon_s // 60)):
            cand = start + m * 60
            if self.matches(cand):
                return float(cand)
        raise ValueError("no cron match within horizon")

    def prev_at_or_before(self, t: float,
                          horizon_s: float = 366 * 86400) -> Optional[float]:
        """Most recent matching minute at or before t, or None."""
        start = (int(t) // 60) * 60
        for m in range(int(horizon_s // 60)):
            cand = start - m * 60
            if self.matches(cand):
                return float(cand)
        return None


def scheduled_workflow(name: str, ns: str, workflow_spec: Dict[str, Any], *,
                       cron: str = "", interval_seconds: float = 0,
                       max_history: int = 5) -> o.Obj:
    if not cron and not interval_seconds:
        raise ValueError("need cron or intervalSeconds")
    if cron:
        CronSchedule.parse(cron)
    WorkflowSpec.from_dict(workflow_spec)
    return {
        "apiVersion": SCHEDULED_WORKFLOW_API_VERSION,
        "kind": SCHEDULED_WORKFLOW_KIND,
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "cron": cron,
            "intervalSeconds": interval_seconds,
            "maxHistory": max_history,
            "workflowSpec": workflow_spec,
        },
    }


class ScheduledWorkflowController:
    """Stamps out Workflow CRs on schedule; prunes old runs."""

    def __init__(self, client: KubeClient,
                 namespace: Optional[str] = None,
                 clock=time.time) -> None:
        self.client = client
        self.namespace = namespace
        self.clock = clock

    def reconcile(self, ns: str, name: str) -> Optional[float]:
        swf = self.client.get_or_none(SCHEDULED_WORKFLOW_API_VERSION,
                                      SCHEDULED_WORKFLOW_KIND, ns, name)
        if swf is None:
            return None
        if swf.get("status", {}).get("phase") == "Failed":
            return None  # invalid schedule; edit the spec to recover
        spec = swf.get("spec", {})
        now = self.clock()
        last_run = float(swf.get("status", {}).get("lastRunTime", 0))

        try:
            due, next_delay = self._due(spec, last_run, now)
        except ValueError as e:
            # invalid cron / neither cron nor interval: fail fast instead of
            # the 5s error-retry hot loop (workflow controller does the same)
            self._set_status(swf, {"phase": "Failed",
                                   "message": f"invalid schedule: {e}"})
            return None
        if due:
            run_name = f"{name}-{int(now)}"
            wf = {
                "apiVersion": WORKFLOW_API_VERSION,
                "kind": WORKFLOW_KIND,
                "metadata": {"name": run_name, "namespace": ns,
                             "labels": {"kubeflow-tpu.org/scheduled-by": name}},
                "spec": dict(spec.get("workflowSpec", {})),
            }
            o.set_owner(wf, swf)
            create_if_absent(self.client, wf)
            swf = dict(swf)
            swf["status"] = {**swf.get("status", {}),
                             "lastRunTime": now,
                             "runs": int(swf.get("status", {})
                                         .get("runs", 0)) + 1}
            update_status_ignore_missing(self.client, swf)
        self._prune(ns, name, int(spec.get("maxHistory", 5)))
        return next_delay

    def _due(self, spec: Dict[str, Any], last_run: float,
             now: float) -> tuple:
        interval = float(spec.get("intervalSeconds", 0) or 0)
        cron_expr = spec.get("cron", "")
        if interval:
            if now - last_run >= interval:
                return True, interval
            return False, interval - (now - last_run)
        if not cron_expr:
            raise ValueError("need cron or intervalSeconds")
        sched = CronSchedule.parse(cron_expr)
        delay = max(sched.next_after(now) - now, 1.0)
        if not last_run:
            # never ran: fire only when the current minute matches (a fresh
            # schedule shouldn't backfill matches from before it existed)
            return sched.matches(now), delay
        if sched.next_after(last_run) > now:
            return False, max(sched.next_after(last_run) - now, 1.0)
        # A match came due while the controller was down or the worker was
        # busy past the matching minute (e.g. hourly '0 * * * *' reconciled
        # at :01). Like CronJob's startingDeadlineSeconds, judge the MOST
        # RECENT missed occurrence against the backfill window — an old
        # out-of-window miss must not mask a fresh in-window one. The
        # reference's ScheduledWorkflow controller does the same catch-up.
        # floor of one minute so a live match (within its own minute bucket)
        # always fires no matter how small the configured window
        window = max(float(spec.get("catchUpWindowSeconds", 3600)), 60.0)
        latest_missed = sched.prev_at_or_before(now)
        if latest_missed is not None and latest_missed > last_run \
                and now - latest_missed <= window:
            return True, delay
        return False, delay

    def _prune(self, ns: str, name: str, max_history: int) -> None:
        runs = self.client.list(
            WORKFLOW_API_VERSION, WORKFLOW_KIND, ns,
            label_selector={"kubeflow-tpu.org/scheduled-by": name})
        terminal = [r for r in runs
                    if r.get("status", {}).get("phase") in ("Succeeded",
                                                            "Failed")]
        terminal.sort(key=lambda r: r["metadata"]["name"])
        for stale in terminal[:-max_history] if max_history else terminal:
            delete_ignore_missing(self.client, WORKFLOW_API_VERSION,
                                  WORKFLOW_KIND, ns,
                                  stale["metadata"]["name"])

    def _set_status(self, swf: o.Obj, status: Dict[str, Any]) -> None:
        merged = {**swf.get("status", {}), **status}
        if swf.get("status") == merged:
            return
        swf = dict(swf)
        swf["status"] = merged
        update_status_ignore_missing(self.client, swf)

    def build_controller(self) -> Controller:
        return Controller(
            self.client, SCHEDULED_WORKFLOW_API_VERSION,
            SCHEDULED_WORKFLOW_KIND, self.reconcile,
            namespace=self.namespace, name="scheduledworkflow-controller",
            resync_period_s=30.0,
        )


def main() -> None:
    import os

    from kubeflow_tpu.k8s.client import HttpKubeClient

    logging.basicConfig(level=logging.INFO)
    ns = os.environ.get("KFTPU_WORKFLOW_NAMESPACE") or None
    ScheduledWorkflowController(
        HttpKubeClient(), namespace=ns).build_controller().run_forever()


if __name__ == "__main__":
    main()
