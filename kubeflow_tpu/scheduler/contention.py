"""Contention-aware slice-window scoring for gang placement.

"On Scheduling Ring-All-Reduce Learning Jobs in Multi-Tenant GPU
Clusters with Communication Contention" (PAPERS.md, arXiv 2207.07817)
makes the case this module implements for TPU pods: two concurrent
ring-all-reduce jobs whose rings share a physical link slow each other
superlinearly, so the scheduler should pay a *packing* cost (waste) to
buy an *uncontended* window when one exists.

The link model is the platform's inter-slice DCN fabric as a linear
chain: slices are ordered by their inventory ordinal and one DCN link
sits between each adjacent pair. A multi-slice gang placed on slice
ordinals ``lo..hi`` (its chosen window, inclusive) rides every link in
``[lo, hi)`` — including links over intermediate slices it does not
occupy, because cross-slice all-reduce traffic transits them. A
single-slice gang stays on in-slice ICI and loads no DCN link.

:func:`choose_slices_contended` extends the
:func:`~kubeflow_tpu.scheduler.inventory.choose_slices_py` scoring with
a leading contention term: candidate windows are ranked by
``(contention, waste, span, position)``. When every link is unloaded
the ranking degenerates to exactly the native core's ``(waste, span,
position)`` — the twin-parity contract is preserved by *delegating* to
:func:`~kubeflow_tpu.scheduler.inventory.choose_slices` (native when
loaded) in that case, and tests pin the equality.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from kubeflow_tpu.scheduler.inventory import choose_slices, choose_slices_py


def link_load(placed_windows: Sequence[Tuple[int, int]],
              n_slices: int) -> List[int]:
    """Per-DCN-link load from already-placed gangs.

    ``placed_windows`` holds each placed gang's ``(lo, hi)`` slice
    ordinals (inclusive); the result has ``n_slices - 1`` entries where
    entry ``i`` is the number of gangs riding the link between slice
    ``i`` and ``i + 1``.
    """
    load = [0] * max(n_slices - 1, 0)
    for lo, hi in placed_windows:
        if lo > hi:
            lo, hi = hi, lo
        for link in range(max(lo, 0), min(hi, len(load))):
            load[link] += 1
    return load


def window_contention(load: Sequence[int], lo: int, hi: int) -> int:
    """Total shared-link load a gang spanning ``lo..hi`` would ride."""
    if lo > hi:
        lo, hi = hi, lo
    return sum(load[max(lo, 0):min(hi, len(load))])


def choose_slices_contended(
    slice_hosts: Sequence[int],
    free_hosts: Sequence[int],
    want: int,
    need_hosts: int,
    load: Optional[Sequence[int]] = None,
) -> Optional[List[int]]:
    """Contention-aware window selection over the free-slice inventory.

    Same feasibility rules as ``choose_slices_py`` (a slice is usable
    only when fully free and large enough), but windows are ranked by
    ``(contention, waste, span, position)`` so an uncontended window is
    always preferred over a contended one, however tightly the
    contended one packs. The contention term rides ``choose_slices_py``'s
    own window enumeration (its ``score`` hook) — one scoring body, not
    a fork to keep in sync. With no load anywhere the result is
    *exactly* the native/Python twin's: that path delegates to
    :func:`choose_slices` so the parity contract (and the native core's
    speed) is kept.
    """
    if load is None or not any(load):
        return choose_slices(slice_hosts, free_hosts, want, need_hosts)
    return choose_slices_py(
        slice_hosts, free_hosts, want, need_hosts,
        score=lambda w: (window_contention(load, w[0], w[-1]),))
