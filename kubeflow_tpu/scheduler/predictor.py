"""Throughput/duration prediction from early-step telemetry.

"Prediction-Assisted Online Distributed Deep Learning Workload
Scheduling in GPU Clusters" (PAPERS.md, arXiv 2501.05563) argues the
queue should *predict* each job's remaining duration from its earliest
steps and order work shortest-remaining-first — the input it assumes
exists is exactly what PR 5 built: per-job ``stepsPerSec`` / ``lastStep``
flowing from worker beacons into TpuJob CR status.

The model, in the platform's absent-never-wrong house style:

- **analytic shape factor** — cross-slice gangs pay DCN latency every
  all-reduce, so a workload's step rate divides by
  ``1 + penalty * (slices - 1)``. The factor carries a workload's
  observed rate across *shapes* and normalizes observations from
  different shapes into one per-accelerator baseline.
- **online correction** — per-job EWMA over observed ``stepsPerSec``
  (beacon medians are already smoothed per-window; the EWMA absorbs
  recompile spikes and warmup), plus a per-accelerator-class EWMA of
  shape-normalized rates so a job that has not beaconed yet can borrow
  the class baseline.
- **absent never wrong** — :meth:`remaining_seconds` returns ``None``
  when neither the job nor its accelerator class has telemetry, or the
  job has no known ``total_steps``. The queue treats ``None`` as
  "unknown, keep FIFO order", never as a fabricated estimate.

Everything is driven by an injectable :data:`~kubeflow_tpu.utils.clock.
Clock` (TPU003 contract); tests feed observations at fake timestamps.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from kubeflow_tpu.utils.clock import Clock

# fractional step-time penalty per slice beyond the first (DCN hop on
# the all-reduce critical path); calibrated coarse on purpose — the
# online correction owns accuracy, the factor only has to rank shapes
DCN_SLICE_PENALTY = 0.15


def shape_factor(slices: int) -> float:
    """Relative step-time multiplier of a ``slices``-wide gang."""
    return 1.0 + DCN_SLICE_PENALTY * max(int(slices) - 1, 0)


@dataclass
class JobEstimate:
    """What the queue gets per gang: rate now + remaining work."""

    steps_per_sec: float
    last_step: int
    remaining_steps: Optional[int]     # None when total_steps unknown
    remaining_seconds: Optional[float]
    source: str                        # "job" | "class"


class ThroughputPredictor:
    """Estimates per-job throughput and remaining duration.

    ``observe`` ingests one telemetry aggregation (the operator calls it
    each reconcile with the CR-status telemetry view); ``estimate`` /
    ``remaining_seconds`` answer the queue's ordering question. Stale
    observations (older than ``ttl_s``) are ignored rather than trusted:
    a preempted job's frozen rate must not keep ordering the queue
    forever.
    """

    def __init__(self, *, clock: Optional[Clock] = None,
                 alpha: float = 0.4, class_alpha: float = 0.2,
                 ttl_s: float = 3600.0) -> None:
        self.clock: Clock = clock if clock is not None else time.monotonic
        self.alpha = alpha
        self.class_alpha = class_alpha
        self.ttl_s = ttl_s
        # (ns, name) -> (ewma steps/sec, last_step, observed_at)
        self._jobs: Dict[Tuple[str, str], Tuple[float, int, float]] = {}
        # accelerator -> ewma of shape-normalized steps/sec
        self._class_base: Dict[str, float] = {}
        self._lock = threading.Lock()

    # -- ingestion ---------------------------------------------------------

    def observe(self, ns: str, name: str, *, steps_per_sec: float,
                last_step: int, accelerator: str = "",
                slices: int = 1) -> None:
        """Fold one telemetry reading in. Zero/negative rates are
        ignored (a gang that has not stepped yet carries no signal)."""
        rate = float(steps_per_sec or 0.0)
        if rate <= 0.0:
            return
        now = self.clock()
        key = (ns, name)
        with self._lock:
            prev = self._jobs.get(key)
            ewma = (rate if prev is None
                    else self.alpha * rate + (1 - self.alpha) * prev[0])
            self._jobs[key] = (ewma, int(last_step), now)
            if accelerator:
                normalized = rate * shape_factor(slices)
                base = self._class_base.get(accelerator)
                self._class_base[accelerator] = (
                    normalized if base is None
                    else self.class_alpha * normalized
                    + (1 - self.class_alpha) * base)

    def forget(self, ns: str, name: str) -> None:
        """Drop a finished/deleted job's series (class baseline keeps
        what it already learned)."""
        with self._lock:
            self._jobs.pop((ns, name), None)

    # -- estimates ---------------------------------------------------------

    def estimate(self, ns: str, name: str, *,
                 total_steps: Optional[int] = None,
                 accelerator: str = "", slices: int = 1
                 ) -> Optional[JobEstimate]:
        """Best available estimate, or ``None`` when nothing is known.

        Resolution order: the job's own (fresh) telemetry, else the
        accelerator class baseline de-normalized to this gang's shape.
        """
        now = self.clock()
        with self._lock:
            rec = self._jobs.get((ns, name))
            if rec is not None and now - rec[2] > self.ttl_s:
                rec = None
            base = self._class_base.get(accelerator)
        if rec is not None:
            rate, last_step, _ = rec
            source = "job"
        elif base is not None and base > 0:
            rate, last_step, source = base / shape_factor(slices), 0, "class"
        else:
            return None
        remaining_steps: Optional[int] = None
        remaining_seconds: Optional[float] = None
        if total_steps is not None and total_steps > 0:
            remaining_steps = max(int(total_steps) - last_step, 0)
            remaining_seconds = remaining_steps / rate if rate > 0 else None
        return JobEstimate(steps_per_sec=rate, last_step=last_step,
                           remaining_steps=remaining_steps,
                           remaining_seconds=remaining_seconds,
                           source=source)

    def remaining_seconds(self, ns: str, name: str, *,
                          total_steps: Optional[int] = None,
                          accelerator: str = "",
                          slices: int = 1) -> Optional[float]:
        """Shortest-remaining-first key; ``None`` = unknown (the queue
        keeps FIFO order for unknowns rather than guessing)."""
        est = self.estimate(ns, name, total_steps=total_steps,
                            accelerator=accelerator, slices=slices)
        return est.remaining_seconds if est is not None else None
