"""The cluster-level gang queue: admission, ordering, placement, preemption.

PR 5 gave every TpuJob a telemetry stream (steps/sec, MFU, straggler
lag) and the placement layer below already maps a gang onto concrete
slices — but jobs were still placed first-come with no queue, no
priorities, and no preemption. This module is the brain above
:mod:`kubeflow_tpu.scheduler.placement` / ``inventory`` that the
scheduling literature assumes exists:

- **tenancy-quota admission** — a gang enters the queue only while its
  namespace's ``google.com/tpu`` chip quota (the tenancy plane's
  ResourceQuota, :func:`kubeflow_tpu.tenancy.profiles.tpu_chip_quota`)
  covers it; over-quota gangs wait in ``BLOCKED`` and re-admit the
  moment a sibling finishes. Whole gangs only: a gang is placed
  atomically or not at all, never partially.
- **priority/FIFO-hybrid ordering with bounded-wait aging** — priority
  classes strictly dominate; *within* a class, gangs with a predicted
  remaining duration (:class:`~kubeflow_tpu.scheduler.predictor.
  ThroughputPredictor`, fed from PR 5 telemetry) run
  shortest-remaining-first, and unpredicted gangs rank as if their
  remaining time were ``aging_max_wait_s`` minus the time they have
  already waited (absent-never-wrong: the queue never fabricates an
  estimate, it only *ages* the unknown toward the front) — so a
  stream of predicted-short gangs can overtake an unpredicted gang
  for at most ``aging_max_wait_s``, never starve it. Preemption
  victims re-enter at the head of their class.
- **contention-aware placement** — candidate slice windows are scored
  by shared-DCN-link overlap with already-placed gangs
  (:mod:`kubeflow_tpu.scheduler.contention`), so two concurrent
  all-reduce rings never ride the same links when an uncontended
  window exists.
- **checkpoint-preempt-requeue** — when a higher-priority gang cannot
  fit, the queue picks minimum-cost victims (fewest chips freed,
  most-recent checkpoint by the ``checkpoint_step`` lookup —
  ``CheckpointManager.latest_step`` in production) and signals
  checkpoint-and-requeue through the TpuJob CR
  (``status.preemption.requested``); the operator checkpoints, tears
  the gang down, confirms via :meth:`GangQueue.confirm_preempted`, and
  the victim resumes later with its step clock intact
  (``CheckpointManager.restore_or_init`` on the worker side).
- **shrink offers to elastic gangs** — before evicting anyone, a gang
  that declared ``spec.elastic`` (a ``minSlices`` floor) is OFFERED a
  shrink (:meth:`GangQueue.shrink_requested`, ``scheduler.queue.
  shrink`` span, ``status.resize.offered`` nudge): the operator edits
  ``spec.slices`` down, the run checkpoint-reshards onto fewer slices
  and KEEPS MAKING PROGRESS while the preemptor takes the freed
  window — strictly cheaper than eviction (docs/ELASTIC.md).

Every decision is traced (``scheduler.queue.admit`` / ``.predict`` /
``.place`` / ``.preempt`` / ``.requeue`` spans on the gang's
identity-derived TpuJob trace) and metered (``kftpu_queue_depth``,
``kftpu_queue_wait_seconds``, ``kftpu_preemptions_total``); the whole
plane runs deterministically under a fake clock + fake KubeClient.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from kubeflow_tpu.k8s.client import ApiError, KubeClient
from kubeflow_tpu.obs.steps import tpujob_trace_ids
from kubeflow_tpu.obs.trace import SpanContext, Tracer
from kubeflow_tpu.scheduler.contention import (
    choose_slices_contended,
    link_load,
    window_contention,
)
from kubeflow_tpu.scheduler.inventory import GangScheduler, SliceInfo
from kubeflow_tpu.scheduler.predictor import ThroughputPredictor
from kubeflow_tpu.tenancy.profiles import tpu_chip_quota
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

# gang lifecycle inside the queue
QUEUED = "Queued"            # admitted, waiting for capacity
BLOCKED = "QuotaBlocked"     # over tenant quota; re-admitted when it fits
PLACED = "Placed"            # holds concrete slices (or unpinned fallback)
PREEMPTING = "Preempting"    # victim signalled; awaiting checkpoint+teardown

_QUEUE_WAIT_BUCKETS = (0.5, 1, 5, 15, 60, 300, 900, 3600, 4 * 3600.0)

_depth = DEFAULT_REGISTRY.gauge(
    "kftpu_queue_depth", "gangs in the scheduler queue by state")
_wait_h = DEFAULT_REGISTRY.histogram(
    "kftpu_queue_wait_seconds", "submit-to-placement wait per gang",
    buckets=_QUEUE_WAIT_BUCKETS)
_preemptions = DEFAULT_REGISTRY.counter(
    "kftpu_preemptions_total", "gangs preempted for a higher priority gang")
_shrink_offers = DEFAULT_REGISTRY.counter(
    "kftpu_shrink_offers_total",
    "elastic gangs offered a shrink in place of preemption")


@dataclass(frozen=True)
class GangRequest:
    """What the queue needs to know about one gang."""

    namespace: str
    name: str
    slices: int
    hosts_per_slice: int
    chips_per_host: int = 4
    accelerator: str = "v5e-8"
    priority: int = 0
    preemptible: bool = True
    total_steps: Optional[int] = None   # predictor hint (spec.totalSteps)
    uid: str = ""                       # CR uid: identity-derived trace
    # elastic floor (spec.elastic.minSlices): the gang consents to run
    # at this many slices, so the queue may OFFER a shrink instead of
    # preempting it outright. None = fixed shape, never shrinkable.
    min_slices: Optional[int] = None

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)

    @property
    def chips(self) -> int:
        return self.slices * self.hosts_per_slice * self.chips_per_host


@dataclass
class _Entry:
    req: GangRequest
    state: str
    submitted_at: float
    seq: int
    admitted_at: Optional[float] = None
    placed_at: Optional[float] = None
    slice_ids: Optional[List[str]] = None
    window: Optional[Tuple[int, int]] = None   # slice ordinals, inclusive
    head: bool = False                         # requeue-at-class-head flag
    head_seq: int = 0
    blocked_reason: str = ""
    preemptions: int = 0
    last_checkpoint_step: Optional[int] = None
    # set on victims while PREEMPTING: who evicted them + that gang's
    # trace ids, so the requeue span lands in the preemptor's trace
    preempted_by: Optional[Tuple[str, str]] = None
    preemptor_trace: Optional[Tuple[str, str]] = None
    waiting_victims: List[Tuple[str, str]] = field(default_factory=list)
    # shrink offers (docs/ELASTIC.md): on the VICTIM, the slice count
    # the queue asked it to shrink to (the operator applies the spec
    # edit); on the PREEMPTOR, the victims whose shrink it awaits —
    # same no-backfill reservation discipline as waiting_victims
    shrink_to: Optional[int] = None
    waiting_shrinks: List[Tuple[str, str]] = field(default_factory=list)
    # set on the preemptor: slices its confirmed victims must actually
    # free (on a real cluster pods drain through a grace period after
    # confirm) — no further preemption until they read fully free
    pending_free: List[str] = field(default_factory=list)
    # last predict-span signature, so the per-cycle ordering pass only
    # records a span when the estimate changes (not every 5s forever)
    last_predicted: Optional[Tuple] = None


def _slice_ordinal(slice_id: str) -> int:
    return int(slice_id.rsplit("_", 1)[1])


class GangQueue:
    """Priority/FIFO-hybrid gang queue with quota admission + preemption.

    ``checkpoint_step(ns, name)`` is the victim-cost input — the most
    recent persisted checkpoint step (``CheckpointManager.latest_step``
    bound per job in production, a fake in tests); ``None`` means "no
    checkpoint known", the costliest victim to lose. ``quota_fn(ns)``
    overrides the tenant chip-quota source (defaults to the tenancy
    plane's ResourceQuota scan).
    """

    def __init__(self, client: KubeClient, *,
                 clock: Optional[Clock] = None,
                 tracer: Optional[Tracer] = None,
                 predictor: Optional[ThroughputPredictor] = None,
                 checkpoint_step: Optional[
                     Callable[[str, str], Optional[int]]] = None,
                 quota_fn: Optional[
                     Callable[[str], Optional[int]]] = None,
                 aging_max_wait_s: float = 3600.0) -> None:
        self.client = client
        self.clock: Clock = clock if clock is not None else time.monotonic
        self.tracer = tracer if tracer is not None else Tracer(
            clock=self.clock)
        self.predictor = (predictor if predictor is not None
                          else ThroughputPredictor(clock=self.clock))
        self.checkpoint_step = checkpoint_step or (lambda ns, name: None)
        self.quota_fn = (quota_fn if quota_fn is not None
                         else lambda ns: tpu_chip_quota(self.client, ns))
        # fairness aging (bounded wait): an unpredicted gang ranks as if
        # it were a predicted gang whose remaining time shrinks linearly
        # from aging_max_wait_s to 0 as it waits — so a stream of
        # predicted-short gangs can overtake it at most aging_max_wait_s
        # seconds, never forever
        self.aging_max_wait_s = float(aging_max_wait_s)
        self.scheduler = GangScheduler(client)
        self._entries: Dict[Tuple[str, str], _Entry] = {}
        self._seq = 0
        self._head_seq = 0
        self._preempt_count = 0
        self._lock = threading.RLock()

    # -- identity/trace helpers -------------------------------------------

    def _trace(self, req: GangRequest) -> SpanContext:
        trace_id, root_id = tpujob_trace_ids(req.namespace, req.name,
                                             req.uid)
        return SpanContext(trace_id, root_id)

    def _span(self, name: str, req: GangRequest,
              attrs: Dict[str, Any],
              parent: Optional[SpanContext] = None) -> None:
        now = self.clock()
        base = {"namespace": req.namespace, "gang": req.name,
                "priority": req.priority}
        base.update(attrs)
        self.tracer.record(name, start=now, end=now,
                           parent=parent if parent is not None
                           else self._trace(req), attrs=base)

    # -- submission / admission -------------------------------------------

    def submit(self, req: GangRequest) -> str:
        """Idempotently enter a gang; returns its queue state. A
        re-submit with a changed spec (priority edit, resize) updates
        the request and re-runs admission for waiting gangs."""
        with self._lock:
            entry = self._entries.get(req.key)
            if entry is None:
                self._seq += 1
                entry = _Entry(req=req, state=BLOCKED,
                               submitted_at=self.clock(), seq=self._seq)
                self._entries[req.key] = entry
                self._admit(entry)
            elif entry.req != req:
                old = entry.req
                entry.req = req
                if entry.state in (QUEUED, BLOCKED, PLACED):
                    # a changed spec (priority edit, elastic resize)
                    # invalidates any grant — it was sized for the old
                    # shape — and re-runs admission; a PREEMPTING victim
                    # finishes its teardown first
                    entry.slice_ids = None
                    entry.window = None
                    entry.state = BLOCKED
                    self._admit(entry)
                if old.slices != req.slices:
                    # the resize a shrink offer asked for (or any other
                    # reshape) arrived: the offer is settled — release
                    # the preemptor waiting on it so this cycle can
                    # place onto the freed capacity
                    entry.shrink_to = None
                    for e in self._entries.values():
                        e.waiting_shrinks = [
                            k for k in e.waiting_shrinks if k != req.key]
            self._export()
            return entry.state

    def _quota_used(self, ns: str, exclude: Tuple[str, str]) -> int:
        return sum(e.req.chips for k, e in self._entries.items()
                   if e.req.namespace == ns and k != exclude
                   and e.state in (QUEUED, PLACED, PREEMPTING))

    def _admit(self, entry: _Entry) -> None:
        """Quota gate: BLOCKED -> QUEUED when the tenant's chip quota
        covers the gang next to its already-admitted siblings."""
        req = entry.req
        quota = self.quota_fn(req.namespace)
        if quota is not None:
            used = self._quota_used(req.namespace, req.key)
            if used + req.chips > quota:
                entry.state = BLOCKED
                entry.blocked_reason = (
                    f"namespace {req.namespace!r} chip quota {quota} "
                    f"exceeded: {used} in use + {req.chips} requested")
                return
        entry.state = QUEUED
        entry.blocked_reason = ""
        entry.admitted_at = self.clock()
        self._span("scheduler.queue.admit", req,
                   {"chips": req.chips, "quota": quota})

    # -- ordering ----------------------------------------------------------

    def _order_key(self, entry: _Entry) -> Tuple:
        req = entry.req
        remaining = self.predictor.remaining_seconds(
            req.namespace, req.name, total_steps=req.total_steps,
            accelerator=req.accelerator, slices=req.slices)
        signature = (remaining is not None,
                     round(remaining, 3) if remaining is not None else None)
        if signature != entry.last_predicted:
            # span only when the estimate changes — a steady queue must
            # not evict the span ring's incident-debugging window
            entry.last_predicted = signature
            self._span("scheduler.queue.predict", req,
                       {"remainingSeconds": signature[1],
                        "known": signature[0]})
        # priority class desc; requeued victims at the class head (in
        # requeue order); then one merged shortest-remaining scale:
        # predicted gangs rank by remaining seconds, unpredicted gangs
        # by (aging_max_wait_s - waited) — starting as the longest
        # plausible job and AGING toward rank 0, so predicted-short
        # gangs win early but can never starve the unpredicted tail
        # beyond the bound; FIFO (seq) breaks ties
        if remaining is not None:
            rank = remaining
        else:
            since = (entry.admitted_at if entry.admitted_at is not None
                     else entry.submitted_at)
            rank = max(self.aging_max_wait_s
                       - max(self.clock() - since, 0.0), 0.0)
        return (-req.priority,
                (0, entry.head_seq) if entry.head else (1, 0),
                rank,
                entry.seq)

    # -- the scheduling cycle ----------------------------------------------

    def schedule(self) -> None:
        """One cycle: re-admit, order, place what fits, preempt for the
        highest-priority gang that does not. Idempotent and cheap when
        nothing changed; callers run it per reconcile or per tick."""
        with self._lock:
            for entry in sorted(self._entries.values(),
                                key=lambda e: e.seq):
                if entry.state == BLOCKED:
                    self._admit(entry)
            inv_cache: Dict[str, List[SliceInfo]] = {}
            waiting = sorted(
                (e for e in self._entries.values() if e.state == QUEUED),
                key=self._order_key)
            preempt_tried = False
            reserved: set = set()   # accelerators a preempting gang owns
            for entry in waiting:
                if entry.req.accelerator in reserved:
                    continue
                if self._try_place(entry, inv_cache):
                    continue
                if (entry.waiting_victims or entry.waiting_shrinks
                        or entry.pending_free):
                    # this gang paid an eviction (or a shrink offer)
                    # for the next free window on its accelerator:
                    # lower-ordered gangs must not backfill onto it, or
                    # the eviction is wasted and the queue preempts in
                    # a loop
                    reserved.add(entry.req.accelerator)
                    continue
                if not preempt_tried:
                    # only the frontmost unplaced gang may evict — a
                    # lower-ordered gang preempting past it would
                    # invert the queue's own ordering
                    preempt_tried = True
                    self._try_preempt(entry, inv_cache)
                    if entry.waiting_victims or entry.waiting_shrinks:
                        reserved.add(entry.req.accelerator)
            self._export()

    def _inventory(self, inv_cache: Dict[str, List[SliceInfo]],
                   accelerator: str) -> List[SliceInfo]:
        inv = inv_cache.get(accelerator)
        if inv is None:
            # a granted slice is committed the moment the queue places a
            # gang — before the operator creates its pods — so the pod
            # scan alone undercounts; overlay the grants or a later
            # cycle would double-book the window
            granted = {sid for e in self._entries.values()
                       if e.state in (PLACED, PREEMPTING)
                       and e.req.accelerator == accelerator
                       for sid in (e.slice_ids or [])}
            inv = [SliceInfo(slice_id=s.slice_id, shape=s.shape,
                             hosts=s.hosts,
                             free_hosts=0 if s.slice_id in granted
                             else s.free_hosts)
                   for s in self.scheduler.inventory(accelerator)]
            inv_cache[accelerator] = inv
        return inv

    def _placed_windows(self, accelerator: str) -> List[Tuple[int, int]]:
        return [e.window for e in self._entries.values()
                if e.state in (PLACED, PREEMPTING)
                and e.req.accelerator == accelerator
                and e.window is not None]

    def _position_load(self, inv: List[SliceInfo],
                       accelerator: str) -> List[int]:
        """Ordinal-space link load re-indexed to inventory positions
        (identity for the contiguous-ordinal common case)."""
        ordinals = [_slice_ordinal(s.slice_id) for s in inv]
        if not ordinals:
            return []
        load = link_load(self._placed_windows(accelerator),
                         max(ordinals) + 1)
        return [window_contention(load, ordinals[i], ordinals[i + 1])
                for i in range(len(ordinals) - 1)]

    def _try_place(self, entry: _Entry,
                   inv_cache: Dict[str, List[SliceInfo]]) -> bool:
        req = entry.req
        inv = self._inventory(inv_cache, req.accelerator)
        if not inv:
            # no concrete slice inventory (real GKE placement policy
            # owns packing): the queue still orders/gates, placement is
            # unpinned — an empty slice list the operator passes through
            chosen_ids: List[str] = []
            window = None
            contention = 0
        else:
            load = self._position_load(inv, req.accelerator)
            chosen = choose_slices_contended(
                [s.hosts for s in inv], [s.free_hosts for s in inv],
                req.slices, req.hosts_per_slice, load)
            if chosen is None:
                return False
            chosen_ids = [inv[i].slice_id for i in chosen]
            ordinals = [_slice_ordinal(s) for s in chosen_ids]
            window = (min(ordinals), max(ordinals))
            contention = window_contention(
                link_load(self._placed_windows(req.accelerator),
                          max(ordinals) + 1), window[0], window[1])
            for i in chosen:  # claim within this cycle's cached scan
                inv[i] = SliceInfo(slice_id=inv[i].slice_id,
                                   shape=inv[i].shape, hosts=inv[i].hosts,
                                   free_hosts=0)
        now = self.clock()
        entry.state = PLACED
        entry.placed_at = now
        entry.slice_ids = chosen_ids
        entry.window = window
        entry.head = False
        entry.pending_free = []     # the eviction (if any) paid off
        # capacity arrived without the shrink (a sibling finished):
        # revoke the offer so the victim does not needlessly
        # checkpoint-teardown-reshard for nobody
        self._revoke_shrinks(entry)
        wait = max(now - entry.submitted_at, 0.0)
        # exemplar: the gang's identity-derived trace, so a long-wait
        # bucket opens the admit->place span tree that waited
        _wait_h.observe(wait,
                        exemplar_trace_id=self._trace(req).trace_id)
        self._span("scheduler.queue.place", req,
                   {"slices": ",".join(chosen_ids) or "unpinned",
                    "contention": contention,
                    "waitSeconds": round(wait, 3)})
        return True

    # -- preemption --------------------------------------------------------

    # lost-work sentinel for victims whose progress is unobserved: the
    # absent-never-wrong stance applied to eviction — never treat an
    # unknown run as cheap to kill
    _UNKNOWN_LOST = 1 << 30

    def _victim_cost(self, victim: _Entry) -> Tuple:
        """(chips freed, steps of work lost) — fewest chips first, then
        the most recent checkpoint (least lost work). No checkpoint
        costs the whole observed run; no *telemetry* means the lost
        work is unknowable and sorts as maximal, so a silent job is
        never mistaken for a cheap victim."""
        req = victim.req
        est = self.predictor.estimate(
            req.namespace, req.name, accelerator=req.accelerator,
            slices=req.slices)
        if est is None or est.source != "job":
            # a class-baseline estimate says nothing about THIS job's
            # progress either
            lost = self._UNKNOWN_LOST
        else:
            ckpt = self.checkpoint_step(req.namespace, req.name)
            lost = max(est.last_step - (ckpt if ckpt is not None else 0),
                       0)
        return (req.chips, lost, -victim.seq)

    def _try_preempt(self, entry: _Entry,
                     inv_cache: Dict[str, List[SliceInfo]]) -> None:
        req = entry.req
        if entry.waiting_victims or entry.waiting_shrinks:
            # a previous preemption/shrink for this gang is still
            # settling; never widen the blast radius while it does
            return
        inv = self._inventory(inv_cache, req.accelerator)
        if not inv:
            return
        if entry.pending_free:
            # confirmed victims' pods may still be draining (a real
            # cluster's grace period): until every evicted slice reads
            # fully free, the earlier eviction has not settled — do
            # not pick more victims on its account
            by_id = {s.slice_id: s for s in inv}
            for sid in entry.pending_free:
                info = by_id.get(sid)
                if info is not None and info.free_hosts != info.hosts:
                    return
            entry.pending_free = []
        # shrink offers first (docs/ELASTIC.md): an elastic gang that
        # declared a minSlices floor can FREE the needed window without
        # losing its run — strictly cheaper than eviction, so it is
        # tried before any victim is picked. One offer at a time (the
        # no-widened-blast-radius rule applied to shrinks).
        shrinkables = sorted(
            (e for e in self._entries.values()
             if e.state == PLACED
             and e.req.priority < req.priority
             and e.req.accelerator == req.accelerator
             and e.slice_ids
             and e.req.min_slices is not None
             and e.req.min_slices < e.req.slices
             and e.shrink_to is None),
            key=self._victim_cost)
        for victim in shrinkables:
            target = self._best_shrink_target(inv, req, victim)
            if target is not None:
                self._signal_shrink(entry, victim, target)
                entry.waiting_shrinks = [victim.req.key]
                return
        candidates = sorted(
            (e for e in self._entries.values()
             if e.state == PLACED and e.req.preemptible
             and e.req.priority < req.priority
             and e.req.accelerator == req.accelerator
             and e.slice_ids),
            key=self._victim_cost)
        if not candidates:
            return
        chosen = self._victim_set(inv, req, candidates)
        if not chosen:
            return
        for victim in chosen:
            self._signal_preemption(entry, victim)
        entry.waiting_victims = [v.req.key for v in chosen]

    def _victim_set(self, inv: List[SliceInfo], req: GangRequest,
                    candidates: List[_Entry]) -> List[_Entry]:
        """Minimum-cost victim set that actually makes the gang fit:
        the cheapest single sufficient victim, else cheapest-first
        accumulation; empty when even evicting everyone would not."""

        def feasible(victims: List[_Entry]) -> bool:
            freed = {sid for v in victims for sid in (v.slice_ids or [])}
            trial = [SliceInfo(slice_id=s.slice_id, shape=s.shape,
                               hosts=s.hosts,
                               free_hosts=s.hosts if s.slice_id in freed
                               else s.free_hosts)
                     for s in inv]
            return choose_slices_contended(
                [s.hosts for s in trial], [s.free_hosts for s in trial],
                req.slices, req.hosts_per_slice) is not None

        for victim in candidates:           # cheapest sufficient single
            if feasible([victim]):
                return [victim]
        acc: List[_Entry] = []
        for victim in candidates:           # else accumulate by cost
            acc.append(victim)
            if feasible(acc):
                return acc
        return []

    def _best_shrink_target(self, inv: List[SliceInfo], req: GangRequest,
                            victim: _Entry) -> Optional[int]:
        """The LARGEST feasible shrink count in
        ``[min_slices, slices)`` — the victim gives up only what the
        preemptor's window actually needs. Shrinking straight to the
        floor (the pre-ISSUE-12 behavior) threw away slices nobody
        asked for: a 4-slice gang shrank to 1 so a 1-slice preemptor
        could land, losing 2 slices of throughput for nothing. None
        when even the floor doesn't free enough — checked FIRST:
        feasibility is monotone in target (fewer victim slices only
        ever free more), so an infeasible floor rejects in one check
        instead of O(slices) scans on every schedule() tick."""
        floor = victim.req.min_slices
        if not self._shrink_feasible(inv, req, victim, floor):
            return None
        for target in range(victim.req.slices - 1, floor, -1):
            if self._shrink_feasible(inv, req, victim, target):
                return target
        return floor

    def _shrink_feasible(self, inv: List[SliceInfo], req: GangRequest,
                         victim: _Entry, target: int) -> bool:
        """True when, with the victim's slices transiently freed (the
        resize re-places the whole gang), BOTH the preemptor at its
        full size AND the victim at its shrunk ``target`` fit — a
        shrink that leaves the shrunk gang homeless is an eviction
        with extra steps, not an offer."""
        freed = set(victim.slice_ids or [])
        hosts = [s.hosts for s in inv]
        free = [s.hosts if s.slice_id in freed else s.free_hosts
                for s in inv]
        chosen = choose_slices_contended(hosts, free, req.slices,
                                         req.hosts_per_slice)
        if chosen is None:
            return False
        for i in chosen:
            free[i] = 0
        return choose_slices_contended(
            hosts, free, target, victim.req.hosts_per_slice) is not None

    def _signal_shrink(self, entry: _Entry, victim: _Entry,
                       target: int) -> None:
        """Mark the elastic victim and nudge its CR
        (``status.resize.offered``) — the operator's cue to apply the
        ``spec.slices`` edit; the resize then flows through the normal
        snapshot→teardown→re-gang path and :meth:`submit` (seeing the
        new shape) settles the offer."""
        vreq = victim.req
        victim.shrink_to = target
        _shrink_offers.inc()
        self._span("scheduler.queue.shrink", entry.req,
                   {"victim": f"{vreq.namespace}/{vreq.name}",
                    "fromSlices": vreq.slices,
                    "toSlices": target})
        log.info("offering %s/%s (priority %d) a shrink %d -> %d "
                 "slice(s) for %s/%s (priority %d)",
                 vreq.namespace, vreq.name, vreq.priority, vreq.slices,
                 target, entry.req.namespace, entry.req.name,
                 entry.req.priority)
        from kubeflow_tpu.manifests.components.tpujob_operator import (
            API_VERSION,
            TPUJOB_KIND,
        )

        job = self.client.get_or_none(API_VERSION, TPUJOB_KIND,
                                      vreq.namespace, vreq.name)
        if job is None:
            return
        status = dict(job.get("status", {}))
        resize = dict(status.get("resize") or {})
        resize.update({
            "offered": target,
            "by": f"{entry.req.namespace}/{entry.req.name}",
        })
        status["resize"] = resize
        job = dict(job)
        job["status"] = status
        try:
            self.client.update_status(job)
        except ApiError as e:
            if e.code != 404:
                raise

    def _revoke_shrinks(self, entry: _Entry) -> None:
        """Withdraw every shrink offer ``entry`` (the preemptor) was
        waiting on: clear the victims' ``shrink_to`` and best-effort
        erase the ``status.resize.offered`` nudge, so an offer whose
        beneficiary went away (released, or placed elsewhere) never
        costs the victim a checkpoint-teardown-reshard for nothing."""
        for key in entry.waiting_shrinks:
            victim = self._entries.get(key)
            if victim is None or victim.shrink_to is None:
                continue
            victim.shrink_to = None
            self._clear_shrink_nudge(victim.req)
        entry.waiting_shrinks = []

    def _clear_shrink_nudge(self, vreq: GangRequest) -> None:
        from kubeflow_tpu.manifests.components.tpujob_operator import (
            API_VERSION,
            TPUJOB_KIND,
        )

        job = self.client.get_or_none(API_VERSION, TPUJOB_KIND,
                                      vreq.namespace, vreq.name)
        if job is None:
            return
        status = dict(job.get("status", {}))
        resize = dict(status.get("resize") or {})
        if "offered" not in resize:
            return
        resize.pop("offered", None)
        resize.pop("by", None)
        status["resize"] = resize
        job = dict(job)
        job["status"] = status
        try:
            self.client.update_status(job)
        except ApiError as e:
            if e.code != 404:
                raise

    def shrink_requested(self, ns: str, name: str) -> Optional[int]:
        """The slice count this elastic gang was asked to shrink to
        (None = no offer pending) — the operator polls this each
        reconcile and applies the spec edit."""
        with self._lock:
            entry = self._entries.get((ns, name))
            return entry.shrink_to if entry is not None else None

    def _signal_preemption(self, entry: _Entry, victim: _Entry) -> None:
        """Mark the victim and write ``status.preemption.requested``
        on its CR — the operator's cue to checkpoint, tear down, and
        confirm. The CR write doubles as the watch-event nudge when the
        operator runs on the controller runtime."""
        vreq = victim.req
        victim.state = PREEMPTING
        victim.preempted_by = entry.req.key
        ptrace = self._trace(entry.req)
        victim.preemptor_trace = (ptrace.trace_id, ptrace.span_id)
        self._preempt_count += 1
        _preemptions.inc()
        self._span("scheduler.queue.preempt", entry.req,
                   {"victim": f"{vreq.namespace}/{vreq.name}",
                    "victimChips": vreq.chips,
                    "victimPriority": vreq.priority})
        log.info("preempting %s/%s (priority %d) for %s/%s (priority %d)",
                 vreq.namespace, vreq.name, vreq.priority,
                 entry.req.namespace, entry.req.name, entry.req.priority)
        from kubeflow_tpu.manifests.components.tpujob_operator import (
            API_VERSION,
            TPUJOB_KIND,
        )

        job = self.client.get_or_none(API_VERSION, TPUJOB_KIND,
                                      vreq.namespace, vreq.name)
        if job is None:
            return
        status = dict(job.get("status", {}))
        status["preemption"] = {
            "requested": True,
            "by": f"{entry.req.namespace}/{entry.req.name}",
            "count": victim.preemptions + 1,
        }
        job = dict(job)
        job["status"] = status
        try:
            self.client.update_status(job)
        except ApiError as e:
            if e.code != 404:
                raise

    def preemption_requested(self, ns: str, name: str) -> bool:
        with self._lock:
            entry = self._entries.get((ns, name))
            return entry is not None and entry.state == PREEMPTING

    def confirm_preempted(self, ns: str, name: str,
                          checkpoint_step: Optional[int] = None) -> None:
        """The operator checkpointed and tore the victim down: free its
        slices and re-admit it at the head of its priority class with
        its queue position (and the checkpoint's step clock) intact."""
        with self._lock:
            entry = self._entries.get((ns, name))
            if entry is None or entry.state != PREEMPTING:
                return
            preemptor = (self._entries.get(entry.preempted_by)
                         if entry.preempted_by else None)
            if preemptor is not None and entry.slice_ids:
                # the preemptor must watch these slices actually drain
                # (grace periods) before it may evict anyone else
                preemptor.pending_free.extend(entry.slice_ids)
            self._head_seq += 1
            entry.state = QUEUED
            entry.head = True
            entry.head_seq = self._head_seq
            entry.slice_ids = None
            entry.window = None
            entry.preemptions += 1
            entry.last_checkpoint_step = checkpoint_step
            parent = (SpanContext(*entry.preemptor_trace)
                      if entry.preemptor_trace else None)
            self._span("scheduler.queue.requeue", entry.req,
                       {"victim": f"{ns}/{name}",
                        "checkpointStep": checkpoint_step,
                        "atHead": True}, parent=parent)
            if preemptor is not None:
                preemptor.waiting_victims = [
                    k for k in preemptor.waiting_victims if k != (ns, name)]
            entry.preempted_by = None
            entry.preemptor_trace = None
            self._export()

    # -- placement hand-off ------------------------------------------------

    def placement_for(self, ns: str, name: str) -> Optional[List[str]]:
        """Concrete slice ids once placed (``[]`` = placed unpinned),
        ``None`` while the gang still waits."""
        with self._lock:
            entry = self._entries.get((ns, name))
            if entry is None or entry.state != PLACED:
                return None
            return list(entry.slice_ids or [])

    def invalidate_placement(self, ns: str, name: str) -> None:
        """The operator found the granted slices no longer free (an
        actor outside the queue claimed them): back to the queue."""
        with self._lock:
            entry = self._entries.get((ns, name))
            if entry is not None and entry.state == PLACED:
                entry.state = QUEUED
                entry.slice_ids = None
                entry.window = None
                self._export()

    def state_of(self, ns: str, name: str) -> Optional[str]:
        with self._lock:
            entry = self._entries.get((ns, name))
            return entry.state if entry is not None else None

    def blocked_reason(self, ns: str, name: str) -> str:
        with self._lock:
            entry = self._entries.get((ns, name))
            return entry.blocked_reason if entry is not None else ""

    def last_checkpoint_step(self, ns: str, name: str) -> Optional[int]:
        with self._lock:
            entry = self._entries.get((ns, name))
            return entry.last_checkpoint_step if entry is not None else None

    def release(self, ns: str, name: str) -> None:
        """Terminal/deleted gang: drop it, freeing quota and slices.
        Shrink offers it was waiting on are withdrawn — the would-be
        beneficiary is gone, nobody needs the victim's capacity."""
        with self._lock:
            entry = self._entries.pop((ns, name), None)
            if entry is None:
                return
            self._revoke_shrinks(entry)
            self.predictor.forget(ns, name)
            for e in self._entries.values():
                e.waiting_victims = [k for k in e.waiting_victims
                                     if k != (ns, name)]
                e.waiting_shrinks = [k for k in e.waiting_shrinks
                                     if k != (ns, name)]
            self._export()

    # -- observability -----------------------------------------------------

    def _export(self) -> None:
        counts = {QUEUED: 0, BLOCKED: 0, PLACED: 0, PREEMPTING: 0}
        for e in self._entries.values():
            counts[e.state] = counts.get(e.state, 0) + 1
        for state, n in counts.items():
            _depth.set(n, state=state)

    def status(self) -> Dict[str, Any]:
        """The dashboard's ``GET /api/metrics/scheduler`` payload."""
        now = self.clock()
        with self._lock:
            gangs = []
            counts: Dict[str, int] = {QUEUED: 0, BLOCKED: 0, PLACED: 0,
                                      PREEMPTING: 0}
            for e in sorted(self._entries.values(), key=lambda e: e.seq):
                counts[e.state] = counts.get(e.state, 0) + 1
                req = e.req
                gangs.append({
                    "namespace": req.namespace,
                    "name": req.name,
                    "state": e.state,
                    "priority": req.priority,
                    "preemptible": req.preemptible,
                    "chips": req.chips,
                    "accelerator": req.accelerator,
                    "slices": list(e.slice_ids or []),
                    "waitSeconds": round(
                        max((e.placed_at if e.placed_at is not None
                             else now) - e.submitted_at, 0.0), 3),
                    "preemptions": e.preemptions,
                    "blockedReason": e.blocked_reason,
                })
            return {"depth": counts,
                    "preemptionsTotal": self._preempt_count,
                    "gangs": gangs}

    # -- runtime -----------------------------------------------------------

    def build_controller(self, interval_s: float = 5.0):
        """Periodic scheduling on the shared workqueue runtime
        (:mod:`kubeflow_tpu.operators.controller` tick mode): cycles run
        as uniformly-traced reconciles next to the operators'."""
        from kubeflow_tpu.operators.controller import Controller

        def tick(_ns: str, _name: str) -> float:
            self.schedule()
            return interval_s

        return Controller.periodic(tick, name="scheduler-queue",
                                   tracer=self.tracer)


def request_from_spec(ns: str, name: str, spec: Any,
                      uid: str = "") -> GangRequest:
    """Build a :class:`GangRequest` from a parsed
    :class:`~kubeflow_tpu.operators.tpujob.TpuJobSpec` (kept here so the
    queue's view of a spec lives next to the queue)."""
    return GangRequest(
        namespace=ns, name=name, slices=spec.slices,
        hosts_per_slice=spec.hosts_per_slice,
        chips_per_host=spec.chips_per_host,
        accelerator=spec.accelerator, priority=spec.priority,
        preemptible=spec.preemptible,
        total_steps=spec.total_steps or None, uid=uid,
        min_slices=getattr(spec, "min_slices", None))
