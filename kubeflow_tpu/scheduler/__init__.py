"""Slice-aware gang placement + the cluster scheduler plane.

Bottom-up: :mod:`placement` (worker index → slice/host, ICI ring
order), :mod:`inventory` (concrete free slices + best-fit assignment,
native/Python twins), :mod:`contention` (shared-DCN-link window
scoring), :mod:`predictor` (telemetry-driven remaining-duration
estimates), :mod:`queue` (the cluster-level brain: quota admission,
priority/predicted ordering, contention-aware placement,
checkpoint-preempt-requeue). docs/SCHEDULER.md has the protocol.
"""

from kubeflow_tpu.scheduler.placement import (  # noqa: F401
    ACCELERATORS,
    SlicePlacement,
    accelerator_info,
    place_gang,
    ring_order,
)
