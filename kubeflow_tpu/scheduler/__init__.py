"""Slice-aware gang placement for TPU pod slices."""

from kubeflow_tpu.scheduler.placement import (  # noqa: F401
    ACCELERATORS,
    SlicePlacement,
    accelerator_info,
    place_gang,
    ring_order,
)
