"""Slice-aware gang placement (SURVEY.md §7 hard part (a)).

A TPU slice is indivisible and topology-addressed; placement must map a
job's worker index → (slice, host) so that ring/neighbour collectives run
between ICI-adjacent hosts. The reference had nothing comparable — its gang
scheduling was an optional kube-batch podgroup flag with no topology
awareness (``tf-job-operator.libsonnet:107-109``), and GPU placement was a
bare ``nvidia.com/gpu`` resource limit.

Worker→host ordering follows the slice's ICI ring so that
``jax.lax.ppermute``-based ring attention between adjacent process ids rides
one ICI hop. A native (C++) placement core slots in behind
:func:`place_gang` for large inventories.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

from kubeflow_tpu.obs import TRACER

# accelerator type -> (chips, hosts, physical topology string), derived
# from the platform's provisioning inventory so placement and node pools
# can never disagree about slice geometry
from kubeflow_tpu.platform.slices import SLICE_SHAPES  # noqa: E402

ACCELERATORS: Dict[str, Tuple[int, int, str]] = {
    name: (s.chips, s.hosts, s.topology) for name, s in SLICE_SHAPES.items()
}


@dataclass(frozen=True)
class SlicePlacement:
    """Where one worker lands: which slice, which host in it, its topology."""

    slice_index: int
    host: int
    topology: str
    accelerator: str


def accelerator_info(accelerator: str) -> Tuple[int, int, str]:
    if accelerator not in ACCELERATORS:
        known = ", ".join(sorted(ACCELERATORS))
        raise ValueError(f"unknown accelerator {accelerator!r}; known: {known}")
    return ACCELERATORS[accelerator]


def ring_order(n_hosts: int, topology: str) -> List[int]:
    """Host visitation order that is ICI-contiguous.

    For 2-D slices (v5e/v6e ``AxB``), hosts tile the torus row-major in
    2x2-chip blocks; a boustrophedon (snake) walk over host rows keeps every
    consecutive pair physically adjacent, closing the ring via the torus
    wraparound links.
    """
    dims = [int(d) for d in topology.split("x")]
    if len(dims) != 2 or n_hosts <= 2:
        return list(range(n_hosts))
    # hosts form a grid of (rows, cols) = (A/2, B/2) 2x2 blocks on v5e
    rows = max(dims[0] // 2, 1)
    cols = max(n_hosts // rows, 1)
    if rows * cols != n_hosts:
        # partial-slice request that doesn't tile the host grid: identity
        # order (contiguity is best-effort for ragged shapes)
        return list(range(n_hosts))
    order = []
    for r in range(rows):
        cs = range(cols) if r % 2 == 0 else range(cols - 1, -1, -1)
        order.extend(r * cols + c for c in cs)
    return order


def place_gang(
    *, slices: int, hosts_per_slice: int, accelerator: str
) -> List[SlicePlacement]:
    """Assign every worker index a (slice, host) with ICI-ring host order.

    Process ids are laid out slice-major so intra-slice neighbours (the hot
    ring) are consecutive ids, and cross-slice traffic (DCN) only happens
    between blocks of ``hosts_per_slice`` ids.
    """
    chips, max_hosts, topology = accelerator_info(accelerator)
    # a non-positive gang is a caller bug, never an empty placement: the
    # scheduler queue trusts placement errors to be loud (silently
    # returning [] here let a slices<=0 spec "place" a zero-worker gang)
    if slices < 1:
        raise ValueError(f"slices must be >= 1, got {slices}")
    if hosts_per_slice < 1:
        raise ValueError(
            f"hosts_per_slice must be >= 1, got {hosts_per_slice}")
    if hosts_per_slice > max_hosts:
        raise ValueError(
            f"{accelerator} has {max_hosts} hosts; requested {hosts_per_slice}"
        )
    # decision span: which gang got which slices/hosts, correlatable
    # with the job's trace when a caller has one active
    with TRACER.span("scheduler.place_gang", attrs={
            "accelerator": accelerator, "slices": slices,
            "hosts_per_slice": hosts_per_slice,
            "workers": slices * hosts_per_slice}):
        order = ring_order(hosts_per_slice, topology)
        out: List[SlicePlacement] = []
        for s in range(slices):
            for i in range(hosts_per_slice):
                out.append(SlicePlacement(
                    slice_index=s,
                    host=order[i],
                    topology=topology,
                    accelerator=accelerator,
                ))
    return out
