"""Cluster slice inventory + concrete gang assignment.

Extends the logical placement of :mod:`kubeflow_tpu.scheduler.placement`
(worker index → slice ordinal) with *cluster* awareness: which concrete
slices exist (node labels ``kubeflow-tpu.org/slice-shape`` /
``slice-index`` written by the platform layer), which are fully free
(occupied = any running worker pod pinned to that slice), and which to
hand a new gang. Selection is best-fit + adjacency-window — implemented
twice with identical semantics: the native C++ core
(``kubeflow_tpu/native/placement.cc``) and the Python twin below; tests
assert they agree.
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from kubeflow_tpu.k8s.client import KubeClient
from kubeflow_tpu.native import load_library

SHAPE_LABEL = "kubeflow-tpu.org/slice-shape"
SLICE_INDEX_LABEL = "kubeflow-tpu.org/slice-index"
ASSIGNED_SLICE_LABEL = "kubeflow-tpu.org/assigned-slice"


@dataclass(frozen=True)
class SliceInfo:
    """One concrete slice in the cluster."""

    slice_id: str          # "<shape>_<index>" (label-safe), stable id
    shape: str             # e.g. v5e-8
    hosts: int             # host count of the shape
    free_hosts: int        # hosts with no assigned worker pod


def choose_slices_py(slice_hosts: Sequence[int], free_hosts: Sequence[int],
                     want: int, need_hosts: int,
                     score: Optional[Callable[[Sequence[int]], tuple]] = None,
                     ) -> Optional[List[int]]:
    """Python twin of ``kftpu_place_slices`` (same scoring, same result).

    ``score(window) -> tuple`` optionally PREPENDS ranking terms to the
    native ``(waste, span, position)`` key — the contention plane's hook
    (:mod:`kubeflow_tpu.scheduler.contention`) — so extended scorers
    reuse this one window enumeration instead of forking it; with no
    ``score`` the ranking is exactly the native core's.
    """
    n = len(slice_hosts)
    if want <= 0 or n <= 0 or want > n:
        return None
    feas = [i for i in range(n)
            if free_hosts[i] == slice_hosts[i]
            and slice_hosts[i] >= need_hosts]
    if len(feas) < want:
        return None
    best = None  # (*score, waste, span, start)
    for s in range(len(feas) - want + 1):
        window = feas[s:s + want]
        waste = sum(slice_hosts[i] - need_hosts for i in window)
        span = window[-1] - window[0]
        key = (tuple(score(window)) if score is not None else ()) \
            + (waste, span)
        if best is None or key < best[:-1]:
            best = key + (s,)
    s = best[-1]
    return feas[s:s + want]


def choose_slices(slice_hosts: Sequence[int], free_hosts: Sequence[int],
                  want: int, need_hosts: int) -> Optional[List[int]]:
    """Native core when available, Python twin otherwise."""
    lib = load_library()
    if lib is None:
        return choose_slices_py(slice_hosts, free_hosts, want, need_hosts)
    n = len(slice_hosts)
    arr = ctypes.c_int32 * n
    out = (ctypes.c_int32 * max(want, 1))()
    rc = lib.kftpu_place_slices(
        arr(*slice_hosts), arr(*free_hosts), n, want, need_hosts, out)
    if rc != 0:
        return None
    return [out[i] for i in range(want)]


class GangScheduler:
    """Assigns whole gangs onto concrete free slices.

    The reference's analogue is optional kube-batch podgroups with no
    topology model (``tf-job-operator.libsonnet:107-109``); here the
    whole-slice constraint and adjacency preference are first-class.
    """

    def __init__(self, client: KubeClient) -> None:
        self.client = client

    def inventory(self, shape: str) -> List[SliceInfo]:
        """Concrete slices of ``shape``, with free-host accounting."""
        nodes = self.client.list("v1", "Node",
                                 label_selector={SHAPE_LABEL: shape})
        hosts_per_slice: Dict[str, int] = {}
        for node in nodes:
            labels = node.get("metadata", {}).get("labels", {}) or {}
            idx = labels.get(SLICE_INDEX_LABEL, "0")
            hosts_per_slice[idx] = hosts_per_slice.get(idx, 0) + 1

        # occupied hosts: running/pending worker pods pinned to a slice.
        # The existence selector ({label: None}) makes the scan
        # O(assigned pods), not O(cluster) — a serving fleet's thousands
        # of unpinned pods never cross the wire; the shape prefix is
        # then filtered here (k8s selectors have no prefix operator).
        busy: Dict[str, int] = {}
        for pod in self.client.list("v1", "Pod",
                                    label_selector={ASSIGNED_SLICE_LABEL:
                                                    None}):
            labels = pod.get("metadata", {}).get("labels", {}) or {}
            assigned = labels.get(ASSIGNED_SLICE_LABEL, "")
            phase = pod.get("status", {}).get("phase", "Pending")
            if assigned.startswith(f"{shape}_") and phase in ("Pending",
                                                             "Running"):
                idx = assigned.rsplit("_", 1)[1]
                busy[idx] = busy.get(idx, 0) + 1

        out = []
        for idx in sorted(hosts_per_slice, key=lambda s: int(s)):
            hosts = hosts_per_slice[idx]
            out.append(SliceInfo(
                slice_id=f"{shape}_{idx}",
                shape=shape,
                hosts=hosts,
                free_hosts=max(hosts - busy.get(idx, 0), 0),
            ))
        return out

    def assign(self, shape: str, slices: int, hosts_per_slice: int,
               inventory: Optional[List[SliceInfo]] = None,
               ) -> Optional[List[str]]:
        """Concrete slice ids for a gang, or None when infeasible.

        Empty inventory also returns None — on real GKE the TPU placement
        policy owns slice packing and the operator falls back to
        selector-only scheduling. Pass ``inventory`` to reuse an existing
        scan instead of re-listing the cluster.
        """
        inv = inventory if inventory is not None else self.inventory(shape)
        if not inv:
            return None
        chosen = choose_slices(
            [s.hosts for s in inv], [s.free_hosts for s in inv],
            slices, hosts_per_slice)
        if chosen is None:
            return None
        return [inv[i].slice_id for i in chosen]
