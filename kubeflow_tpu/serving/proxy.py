"""Request-logging HTTP proxy in front of the model server.

Reference: the k8s-model-server http-proxy — a tornado bridge converting
JSON requests into model-server calls, paired with a fluentd sidecar that
tails request logs (``/root/reference/components/k8s-model-server/
http-proxy/server.py``; request-logging docs in the same dir). This proxy
forwards ``POST /model/<name>:predict`` to the backend's
``/v1/models/<name>:predict`` and emits one structured JSONL log line per
request (latency, status, model, batch size) — the stream a log shipper
tails instead of a fluentd sidecar.

Autoscale wiring: the proxy is the request-telemetry source of the
serving autoscaler (:mod:`kubeflow_tpu.autoscale`). Hand the
constructor a ``reporter`` (anything with ``request_start(model)`` /
``request_finish(model)`` — the in-process
:class:`~kubeflow_tpu.autoscale.metrics.MetricsAggregator`, or a small
shim POSTing to the autoscaler service's ``/api/autoscale/report``) and
every predict call is counted in-flight for the window math. With an
``admit_gate`` (``can_admit(model) -> bool``, the
:class:`~kubeflow_tpu.autoscale.reconciler.Autoscaler`), the proxy also
plays the Knative-activator role: requests against a model with no
warmed replica are answered 503 + ``Retry-After`` instead of being
forwarded into a cold backend — their telemetry is exactly what wakes
the scale-from-zero loop.
"""

from __future__ import annotations

import json
import logging
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional, Tuple

from kubeflow_tpu.obs import TRACER, current_context, extract, inject
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.jsonhttp import serve_json

log = logging.getLogger(__name__)

_proxied = DEFAULT_REGISTRY.counter(
    "kftpu_proxy_requests_total", "proxied predict requests")
_gate_degraded = DEFAULT_REGISTRY.counter(
    "kftpu_proxy_admit_gate_degraded_total",
    "admit-gate checks that failed open (autoscaler unreachable)")


class PredictProxy:
    def __init__(self, backend_url: str, *, log_stream=None,
                 timeout_s: float = 30.0, reporter=None,
                 admit_gate=None, retry_after_s: int = 1) -> None:
        self.backend_url = backend_url.rstrip("/")
        self.log_stream = log_stream if log_stream is not None else sys.stdout
        self.timeout_s = timeout_s
        self.reporter = reporter
        self.admit_gate = admit_gate
        self.retry_after_s = retry_after_s

    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str = "",
               headers: Optional[Dict[str, str]] = None) -> Tuple[int, Any]:
        if method == "GET" and path == "/healthz":
            return 200, {"ok": True, "backend": self.backend_url}
        if method != "POST" or not (path.startswith("/model/")
                                    and path.endswith(":predict")):
            return 404, {"error": "use POST /model/<name>:predict"}
        model = path[len("/model/"):-len(":predict")]
        t0 = time.perf_counter()
        # start/finish bracket EVERY outcome (including the 503 hold):
        # the held request's in-flight blip is the demand signal that
        # wakes the scale-from-zero loop
        if self.reporter is not None:
            self.reporter.request_start(model)
        with TRACER.span("serving.proxy", remote=extract(headers),
                         attrs={"model": model}) as sp:
            try:
                if (self.admit_gate is not None
                        and not self.admit_gate.can_admit(model)):
                    code, payload = 503, {
                        "error": f"no ready replica for {model!r}; "
                                 "scaling up",
                        "retryAfterSeconds": self.retry_after_s,
                    }
                else:
                    code, payload = self._forward(model, body or {})
            finally:
                if self.reporter is not None:
                    self.reporter.request_finish(model)
            sp.attrs["http.status"] = code
            trace_id = sp.trace_id
        latency_ms = (time.perf_counter() - t0) * 1000.0
        _proxied.inc(model=model)
        self._log({
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "model": model,
            "status": code,
            "latency_ms": round(latency_ms, 2),
            "instances": len((body or {}).get("instances", []) or []),
            "user": user or None,
            # the prediction log joins the trace tree on this key
            "trace_id": trace_id,
        })
        return code, payload

    def _forward(self, model: str,
                 body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        url = f"{self.backend_url}/v1/models/{model}:predict"
        fwd_headers = {"Content-Type": "application/json"}
        ctx = current_context()
        if ctx is not None:
            inject(fwd_headers, ctx)
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(), method="POST",
            headers=fwd_headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read() or b"{}")
            except json.JSONDecodeError:
                return e.code, {"error": f"backend returned {e.code}"}
        except (urllib.error.URLError, OSError) as e:
            return 502, {"error": f"backend unreachable: {e}"}

    def _log(self, record: Dict[str, Any]) -> None:
        self.log_stream.write(json.dumps(record) + "\n")
        self.log_stream.flush()


class RemoteReporter:
    """Cross-pod telemetry: POSTs start/finish events to the autoscaler
    service's ``/api/autoscale/report``. Best-effort AND off the hot
    path — events go through a bounded queue drained by a background
    thread, so a slow or dead autoscaler costs dropped telemetry (the
    loop degrades to static replicas), never predict latency."""

    def __init__(self, base_url: str, timeout_s: float = 2.0,
                 queue_size: int = 1024) -> None:
        import queue as _queue
        import threading

        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.dropped = 0
        self._q: "_queue.Queue" = _queue.Queue(maxsize=queue_size)
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="autoscale-reporter")
        self._thread.start()

    def _drain(self) -> None:
        while True:
            event, model = self._q.get()
            req = urllib.request.Request(
                f"{self.base_url}/api/autoscale/report",
                data=json.dumps({"model": model,
                                 "event": event}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=self.timeout_s):
                    pass
            except (urllib.error.URLError, OSError):
                pass

    def _enqueue(self, event: str, model: str) -> None:
        import queue as _queue

        try:
            self._q.put_nowait((event, model))
        except _queue.Full:
            # drop rather than block a predict; a start/finish pair lost
            # here skews one window sample, nothing more
            self.dropped += 1

    def request_start(self, model: str) -> None:
        self._enqueue("start", model)

    def request_finish(self, model: str) -> None:
        self._enqueue("finish", model)


class RemoteAdmitGate:
    """Cross-pod activator gate: asks the autoscaler service whether a
    model has a warmed replica, with a short per-model cache so the
    predict path pays at most one status GET per TTL — and FAILS OPEN
    (admit) when the autoscaler is unreachable: a broken control plane
    must degrade to static serving, not to a 503 wall."""

    def __init__(self, base_url: str, timeout_s: float = 1.0,
                 ttl_s: float = 1.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.ttl_s = ttl_s
        self._cache: Dict[str, Tuple[float, bool]] = {}

    def can_admit(self, model: str) -> bool:
        now = time.monotonic()
        hit = self._cache.get(model)
        if hit is not None and now - hit[0] < self.ttl_s:
            return hit[1]
        ok = True
        try:
            with urllib.request.urlopen(
                    f"{self.base_url}/api/autoscale/can_admit?"
                    + urllib.parse.urlencode({"model": model}),
                    timeout=self.timeout_s) as resp:
                ok = bool(json.loads(resp.read()).get("canAdmit", True))
        except (urllib.error.URLError, OSError, ValueError) as e:
            # fail OPEN, but never SILENTLY: the degraded-gate counter
            # is what tells on-call the activator is flying blind
            # (scale-from-zero holds stop working) while traffic still
            # flows
            ok = True
            _gate_degraded.inc()
            log.warning("admit gate degraded (autoscaler at %s "
                        "unreachable: %s); failing open", self.base_url, e)
        self._cache[model] = (now, ok)
        return ok


def main() -> None:
    import os

    reporter = admit_gate = None
    autoscale_url = os.environ.get("KFTPU_AUTOSCALE_URL", "")
    if autoscale_url:
        reporter = RemoteReporter(autoscale_url)
        # the activator role end-to-end: scale-from-zero requests are
        # held (503 + Retry-After) instead of forwarded into a
        # zero-endpoint backend Service
        admit_gate = RemoteAdmitGate(autoscale_url)
    proxy = PredictProxy(
        os.environ.get("KFTPU_BACKEND_URL", "http://localhost:8500"),
        reporter=reporter, admit_gate=admit_gate)
    serve_json(proxy.handle, int(os.environ.get("KFTPU_PROXY_PORT", "8008")))


if __name__ == "__main__":
    main()
