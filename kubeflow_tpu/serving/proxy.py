"""Request-logging HTTP proxy in front of the model server.

Reference: the k8s-model-server http-proxy — a tornado bridge converting
JSON requests into model-server calls, paired with a fluentd sidecar that
tails request logs (``/root/reference/components/k8s-model-server/
http-proxy/server.py``; request-logging docs in the same dir). This proxy
forwards ``POST /model/<name>:predict`` to the backend's
``/v1/models/<name>:predict`` and emits one structured JSONL log line per
request (latency, status, model, batch size) — the stream a log shipper
tails instead of a fluentd sidecar.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple

from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.jsonhttp import serve_json

_proxied = DEFAULT_REGISTRY.counter(
    "kftpu_proxy_requests_total", "proxied predict requests")


class PredictProxy:
    def __init__(self, backend_url: str, *, log_stream=None,
                 timeout_s: float = 30.0) -> None:
        self.backend_url = backend_url.rstrip("/")
        self.log_stream = log_stream if log_stream is not None else sys.stdout
        self.timeout_s = timeout_s

    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str = "") -> Tuple[int, Any]:
        if method == "GET" and path == "/healthz":
            return 200, {"ok": True, "backend": self.backend_url}
        if method != "POST" or not (path.startswith("/model/")
                                    and path.endswith(":predict")):
            return 404, {"error": "use POST /model/<name>:predict"}
        model = path[len("/model/"):-len(":predict")]
        t0 = time.perf_counter()
        code, payload = self._forward(model, body or {})
        latency_ms = (time.perf_counter() - t0) * 1000.0
        _proxied.inc(model=model)
        self._log({
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "model": model,
            "status": code,
            "latency_ms": round(latency_ms, 2),
            "instances": len((body or {}).get("instances", []) or []),
            "user": user or None,
        })
        return code, payload

    def _forward(self, model: str,
                 body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        url = f"{self.backend_url}/v1/models/{model}:predict"
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read() or b"{}")
            except json.JSONDecodeError:
                return e.code, {"error": f"backend returned {e.code}"}
        except (urllib.error.URLError, OSError) as e:
            return 502, {"error": f"backend unreachable: {e}"}

    def _log(self, record: Dict[str, Any]) -> None:
        self.log_stream.write(json.dumps(record) + "\n")
        self.log_stream.flush()


def main() -> None:
    import os

    proxy = PredictProxy(
        os.environ.get("KFTPU_BACKEND_URL", "http://localhost:8500"))
    serve_json(proxy.handle, int(os.environ.get("KFTPU_PROXY_PORT", "8008")))


if __name__ == "__main__":
    main()
