"""Model registry — experiment/model metadata tracking (modeldb parity).

The reference deploys ModelDB for this: a backend + frontend + mongo
stack recording models, experiment runs, their metrics and lineage
(``/root/reference/kubeflow/modeldb/modeldb.libsonnet``: backend :6543,
frontend :3000, db). Here the same capability is a file-backed registry
service over the framework's own model store — no database pod, same
durability contract as the run archive
(:mod:`kubeflow_tpu.workflows.archive`):

- every *registered* model version records kind/config, training
  metrics, lineage (the TpuJob / workflow / dataset / commit that
  produced it), and a lifecycle stage;
- stages gate serving: ``none → staging → production → archived`` —
  the production alias answers "which version does the traffic split
  point at" without editing manifests;
- the REST API (:class:`RegistryService`) is what the dashboard's
  models page and CI promotion steps drive.

Registration happens at export time (:func:`register_export` wraps
:func:`kubeflow_tpu.serving.model_store.export_model`) or explicitly
via the API.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.workflows.archive import _atomic_write

ENV_REGISTRY_DIR = "KFTPU_MODEL_REGISTRY_DIR"

STAGES = ("none", "staging", "production", "archived")

# names map 1:1 to store filenames AND to serving model names; restricting
# to this set means no sanitizing (which would silently merge distinct
# names like "a/b" and "a_b" into one document)
_MODEL_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,127}$")

_registrations = DEFAULT_REGISTRY.counter(
    "kftpu_registry_versions_total", "model versions registered")


class RegistryError(Exception):
    """Bad registry request (client error: invalid name/stage)."""


class NotFoundError(RegistryError):
    """Unknown model or version."""


def _check_name(model: str) -> str:
    if not _MODEL_NAME_RE.match(model) or model in (".", ".."):
        raise RegistryError(
            f"invalid model name {model!r}: alphanumerics, '.', '_', '-' "
            "only (must start alphanumeric)")
    return model


class ModelRegistry:
    """One JSON document per model under ``root`` (PVC/GCS mount).

    Writes are read-modify-write over the per-model document; the lock
    serializes them across the service's request threads. Running more
    than one replica over the same PVC would need file locking instead —
    the manifest defaults to one replica for this reason.
    """

    def __init__(self, root: str) -> None:
        self.root = root
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)

    # -- paths -------------------------------------------------------------

    def _path(self, model: str) -> str:
        return os.path.join(self.root, f"{_check_name(model)}.json")

    def _load(self, model: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._path(model)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except json.JSONDecodeError:
            return None

    def _store(self, doc: Dict[str, Any]) -> None:
        _atomic_write(self._path(doc["name"]),
                      json.dumps(doc, indent=1, sort_keys=True).encode())

    # -- write path --------------------------------------------------------

    def register(self, model: str, version: int, *,
                 kind: str = "",
                 config: Optional[Dict[str, Any]] = None,
                 metrics: Optional[Dict[str, float]] = None,
                 lineage: Optional[Dict[str, str]] = None,
                 base_path: str = "") -> Dict[str, Any]:
        """Record (or re-record) a model version's metadata."""
        version = int(version)
        with self._lock:
            doc = self._load(model) or {"name": model, "versions": {}}
            entry = {
                "version": version,
                "kind": kind,
                "config": dict(config or {}),
                "metrics": {k: float(v) for k, v in (metrics or {}).items()},
                "lineage": dict(lineage or {}),
                "base_path": base_path,
                "stage": doc["versions"].get(str(version), {}).get("stage",
                                                                   "none"),
                "registered_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime()),
            }
            doc["versions"][str(version)] = entry
            self._store(doc)
        _registrations.inc(model=model)
        return entry

    def transition(self, model: str, version: int,
                   stage: str) -> Dict[str, Any]:
        """Move a version to a lifecycle stage.

        Promoting to ``production`` demotes the previous production
        version to ``archived`` — exactly one production version per
        model, so the serving alias is unambiguous.
        """
        if stage not in STAGES:
            raise RegistryError(f"invalid stage {stage!r}; valid: {STAGES}")
        with self._lock:
            doc = self._load(model)
            if doc is None or str(int(version)) not in doc["versions"]:
                raise NotFoundError(f"unknown version {model}/{version}")
            if stage == "production":
                for v, e in doc["versions"].items():
                    if (e.get("stage") == "production"
                            and v != str(int(version))):
                        e["stage"] = "archived"
            doc["versions"][str(int(version))]["stage"] = stage
            self._store(doc)
            return doc["versions"][str(int(version))]

    def set_scale(self, model: str, replicas: int, *,
                  reason: str = "") -> Dict[str, Any]:
        """Record the autoscaler's granted replica count on the model
        document. The autoscale reconciler writes this every tick it
        changes the fleet; the dashboard and CI read replica state from
        the same file the lifecycle stage lives in (one source of truth
        per model). Unknown models get a versionless document — a model
        can be watched before its first version registers."""
        replicas = int(replicas)
        if replicas < 0:
            raise RegistryError(f"replicas must be >= 0, got {replicas}")
        with self._lock:
            doc = self._load(model) or {"name": _check_name(model),
                                        "versions": {}}
            doc["scale"] = {
                "replicas": replicas,
                "reason": reason,
                "updated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()),
            }
            self._store(doc)
            return doc["scale"]

    def scale(self, model: str) -> Dict[str, Any]:
        """The recorded replica state (zero replicas when never set)."""
        doc = self._load(model)
        if doc is None:
            raise NotFoundError(f"unknown model {model!r}")
        return doc.get("scale", {"replicas": 0, "reason": "",
                                 "updated_at": ""})

    def log_metrics(self, model: str, version: int,
                    metrics: Dict[str, float]) -> Dict[str, Any]:
        with self._lock:
            doc = self._load(model)
            if doc is None or str(int(version)) not in doc["versions"]:
                raise NotFoundError(f"unknown version {model}/{version}")
            entry = doc["versions"][str(int(version))]
            entry["metrics"].update({k: float(v) for k, v in metrics.items()})
            self._store(doc)
            return entry

    # -- read path ---------------------------------------------------------

    def models(self) -> List[Dict[str, Any]]:
        out = []
        for fname in sorted(os.listdir(self.root)):
            if not fname.endswith(".json"):
                continue
            name = fname[:-len(".json")]
            if not _MODEL_NAME_RE.match(name):
                continue  # stray file on the PVC, not one of ours
            doc = self._load(name)
            if doc is None:
                continue
            versions = doc.get("versions", {})
            prod = next((e for e in versions.values()
                         if e.get("stage") == "production"), None)
            out.append({
                "name": doc["name"],
                "versions": len(versions),
                "production": prod["version"] if prod else None,
                "latest": max((e["version"] for e in versions.values()),
                              default=None),
            })
        return out

    def versions(self, model: str) -> List[Dict[str, Any]]:
        doc = self._load(model)
        if doc is None:
            raise NotFoundError(f"unknown model {model!r}")
        return sorted(doc["versions"].values(), key=lambda e: e["version"])

    def get(self, model: str, version: int) -> Dict[str, Any]:
        doc = self._load(model)
        if doc is None or str(int(version)) not in doc.get("versions", {}):
            raise NotFoundError(f"unknown version {model}/{version}")
        return doc["versions"][str(int(version))]

    def production(self, model: str) -> Optional[Dict[str, Any]]:
        """The serving alias: the single production-stage version."""
        doc = self._load(model)
        if doc is None:
            return None
        return next((e for e in doc["versions"].values()
                     if e.get("stage") == "production"), None)

    def search(self, metric: str, *, minimum: Optional[float] = None,
               model: Optional[str] = None) -> List[Dict[str, Any]]:
        """Versions ranked by a metric (best first) — the leaderboard
        query ModelDB's experiment comparison answers."""
        hits = []
        for m in self.models():
            if model is not None and m["name"] != model:
                continue
            for e in self.versions(m["name"]):
                if metric not in e["metrics"]:
                    continue
                val = e["metrics"][metric]
                if minimum is not None and val < minimum:
                    continue
                hits.append({"model": m["name"], **e})
        return sorted(hits, key=lambda e: e["metrics"][metric], reverse=True)


def register_export(registry: ModelRegistry, path: str, kind: str,
                    params: Any, *,
                    config: Optional[Dict[str, Any]] = None,
                    version: int = 1,
                    metrics: Optional[Dict[str, float]] = None,
                    lineage: Optional[Dict[str, str]] = None,
                    **export_kw: Any) -> str:
    """Export a model version AND register it in one step."""
    from kubeflow_tpu.serving.model_store import export_model

    model = _check_name(os.path.basename(os.path.normpath(path)))
    # name validated BEFORE the export writes anything: a bad name must
    # not leave an exported-but-unregistered version on disk
    vdir = export_model(path, kind, params, config=config, version=version,
                        **export_kw)
    registry.register(model, version, kind=kind, config=config or {},
                      metrics=metrics, lineage=lineage, base_path=path)
    return vdir


class RegistryService:
    """REST surface (modeldb backend role), served by ``serve_json``.

    - ``GET  /api/registry/models``
    - ``GET  /api/registry/models/<m>/versions``
    - ``GET  /api/registry/models/<m>/production``
    - ``GET  /api/registry/models/<m>/scale``              (autoscaler state)
    - ``POST /api/registry/models/<m>/scale``              (set replicas)
    - ``POST /api/registry/models/<m>/versions``           (register)
    - ``POST /api/registry/models/<m>/versions/<v>:metrics``
    - ``POST /api/registry/models/<m>/versions/<v>:transition``
    - ``GET  /api/registry/search?metric=...&min=...``
    """

    def __init__(self, registry: ModelRegistry) -> None:
        self.registry = registry

    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str = "") -> Tuple[int, Any]:
        try:
            return self._route(method, path, body or {})
        except NotFoundError as e:
            return 404, {"error": str(e)}
        except RegistryError as e:
            return 400, {"error": str(e)}
        except (ValueError, TypeError) as e:
            # non-integer version, non-float min, etc — client errors,
            # not the 500 serve_json's blanket handler would report
            return 400, {"error": f"bad request: {e}"}

    def _route(self, method: str, path: str,
               body: Dict[str, Any]) -> Tuple[int, Any]:
        if method == "GET" and path == "/healthz":
            return 200, {"ok": True}
        if path == "/api/registry/models" and method == "GET":
            return 200, {"models": self.registry.models()}
        if path.startswith("/api/registry/search") and method == "GET":
            q = _query(path)
            if "metric" not in q:
                return 400, {"error": "search needs ?metric="}
            minimum = float(q["min"]) if "min" in q else None
            return 200, {"results": self.registry.search(
                q["metric"], minimum=minimum, model=q.get("model"))}
        parts = path.strip("/").split("/")
        # api/registry/models/<m>/...
        if len(parts) >= 4 and parts[:3] == ["api", "registry", "models"]:
            model = parts[3]
            rest = parts[4:]
            if rest == ["versions"] and method == "GET":
                return 200, {"versions": self.registry.versions(model)}
            if rest == ["versions"] and method == "POST":
                if "version" not in body:
                    return 400, {"error": "body needs 'version'"}
                entry = self.registry.register(
                    model, int(body["version"]),
                    kind=body.get("kind", ""),
                    config=body.get("config"),
                    metrics=body.get("metrics"),
                    lineage=body.get("lineage"),
                    base_path=body.get("basePath", ""))
                return 200, entry
            if rest == ["scale"] and method == "GET":
                return 200, self.registry.scale(model)
            if rest == ["scale"] and method == "POST":
                if "replicas" not in body:
                    return 400, {"error": "body needs 'replicas'"}
                return 200, self.registry.set_scale(
                    model, int(body["replicas"]),
                    reason=body.get("reason", ""))
            if rest == ["production"] and method == "GET":
                prod = self.registry.production(model)
                if prod is None:
                    return 404, {"error": f"no production version of "
                                          f"{model!r}"}
                return 200, prod
            if (len(rest) == 2 and rest[0] == "versions"
                    and method == "POST"):
                vpart = rest[1]
                if vpart.endswith(":metrics"):
                    entry = self.registry.log_metrics(
                        model, int(vpart[:-len(":metrics")]),
                        body.get("metrics", {}))
                    return 200, entry
                if vpart.endswith(":transition"):
                    if "stage" not in body:
                        return 400, {"error": "body needs 'stage'"}
                    entry = self.registry.transition(
                        model, int(vpart[:-len(":transition")]),
                        body["stage"])
                    return 200, entry
        return 404, {"error": "unknown endpoint"}


def _query(path: str) -> Dict[str, str]:
    from urllib.parse import parse_qsl, urlsplit

    return dict(parse_qsl(urlsplit(path).query))


def main() -> None:  # pragma: no cover - container entrypoint
    from kubeflow_tpu.utils.jsonhttp import serve_json

    registry = ModelRegistry(os.environ.get(ENV_REGISTRY_DIR, "/registry"))
    serve_json(RegistryService(registry).handle,
               int(os.environ.get("KFTPU_REGISTRY_PORT", "6543")))


if __name__ == "__main__":  # pragma: no cover
    main()
