"""Model multiplexing: N small models on one engine/slice, weight-paged.

A dedicated slice per small model wastes the chip: most fleets serve a
long tail of models whose weights fit HBM many times over but whose
traffic never saturates one slice. The multiplexer gives that headroom
back (PAPERS.md, "Exploring the limits of Concurrency in ML Training on
Google TPUs" — many workloads per TPU is the under-exploited axis):

- **LRU weight paging** from the versioned model store
  (:func:`kubeflow_tpu.serving.model_store.load_version` — with a mesh
  the params land sharded via the same ``shard_put``-shaped placement
  the elastic plane uses): at most ``max_resident`` models hold device
  memory; faulting a cold model in evicts the least-recently-used
  resident one (never a pinned or in-use model);
- a **pinned hot set**: models named in ``pinned`` are loaded up front
  and never evicted — the latency floor for the workloads that matter;
- **single-flight faulting**: concurrent requests for the same cold
  model trigger exactly ONE store load; the rest wait on the leader's
  result (a thundering herd re-reading a params.npz per request would
  multiply cold-start cost by the herd size);
- **cold-start accounting**: per-model fault wall time lands in
  ``snapshot()`` (``cold_start_ms``) and the
  ``kftpu_multiplex_cold_start_ms`` gauge — the number the ROADMAP's
  "cold-start ms, not s" bar is judged on.

``snapshot()`` merges an attached engine's snapshot, so the autoscaler
polls ONE object per backend
(:meth:`kubeflow_tpu.autoscale.metrics.MetricsAggregator
.observe_engine`) and its concurrency signal gains model-occupancy:
capacity tracks resident-weight pressure, not just KV pages.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from kubeflow_tpu.utils import DEFAULT_REGISTRY

log = logging.getLogger(__name__)

_loads_c = DEFAULT_REGISTRY.counter(
    "kftpu_multiplex_loads_total", "model weight loads (cold faults)")
_evictions_c = DEFAULT_REGISTRY.counter(
    "kftpu_multiplex_evictions_total", "resident models paged out (LRU)")
_cold_ms_g = DEFAULT_REGISTRY.gauge(
    "kftpu_multiplex_cold_start_ms",
    "last cold-start fault wall time per model, milliseconds")
_resident_g = DEFAULT_REGISTRY.gauge(
    "kftpu_multiplex_resident_models", "models currently holding weights")


class MultiplexFull(RuntimeError):
    """Every resident model is pinned or in use — nothing can be paged
    out to make room. A load condition (shed or retry), not a bug."""


class _Fault:
    """One in-flight cold load: followers hold THIS object and read
    the leader's outcome off it after ``event`` sets — no global
    error dict that client-controlled unique model names could grow
    forever (each stored exception pins its traceback frames too)."""

    __slots__ = ("event", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.error: Optional[BaseException] = None


class _Resident:
    __slots__ = ("handle", "tick", "inflight", "pinned", "cold_start_ms")

    def __init__(self, handle: Any, tick: int, pinned: bool,
                 cold_start_ms: float) -> None:
        self.handle = handle
        self.tick = tick
        self.inflight = 0
        self.pinned = pinned
        self.cold_start_ms = cold_start_ms


class ModelMultiplexer:
    """LRU weight pager over the model store, single-flight per model.

    ``loader(name) -> handle`` is injectable (tests fault fakes; the
    default binds the store root through
    :func:`~kubeflow_tpu.serving.model_store.load_version`, sharded
    onto ``mesh`` when one is given). ``engine`` (optional) is the
    co-resident decode engine whose snapshot this object's
    ``snapshot()`` extends for the autoscaler poll.
    """

    def __init__(self, store_root: Optional[str] = None, *,
                 max_resident: int, pinned: Sequence[str] = (),
                 loader: Optional[Callable[[str], Any]] = None,
                 engine: Any = None, mesh: Any = None,
                 clock: Optional[Callable[[], float]] = None,
                 request_ledger=None) -> None:
        if max_resident < 1:
            raise ValueError("max_resident must be >= 1")
        if len(set(pinned)) > max_resident:
            raise ValueError(
                f"{len(set(pinned))} pinned models cannot fit a "
                f"max_resident of {max_resident}")
        if loader is None:
            if store_root is None:
                raise ValueError("need store_root or loader")
            loader = self._store_loader(store_root, mesh)
        self.max_resident = int(max_resident)
        self.pinned = tuple(dict.fromkeys(pinned))
        self.loader = loader
        self.engine = engine
        self.clock = clock if clock is not None else time.monotonic
        # request-lifecycle ledger: a cold-start fault is the faulting
        # request's weight_fault phase (carved from whatever base phase
        # it overlaps), keyed by the caller's active trace
        from kubeflow_tpu.obs import requests as _reqobs

        self.rledger = (request_ledger if request_ledger is not None
                        else _reqobs.DEFAULT_LEDGER)
        self._resident: Dict[str, _Resident] = {}
        self._loading: Dict[str, _Fault] = {}
        self._lock = threading.Lock()
        self._tick = 0
        self.loads = 0
        self.evictions = 0
        for name in self.pinned:
            self.get(name)

    @staticmethod
    def _store_loader(store_root: str, mesh: Any):
        import os

        from kubeflow_tpu.serving import model_store

        def load(name: str):
            base = os.path.join(store_root, name)
            versions = model_store.list_versions(base)
            if not versions:
                raise FileNotFoundError(
                    f"no versions of {name!r} under {store_root}")
            return model_store.load_version(base, versions[-1], mesh=mesh)

        return load

    # -- faulting ----------------------------------------------------------

    def get(self, name: str) -> Any:
        """The model's handle, faulting its weights in if cold.

        Raises :class:`MultiplexFull` when nothing can be evicted to
        make room, and re-raises the leader's load error to every
        waiter of the same fault (a failed load must fail the herd, not
        strand it)."""
        while True:
            with self._lock:
                res = self._resident.get(name)
                if res is not None:
                    self._tick += 1
                    res.tick = self._tick
                    return res.handle
                fault = self._loading.get(name)
                if fault is None:
                    # leader: room-make BEFORE claiming the fault (the
                    # claim would count itself toward the committed
                    # budget, and a MultiplexFull after installing it
                    # would strand followers on a never-set event),
                    # all under the lock so two faults cannot evict
                    # past the budget
                    self._evict_for_one_locked()
                    fault = self._loading[name] = _Fault()
                    break
            # follower: wait for the leader's outcome outside the lock
            # — read it off the shared fault object (a failed load
            # fails the whole herd; a success loops to residency).
            # The wait is THIS request's weight_fault stall too: every
            # member of the herd pays the cold start, and each record
            # shows its own share
            tw0 = self.clock()
            fault.event.wait()
            self._note_weight_fault(tw0, self.clock())
            if fault.error is not None:
                raise fault.error
        t0 = self.clock()
        try:
            handle = self.loader(name)
        except BaseException as e:
            with self._lock:
                del self._loading[name]
            fault.error = e
            fault.event.set()
            raise
        cold_ms = (self.clock() - t0) * 1000.0
        self._note_weight_fault(t0, t0 + cold_ms / 1000.0)
        with self._lock:
            self._tick += 1
            self._resident[name] = _Resident(
                handle, self._tick, name in self.pinned, cold_ms)
            del self._loading[name]
            self.loads += 1
            n_res = len(self._resident)
        fault.event.set()
        _loads_c.inc(model=name)
        _cold_ms_g.set(round(cold_ms, 3), model=name)
        _resident_g.set(n_res)
        log.info("multiplex: faulted %s in %.1f ms (%d resident)",
                 name, cold_ms, n_res)
        return handle

    def _note_weight_fault(self, t0: float, t1: float) -> None:
        """Attribute a cold-start window to the calling request's
        lifecycle record (keyed by the thread's active trace; callers
        outside any trace simply have no record to charge)."""
        from kubeflow_tpu.obs import current_context

        ctx = current_context()
        if ctx is not None:
            from kubeflow_tpu.obs import requests as _reqobs

            self.rledger.stall(ctx.trace_id, _reqobs.WEIGHT_FAULT,
                               t0, t1)

    def _evict_for_one_locked(self) -> None:
        """Make room for one incoming model (caller holds the lock).

        Loads in flight count toward the budget — the leader that
        claimed a fault owns its slot before the weights arrive."""
        committed = len(self._resident) + len(self._loading)
        while committed + 1 > self.max_resident:
            victim = min(
                (r for r in self._resident.items()
                 if not r[1].pinned and r[1].inflight == 0),
                key=lambda kv: kv[1].tick, default=None)
            if victim is None:
                raise MultiplexFull(
                    f"{len(self._resident)} resident / "
                    f"{len(self._loading)} loading, all pinned or in "
                    f"use — cannot page anything out")
            del self._resident[victim[0]]
            self.evictions += 1
            committed -= 1
            _evictions_c.inc()
            _resident_g.set(len(self._resident))
            log.info("multiplex: paged out %s", victim[0])

    # -- request accounting ------------------------------------------------

    def lease(self, name: str) -> "_Lease":
        """``with mux.lease(name) as handle:`` — the in-use guard that
        keeps a model resident for the duration of a request (eviction
        skips models with live leases)."""
        while True:
            handle = self.get(name)
            with self._lock:
                res = self._resident.get(name)
                if res is not None:
                    res.inflight += 1
                    return _Lease(self, name, handle)
            # evicted between get() and the lock (a zero-inflight race
            # on a saturated pager): retry the fault — OUTSIDE the
            # lock, since get() takes it (recursing under the held
            # non-reentrant lock deadlocked the whole pager)

    def _release(self, name: str) -> None:
        with self._lock:
            res = self._resident.get(name)
            if res is not None:
                res.inflight = max(0, res.inflight - 1)

    # -- telemetry ---------------------------------------------------------

    def snapshot(self) -> dict:
        """Engine-snapshot superset for the autoscaler poll: the
        attached engine's fields plus model-occupancy. ``models_held``
        (resident minus idle-evictable) over ``models_max`` is the
        resident-weight pressure; idle UNPINNED resident models are
        reclaimable cache, not load (the ``pages_evictable`` stance
        applied to weights). Pinned models are never evictable — a
        pager saturated by its pinned hot set must read as pressure,
        since no other model can fault in."""
        snap: Dict[str, Any] = (dict(self.engine.snapshot())
                                if self.engine is not None
                                else {"active_slots": 0, "pending": 0,
                                      "slots": 0, "closed": False})
        with self._lock:
            resident = {
                name: {"inflight": r.inflight, "pinned": r.pinned,
                       "cold_start_ms": round(r.cold_start_ms, 3)}
                for name, r in sorted(self._resident.items())}
            evictable = sum(1 for r in self._resident.values()
                            if r.inflight == 0 and not r.pinned)
            snap.update({
                "multiplex": True,
                "models_resident": len(resident),
                "models_max": self.max_resident,
                "models_evictable": evictable,
                "models_loading": len(self._loading),
                "models_pinned": len(self.pinned),
                "multiplex_loads": self.loads,
                "multiplex_evictions": self.evictions,
                "models": resident,
            })
        return snap

    def resident_models(self) -> List[str]:
        with self._lock:
            return sorted(self._resident)


class _Lease:
    def __init__(self, mux: ModelMultiplexer, name: str,
                 handle: Any) -> None:
        self.mux = mux
        self.name = name
        self.handle = handle

    def __enter__(self) -> Any:
        return self.handle

    def __exit__(self, *exc) -> None:
        self.mux._release(self.name)
