"""Continuous-batching decode engine: concurrent generate requests share
one compiled decode step.

The reference platform's serving tier batches at the RPC layer
(TF-Serving's ``enable_batching`` scheduler,
``/root/reference/kubeflow/tf-serving/tf-serving-template.libsonnet:33-48``)
— whole requests queue for a fixed-shape batch. That is the wrong shape
for autoregressive decoding, where a request is a *sequence* of steps:
batching whole requests serializes callers behind the longest
generation. TPU-first, the engine instead owns a persistent device-side
KV cache with ``slots`` independent rows and runs ONE compiled
single-token step over all of them, forever:

- **submit** — a request (prompt + sampling params) joins the admission
  queue; its prompt is prefilled at batch 1 into a fresh cache row
  (one compiled prefill per power-of-two prompt bucket, exactly the
  unary path's bucketing) and the row is written into a free slot of
  the engine cache with one ``dynamic_update_slice`` (the compiled
  *insert* — cheap: it touches one row);
- **step** — every active slot advances one token under one jit:
  per-row cache positions (the decode core's ragged-batch contract,
  ``kubeflow_tpu/models/transformer.py:_decode_attend``), per-row
  sampling parameters, and per-row PRNG keys derived as
  ``fold_in(key(seed), step_index)`` so a request's tokens are
  reproducible regardless of which co-tenants share its batch;
- tokens stream to per-request queues the moment the host sees them —
  time-to-first-token is one prefill + one step, not one full
  generation.

Static shapes everywhere: the engine batch is fixed at ``slots``, idle
rows decode garbage that nothing reads (their writes land in rows the
next insert overwrites), and the compiled-program inventory is small
and bounded: prefill (per prompt bucket), the burst batch-prefill (per
batch-bucket × prompt-bucket — a burst of same-bucket requests admits
through ONE prefill instead of sequential row prefills), insert (whole
row and from-batch-row variants), the general sampled step, the
all-greedy argmax step (dispatched whenever no in-flight request
samples — it skips the per-row sampler entirely), and the
prefix-continuation (per suffix bucket). ``precompile=True`` builds
both STEP programs up front, so a greedy↔sampled workload shift never
pauses co-tenant decode on an XLA compile. Prefill programs (row and
batch) compile lazily on the first request of each shape, and since
admission and stepping share the engine thread that first-shape compile
does pause in-flight streams — pre-existing row-path behavior; the
batch path adds batch-bucket shapes to the inventory
(``KFTPU_ADMIT_BATCH=0`` pins admission back to the row path's one
program per prompt bucket if that matters more than burst TTFT).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import logging
import os
import queue
import threading
import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.decode import (
    decode_step,
    prefill,
    prefill_continue,
    sample_logits,
)
from kubeflow_tpu.obs import (
    SpanContext,
    Tracer,
    current_context,
    profiler_annotator,
)
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

_steps_total = DEFAULT_REGISTRY.counter(
    "kftpu_engine_steps_total", "shared decode steps executed")
_tokens_total = DEFAULT_REGISTRY.counter(
    "kftpu_engine_tokens_total", "tokens produced by the decode engine")
_occupancy = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_active_slots", "active slots in the decode batch")
_queue_depth = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_pending_requests", "requests waiting for a slot")
_prefix_hits = DEFAULT_REGISTRY.counter(
    "kftpu_engine_prefix_hits_total", "prefix-cache hits at admission")
_prefix_misses = DEFAULT_REGISTRY.counter(
    "kftpu_engine_prefix_misses_total", "prefix-cache misses at admission")
_prefix_bytes_g = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_prefix_cache_bytes",
    "HBM bytes held by cached prompt-prefix KV rows")
_prefix_budget_g = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_prefix_cache_budget_bytes",
    "prefix-cache byte budget (entries evict LRU to stay under it)")
_queue_wait_h = DEFAULT_REGISTRY.histogram(
    "engine_queue_wait_seconds",
    "time a generate request waits for a decode slot")

_END = object()  # per-request stream sentinel


class EngineClosed(RuntimeError):
    """The engine was shut down (version rollover) — retryable."""


class _CacheInvalidated(RuntimeError):
    """A donating device call consumed the engine cache and then
    failed: the engine can never step again. Raised THROUGH run_once so
    the loop applies the same close-and-evict protocol as a step
    failure (row-path retries against a consumed cache would fail every
    request while keeping the corpse serving)."""


def pow2_bucket(n: int, cap: int) -> int:
    """Round ``n`` up to a power of two, capped at ``cap`` — the shared
    compiled-program bucketing rule for prompts (one compiled prefill
    per bucket, in both the unary path and engine admission)."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


def _batch_axis(leaf: jnp.ndarray) -> int:
    """Cache leaves are ``positions`` (B,)|(L, B) or ``k``/``v``
    (B, S, KH, Dh)|(L, B, S, KH, Dh) depending on whether layers are
    stacked by ``nn.scan`` — the batch axis is determined by rank."""
    return {1: 0, 2: 1, 4: 0, 5: 1}[leaf.ndim]


@dataclasses.dataclass
class _Request:
    prompt: np.ndarray           # (S,) int32, true length (no padding)
    max_new: int
    temperature: float
    top_k: int
    top_p: float
    seed: int
    eos_id: Optional[int]
    # first N prompt tokens are a reusable prefix (shared system
    # prompt): its prefill is served from the engine's prefix cache
    prefix_len: int = 0
    # trace context captured at submit() — the engine thread parents its
    # queue-wait/admit/decode spans onto the submitting request's span
    ctx: Optional[SpanContext] = None
    t_submit: float = 0.0
    # queue-wait recorded once: a failed batch admission retries members
    # through the row path, which must not observe the wait twice
    _wait_noted: bool = False
    out: "queue.Queue[Any]" = dataclasses.field(
        default_factory=queue.Queue)
    error: Optional[Exception] = None
    # consumed tokens, so stream()/result() are replayable (a second
    # call must not block on the drained queue)
    _seen: List[int] = dataclasses.field(default_factory=list)
    _done: bool = False

    def stream(self):
        """Yield token ids as the engine produces them (replayable:
        tokens already consumed are yielded first)."""
        yield from list(self._seen)
        while not self._done:
            tok = self.out.get()
            if tok is _END:
                self._done = True
                if self.error is not None:
                    raise self.error
                return
            self._seen.append(tok)
            yield tok
        if self.error is not None:
            raise self.error

    def result(self) -> List[int]:
        return list(self.stream())


@dataclasses.dataclass
class _Slot:
    req: _Request
    produced: int = 0  # tokens emitted so far (1 after the prefill sample);
    # the device-facing step/token state lives in the engine's host-side
    # arrays (_stepidx/_tokens) — the slot only tracks delivery
    t_decode0: float = 0.0  # decode-phase start (the decode span's start)


class DecodeEngine:
    """One engine per loaded transformer model version.

    ``submit()`` is thread-safe and returns a handle whose ``stream()``
    yields tokens as decode steps complete. The engine thread runs
    admit → step forever; ``close()`` drains it.
    """

    def __init__(self, config, params, *, slots: int = 8,
                 steps_per_sync: int = 1, mesh=None,
                 prefix_cache_entries: int = 4,
                 prefix_cache_bytes: Optional[int] = None,
                 sampler_bound: Optional[int] = None,
                 admit_batch_max: Optional[int] = None,
                 precompile: bool = False,
                 autostart: bool = True, name: str = "",
                 clock: Optional[Clock] = None,
                 tracer: Optional[Tracer] = None) -> None:
        self.config = config
        self.slots = slots
        # host-side timing source for queue-wait/admit/decode spans; a
        # fake clock makes engine span trees deterministic in tests
        self.clock: Clock = clock if clock is not None else time.monotonic
        # spans land in the shared collector; the profiler annotator
        # mirrors live admit/prefill spans onto the XLA host timeline
        # during a capture (docs/OBSERVABILITY.md)
        self.tracer = tracer if tracer is not None else Tracer(
            clock=self.clock, annotator=profiler_annotator())
        # lax.top_k-bounded sampler (models/decode.py:sample_logits
        # ``bound``): avoids the per-token full-vocab sort the exact
        # sampler pays at every sampled step — 0 selects the exact sort
        # path, None reads KFTPU_SAMPLER_BOUND (default 64)
        if sampler_bound is None:
            sampler_bound = int(os.environ.get("KFTPU_SAMPLER_BOUND",
                                               "64"))
        self.sampler_bound = int(sampler_bound)
        # burst admission: same-bucket pending requests prefill as ONE
        # batch of up to this many rows. The cap bounds the transient
        # HBM spike (a batch prefill materializes that many extra
        # full-context KV rows until their inserts land) and the
        # compiled-program inventory; <=1 disables batching entirely
        # (every request takes the row path). KFTPU_ADMIT_BATCH.
        if admit_batch_max is None:
            admit_batch_max = int(os.environ.get("KFTPU_ADMIT_BATCH",
                                                 "8"))
        self.admit_batch_max = int(admit_batch_max)
        # multi-chip serving: with a Mesh (params already placed with
        # tensor-parallel shardings, e.g. via models.param_partition_specs)
        # every compiled engine program runs under it, and the model's
        # logical-axis constraints shard the KV cache over the same axes
        self.mesh = mesh
        # decode steps executed on-device per host round-trip: >1 hides
        # dispatch/transfer latency (the dominant cost when the host is
        # remote from the chip) at the price of admission/EOS reacting
        # up to that many tokens late — tokens past a row's EOS or
        # budget are computed and discarded
        self.steps_per_sync = max(1, int(steps_per_sync))
        self.name = name or "model"
        self._params = params
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._active: List[Optional[_Slot]] = [None] * slots
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # guards _active between admit/step

        if mesh is not None:
            from kubeflow_tpu.parallel.mesh import mesh_context

            self._mesh_ctx = lambda: mesh_context(mesh)
        else:
            import contextlib

            self._mesh_ctx = contextlib.nullcontext

        Smax = config.max_seq_len
        bnd = self.sampler_bound if self.sampler_bound > 0 else None

        @jax.jit
        def _prefill_and_sample(params, prompt, true_len, temperature,
                                top_k, top_p, seed):
            logits, cache = prefill(config, params, prompt, true_len)
            key = jax.random.fold_in(jax.random.key(seed), 0)
            tok = sample_logits(logits, key, temperature=temperature,
                                top_k=top_k, top_p=top_p, bound=bnd)
            return tok[0], cache

        @jax.jit
        def _continue_and_sample(params, cache, suffix, suffix_len,
                                 total_len, temperature, top_k, top_p,
                                 seed):
            logits, cache = prefill_continue(
                config, params, cache, suffix, suffix_len, total_len)
            key = jax.random.fold_in(jax.random.key(seed), 0)
            tok = sample_logits(logits, key, temperature=temperature,
                                top_k=top_k, top_p=top_p, bound=bnd)
            return tok[0], cache

        @jax.jit
        def _prefill_batch_and_sample(params, prompts, true_lens, temps,
                                      top_ks, top_ps, seeds):
            """Burst admission: same-bucket requests prefill TOGETHER —
            one compiled (B, S) prefill instead of B sequential row
            prefills, with per-row ragged lengths and sampling params
            (the decode core's contract). Burst time-to-first-token
            drops from B×prefill to ~one batched prefill."""
            logits, cache = prefill(config, params, prompts, true_lens)

            def one(row_logits, seed, t, k, p):
                key = jax.random.fold_in(jax.random.key(seed), 0)
                return sample_logits(row_logits[None], key,
                                     temperature=t, top_k=k, top_p=p,
                                     bound=bnd)[0]

            toks = jax.vmap(one)(logits, seeds, temps, top_ks, top_ps)
            return toks, cache

        self._prefill_batch = _prefill_batch_and_sample

        def _insert_rows(engine_cache, batch_cache, slot_ids, valid):
            """Insert every valid batch-prefill row into its engine slot
            in ONE device dispatch (a scan of per-row dynamic updates).
            Burst admission used to pay one dispatch per member; on
            high-dispatch-latency transports those per-row launches
            dominated admission wall time (measured round 5: 48 inserts
            ≈ 1.4 s of the engine bench's 4.2 s). Pad rows (``valid``
            False) write a slot's current contents back — a no-op."""

            def put(big, small, row, slot, ok):
                ax = _batch_axis(big)
                piece = jax.lax.dynamic_slice_in_dim(
                    small, row, 1, axis=ax).astype(big.dtype)
                idx = tuple(slot if a == ax else 0
                            for a in range(big.ndim))
                cur = jax.lax.dynamic_slice(big, idx, piece.shape)
                return jax.lax.dynamic_update_slice(
                    big, jnp.where(ok, piece, cur), idx)

            def body(cache, xs):
                row, slot, ok = xs
                return jax.tree_util.tree_map(
                    lambda big, small: put(big, small, row, slot, ok),
                    cache, batch_cache), None

            cache, _ = jax.lax.scan(
                body, engine_cache,
                (jnp.arange(slot_ids.shape[0]), slot_ids, valid))
            return cache

        self._insert_rows = jax.jit(_insert_rows, donate_argnums=(0,))

        self._continue = _continue_and_sample
        # LRU of prefilled prompt prefixes: (len, token bytes) →
        # 1-row cache, BYTE-budgeted (every entry is a full-context row,
        # so the HBM cost scales with max_seq_len × layers — an entry
        # count hides it from the operator). Budget resolution: the
        # explicit ``prefix_cache_bytes`` arg, else KFTPU_PREFIX_CACHE_
        # BYTES, else ``prefix_cache_entries`` × the per-row byte size
        # (computed below once the cache layout is known). _continue
        # never mutates a stored entry (functional apply, no donation).
        self._prefix_store: "collections.OrderedDict" = \
            collections.OrderedDict()
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_cache_bytes = 0  # bytes currently held

        def _insert(engine_cache, row_cache, slot):
            return jax.tree_util.tree_map(
                lambda big, row: jax.lax.dynamic_update_slice(
                    big, row.astype(big.dtype),
                    tuple(slot if a == _batch_axis(big) else 0
                          for a in range(big.ndim))),
                engine_cache, row_cache)

        self._insert = jax.jit(_insert, donate_argnums=(0,))

        K = self.steps_per_sync

        def _step(params, cache, tokens, seeds, step_idx, temps, top_k,
                  top_p):
            """K decode steps under one jit; returns (cache, (K, B))."""

            def one(row_logits, seed, idx, t, k, p):
                key = jax.random.fold_in(jax.random.key(seed), idx)
                return sample_logits(row_logits[None], key, temperature=t,
                                     top_k=k, top_p=p, bound=bnd)[0]

            def body(carry, t):
                cache, tokens = carry
                logits, cache = decode_step(config, params, cache, tokens)
                nxt = jax.vmap(one)(logits, seeds, step_idx + t, temps,
                                    top_k, top_p)
                return (cache, nxt), nxt

            (cache, _), toks = jax.lax.scan(
                body, (cache, tokens), jnp.arange(K))
            return cache, toks

        def _step_greedy(params, cache, tokens):
            """The all-greedy fast path: no per-row sampler, no vocab
            sort — argmax only. Dispatched when every in-flight request
            is greedy (the host knows each slot's sampling params), the
            common serving load and the bench configuration."""

            def body(carry, _):
                cache, tokens = carry
                logits, cache = decode_step(config, params, cache, tokens)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (cache, nxt), nxt

            (cache, _), toks = jax.lax.scan(
                body, (cache, tokens), None, length=K)
            return cache, toks

        self._step = jax.jit(_step, donate_argnums=(1,))
        self._step_greedy = jax.jit(_step_greedy, donate_argnums=(1,))
        self._prefill = _prefill_and_sample

        # engine cache: the decode cache shape at batch = slots, zeroed.
        # eval_shape on prefill gives the layout without running it.
        probe = jnp.zeros((1, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda p: prefill(config, p, probe)[1], params)
        # a stored prefix row IS this batch-1 full-context cache — its
        # byte size anchors the prefix-cache budget
        self._prefix_row_bytes = int(sum(
            int(np.prod(s.shape)) * jnp.dtype(s.dtype).itemsize
            for s in jax.tree_util.tree_leaves(shapes)))
        if prefix_cache_bytes is None:
            env = os.environ.get("KFTPU_PREFIX_CACHE_BYTES")
            prefix_cache_bytes = int(env) if env else None
        if prefix_cache_bytes is None:
            prefix_cache_bytes = (max(0, int(prefix_cache_entries))
                                  * self._prefix_row_bytes)
        self._prefix_budget_bytes = max(0, int(prefix_cache_bytes))
        _prefix_budget_g.set(self._prefix_budget_bytes, model=self.name)

        def _engine_shape(s):
            return tuple(slots if a == _batch_axis(s) else d
                         for a, d in enumerate(s.shape))

        def _zeros_tree():
            return jax.tree_util.tree_map(
                lambda s: jnp.zeros(_engine_shape(s), s.dtype), shapes)

        if mesh is None:
            self._cache = _zeros_tree()
        else:
            # k/v leaves shard their kv-heads axis (rank-2 from the end)
            # per the model's logical rules, so the full-context cache
            # never materializes on one device; shape_aware_spec drops
            # the axis when it doesn't divide (GQA kv heads < tp)
            from jax.sharding import NamedSharding

            from kubeflow_tpu.parallel.mesh import (
                logical_to_mesh_axes,
                shape_aware_spec,
            )

            def _sharding(s):
                shape = _engine_shape(s)
                names = [None] * len(shape)
                if len(shape) >= 4:
                    names[-2] = "heads"
                spec = shape_aware_spec(
                    logical_to_mesh_axes(names, config.rules), shape,
                    mesh)
                return NamedSharding(mesh, spec)

            with self._mesh_ctx():
                self._cache = jax.jit(
                    _zeros_tree,
                    out_shardings=jax.tree_util.tree_map(
                        _sharding, shapes))()
        # host-side per-slot sampling state, padded to the batch
        self._tokens = np.zeros((slots,), np.int32)
        self._seeds = np.zeros((slots,), np.int32)
        self._stepidx = np.zeros((slots,), np.int32)
        self._temps = np.zeros((slots,), np.float32)
        self._topk = np.zeros((slots,), np.int32)
        self._topp = np.ones((slots,), np.float32)
        self.steps_total = 0
        self.tokens_total = 0
        self.greedy_steps = 0  # steps served by the argmax fast path
        self.batch_prefills = 0  # burst admissions served batched
        if precompile:
            self._precompile_steps()
        if autostart:
            self.start()

    def _precompile_steps(self) -> None:
        """Run BOTH step programs once on the empty batch so the
        greedy↔sampled dispatch switch never stalls in-flight streams
        on a mid-serving XLA compile. Every slot is idle, so the junk
        tokens land in rows the next insert fully overwrites."""
        B = self.slots
        toks = jnp.zeros((B,), jnp.int32)
        vec_i = jnp.zeros((B,), jnp.int32)
        ones_f = jnp.ones((B,), jnp.float32)
        with self._mesh_ctx():
            self._cache, _ = self._step_greedy(
                self._params, self._cache, toks)
            self._cache, _ = self._step(
                self._params, self._cache, toks, vec_i, vec_i, ones_f,
                vec_i, ones_f)

    # -- public API --------------------------------------------------------

    def submit(self, prompt, *, max_new: int, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0, seed: int = 0,
               eos_id: Optional[int] = None,
               prefix_len: int = 0) -> _Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size + max_new > self.config.max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new} exceeds "
                f"context {self.config.max_seq_len}")
        prefix_len = int(prefix_len)
        if prefix_len and not 0 < prefix_len < prompt.size:
            raise ValueError(
                f"prefix_len {prefix_len} must be in (0, prompt length "
                f"{prompt.size}) — the suffix may not be empty")
        if self._prefix_budget_bytes < self._prefix_row_bytes:
            # cache disabled, or one full-context row alone would bust
            # the byte budget: honor it by serving the full prefill
            prefix_len = 0
        req = _Request(prompt=prompt, max_new=max_new,
                       temperature=float(temperature), top_k=int(top_k),
                       top_p=float(top_p), seed=int(seed), eos_id=eos_id,
                       prefix_len=prefix_len,
                       # the submitting thread's active span (serving
                       # handler) — engine spans parent onto it
                       ctx=current_context(), t_submit=self.clock())
        # the lock orders this against close()'s drain: a submit must
        # either land before the drain (and be failed by it) or see the
        # stop flag and raise — never sit in a queue nobody reads
        with self._lock:
            if self._stop.is_set():
                raise EngineClosed("decode engine closed")
            self._pending.put(req)
        _queue_depth.set(self._pending.qsize(), model=self.name)
        return req

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"decode-engine-{self.name}")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # fail whatever is still in flight — a hung client is worse than
        # a retried request (version retirement path). The lock pairs
        # with submit(): after this drain no new request can enqueue.
        with self._lock:
            active = [s.req for s in self._active if s is not None]
            self._active = [None] * self.slots
            while True:
                try:
                    active.append(self._pending.get_nowait())
                except queue.Empty:
                    break
        for req in active:
            req.error = EngineClosed("decode engine closed")
            req.out.put(_END)

    @property
    def closed(self) -> bool:
        """True once the engine can no longer serve (explicit close or
        a step failure that invalidated the donated cache)."""
        return self._stop.is_set()

    @property
    def active_count(self) -> int:
        with self._lock:
            return sum(s is not None for s in self._active)

    @property
    def pending_count(self) -> int:
        """Requests admitted to submit() but not yet holding a slot."""
        return self._pending.qsize()

    def snapshot(self) -> dict:
        """Occupancy snapshot for the autoscaler's engine poll
        (:meth:`kubeflow_tpu.autoscale.metrics.MetricsAggregator
        .observe_engine`): active slots are the concurrency the proxy
        can't see (one HTTP generate call hides a whole decode stream),
        pending is the admission-queue depth."""
        return {"active_slots": self.active_count,
                "pending": self.pending_count,
                "slots": self.slots,
                "closed": self.closed}

    # -- engine internals --------------------------------------------------

    def _prefix_cache_row(self, prefix: np.ndarray):
        """The 1-row cache holding this prefilled prefix (LRU)."""
        key = (prefix.size, prefix.tobytes())
        cached = self._prefix_store.get(key)
        if cached is not None:
            self._prefix_store.move_to_end(key)
            self.prefix_hits += 1
            _prefix_hits.inc(model=self.name)
            return cached
        self.prefix_misses += 1
        _prefix_misses.inc(model=self.name)
        N = prefix.size
        bucket = pow2_bucket(N, self.config.max_seq_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :N] = prefix
        # sampling args are dummies — only the cache is kept
        _, pcache = self._prefill(
            self._params, jnp.asarray(padded),
            jnp.asarray([N], jnp.int32), jnp.float32(0.0),
            jnp.int32(0), jnp.float32(1.0), jnp.int32(0))
        # byte-budget admission: evict LRU until the new row fits
        # (submit() already routed away callers that can never fit)
        while (self._prefix_store and self.prefix_cache_bytes
                + self._prefix_row_bytes > self._prefix_budget_bytes):
            self._prefix_store.popitem(last=False)
            self.prefix_cache_bytes -= self._prefix_row_bytes
        if (self.prefix_cache_bytes + self._prefix_row_bytes
                <= self._prefix_budget_bytes):
            self._prefix_store[key] = pcache
            self.prefix_cache_bytes += self._prefix_row_bytes
        _prefix_bytes_g.set(self.prefix_cache_bytes, model=self.name)
        return pcache

    def _note_queue_wait(self, req: _Request) -> float:
        """Close out the request's queue phase: one span + the
        ``engine_queue_wait_seconds`` histogram. Returns now. Idempotent
        per request — the row-path retry after a failed batch admission
        must not observe the wait twice."""
        now = self.clock()
        if req._wait_noted:
            return now
        req._wait_noted = True
        wait = max(0.0, now - req.t_submit)
        _queue_wait_h.observe(wait, model=self.name)
        self.tracer.record("engine.queue_wait", start=req.t_submit,
                           end=now, parent=req.ctx,
                           attrs={"model": self.name})
        return now

    def _admit_one(self, req: _Request, slot: int) -> None:
        """Prefill the request's prompt and write it into ``slot``."""
        self._note_queue_wait(req)
        S = req.prompt.size
        with self.tracer.span("engine.admit", parent=req.ctx, attrs={
                "model": self.name, "slot": slot,
                "prompt_tokens": int(S), "batched": False}), \
                self._mesh_ctx():
            if req.prefix_len:
                N = req.prefix_len
                pcache = self._prefix_cache_row(req.prompt[:N])
                suf = S - N
                sbucket = pow2_bucket(suf, self.config.max_seq_len)
                if N + sbucket > self.config.max_seq_len:
                    # a padded suffix would start-clamp its cache write
                    # past the context end; serve the exact length (a
                    # rare boundary compile, like the unary tail case)
                    sbucket = suf
                padded = np.zeros((1, sbucket), np.int32)
                padded[0, :suf] = req.prompt[N:]
                with self.tracer.span("engine.prefill", attrs={
                        "prompt_tokens": int(S),
                        "prefix_len": int(N)}):
                    tok, row_cache = self._continue(
                        self._params, pcache, jnp.asarray(padded),
                        jnp.asarray([suf], jnp.int32),
                        jnp.asarray([S], jnp.int32),
                        jnp.float32(req.temperature),
                        jnp.int32(req.top_k),
                        jnp.float32(req.top_p), jnp.int32(req.seed))
            else:
                bucket = pow2_bucket(S, self.config.max_seq_len)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :S] = req.prompt
                with self.tracer.span("engine.prefill", attrs={
                        "prompt_tokens": int(S), "bucket": bucket}):
                    tok, row_cache = self._prefill(
                        self._params, jnp.asarray(padded),
                        jnp.asarray([S], jnp.int32),
                        jnp.float32(req.temperature),
                        jnp.int32(req.top_k), jnp.float32(req.top_p),
                        jnp.int32(req.seed))
            self._cache = self._insert(self._cache, row_cache,
                                       jnp.int32(slot))
        self._finalize_admission(req, slot, int(tok))

    def _finalize_admission(self, req: _Request, slot: int,
                            first: int) -> None:
        """Emit the prefill-sampled first token and arm the slot's
        host-side step state — shared by the row and batch admission
        paths so their slot initialization can never diverge."""
        st = _Slot(req=req, t_decode0=self.clock())
        self._emit(st, first)
        if not self._finished(st, first):
            with self._lock:
                self._active[slot] = st
        self._tokens[slot] = first
        self._seeds[slot] = req.seed
        self._stepidx[slot] = 1
        self._temps[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p

    def _emit(self, slot: _Slot, token: int) -> None:
        slot.produced += 1
        self.tokens_total += 1
        _tokens_total.inc(model=self.name)
        slot.req.out.put(token)

    def _finished(self, slot: _Slot, token: int) -> bool:
        done = (slot.produced >= slot.req.max_new or
                (slot.req.eos_id is not None and token == slot.req.eos_id))
        if done:
            slot.req.out.put(_END)
        return done

    def run_once(self, timeout: float = 0.1) -> bool:
        """One admit + step cycle; returns True if any work happened.
        The background loop calls this forever; tests call it directly
        (``autostart=False``) for deterministic schedules."""
        worked = self._admit(timeout)
        with self._lock:
            active = [(i, s) for i, s in enumerate(self._active)
                      if s is not None]
        if not active:
            return worked
        # greedy rows ignore seeds/filters entirely, so when EVERY
        # active slot is greedy the cheap argmax step is bit-identical
        # — and skips the per-row sampler (vocab sort) each token
        all_greedy = all(s.req.temperature <= 0.0 for _, s in active)
        with self._mesh_ctx():
            if all_greedy:
                self._cache, toks = self._step_greedy(
                    self._params, self._cache, jnp.asarray(self._tokens))
            else:
                self._cache, toks = self._step(
                    self._params, self._cache, jnp.asarray(self._tokens),
                    jnp.asarray(self._seeds), jnp.asarray(self._stepidx),
                    jnp.asarray(self._temps), jnp.asarray(self._topk),
                    jnp.asarray(self._topp))
        toks = np.asarray(toks)  # (K, B)
        K = toks.shape[0]
        self.steps_total += K
        if all_greedy:
            self.greedy_steps += K
        _steps_total.inc(K, model=self.name)
        self._stepidx += K
        self._tokens = toks[-1].copy()
        for i, slot in active:
            for t in range(K):
                tok = int(toks[t, i])
                self._emit(slot, tok)
                if self._finished(slot, tok):
                    # tokens past EOS/budget in this chunk are discarded
                    with self._lock:
                        self._active[i] = None
                    # the request's decode phase is over: one span with
                    # the token count — the per-request cost record
                    self.tracer.record(
                        "engine.decode", start=slot.t_decode0,
                        end=self.clock(), parent=slot.req.ctx,
                        attrs={"model": self.name,
                               "tokens": slot.produced})
                    break
        _occupancy.set(self.active_count, model=self.name)
        return True

    def _admit(self, timeout: float) -> bool:
        """Move pending requests into free slots.

        A BURST of pending requests sharing a prompt bucket admits
        through ONE compiled batch prefill (``_admit_batch``) instead of
        sequential row prefills; singletons and prefix-cached requests
        keep the row path (its compiled programs already exist)."""
        admitted = False
        with self._lock:
            free = [i for i, s in enumerate(self._active) if s is None]
        block = not any(s is not None for s in self._active)
        batchable: List[tuple] = []  # (req, slot) — no prefix reuse
        for slot in free:
            try:
                req = self._pending.get(block=block and not admitted,
                                        timeout=timeout)
            except queue.Empty:
                break
            admitted = True
            if req.prefix_len or self.admit_batch_max <= 1:
                self._admit_row_safe(req, slot)
            else:
                batchable.append((req, slot))
        if batchable:
            groups: dict = {}
            for req, slot in batchable:
                b = pow2_bucket(req.prompt.size, self.config.max_seq_len)
                groups.setdefault(b, []).append((req, slot))
            for bucket, members in groups.items():
                # chunk to the batch cap (bounds the transient HBM of
                # the extra full-context rows the batch prefill holds)
                for i in range(0, len(members), self.admit_batch_max):
                    chunk = members[i:i + self.admit_batch_max]
                    if len(chunk) == 1:
                        self._admit_row_safe(*chunk[0])
                        continue
                    try:
                        self._admit_batch(bucket, chunk)
                    except _CacheInvalidated:
                        raise  # run_once/_loop closes the engine
                    except Exception:  # noqa: BLE001
                        # the burst shares one device call; don't let it
                        # share the failure — retry each member through
                        # the row path, which fails (or succeeds)
                        # per-request (the engine cache is intact: the
                        # prefill materialized before any donation)
                        log.exception(
                            "batched admission failed; retrying %d "
                            "request(s) individually", len(chunk))
                        for req, slot in chunk:
                            self._admit_row_safe(req, slot)
        _queue_depth.set(self._pending.qsize(), model=self.name)
        _occupancy.set(self.active_count, model=self.name)
        return admitted

    def _admit_row_safe(self, req: _Request, slot: int) -> None:
        """Row-path admission that surfaces failure to THIS caller only."""
        try:
            self._admit_one(req, slot)
        except Exception as e:  # noqa: BLE001 — surface to the caller
            req.error = e
            req.out.put(_END)

    def _admit_batch(self, bucket: int, members: List[tuple]) -> None:
        """One shared prefill for same-bucket requests, then per-row
        inserts into their slots. Rows pad to a power-of-two batch
        (bounded compiled-program inventory: batch buckets × prompt
        buckets); pad rows are length-1 junk nothing reads or inserts.
        Token-identical to the row path: same ragged per-row lengths,
        same ``fold_in(key(seed), 0)`` sampling."""
        k = len(members)
        t0 = self.clock()
        for req, _slot in members:
            self._note_queue_wait(req)
        bb = pow2_bucket(k, min(self.slots, self.admit_batch_max))
        prompts = np.zeros((bb, bucket), np.int32)
        lens = np.ones((bb,), np.int32)
        temps = np.zeros((bb,), np.float32)
        tks = np.zeros((bb,), np.int32)
        tps = np.ones((bb,), np.float32)
        seeds = np.zeros((bb,), np.int32)
        slot_ids = np.zeros((bb,), np.int32)
        valid = np.zeros((bb,), bool)
        for i, (req, slot) in enumerate(members):
            S = req.prompt.size
            prompts[i, :S] = req.prompt
            lens[i] = S
            temps[i] = req.temperature
            tks[i] = req.top_k
            tps[i] = req.top_p
            seeds[i] = req.seed
            slot_ids[i] = slot
            valid[i] = True
        with self._mesh_ctx():
            # annotate the shared device call on the profiler timeline;
            # span-wise it is recorded below as a per-member child of
            # each admit span (a context-managed span here would be an
            # orphan root — the engine thread has no active span — and
            # would crowd the dashboard's trace list)
            ann = (self.tracer.annotator("engine.prefill")
                   if self.tracer.annotator is not None
                   else contextlib.nullcontext())
            p0 = self.clock()
            with ann:
                toks, bcache = self._prefill_batch(
                    self._params, jnp.asarray(prompts),
                    jnp.asarray(lens),
                    jnp.asarray(temps), jnp.asarray(tks),
                    jnp.asarray(tps), jnp.asarray(seeds))
            # force completion (host transfer — block_until_ready is not
            # enough on every transport) BEFORE the donating inserts: a
            # device-side prefill failure must surface while self._cache
            # is still intact, so _admit's row-path fallback retries
            # against a live engine instead of a consumed cache
            toks = np.asarray(toks)
            p1 = self.clock()
            try:
                self._cache = self._insert_rows(
                    self._cache, bcache, jnp.asarray(slot_ids),
                    jnp.asarray(valid))
            except Exception as e:  # noqa: BLE001 — donation consumed
                # the cache; fail the chunk retryably and escalate so
                # the loop closes the engine (no row-path retry can
                # succeed against a consumed cache)
                for req, _ in members:
                    req.error = EngineClosed(
                        "engine cache invalidated during admission")
                    req.out.put(_END)
                raise _CacheInvalidated(str(e)) from e
        self.batch_prefills += 1
        t1 = self.clock()
        for i, (req, slot) in enumerate(members):
            adm = self.tracer.record(
                "engine.admit", start=t0, end=t1, parent=req.ctx,
                attrs={"model": self.name, "slot": slot,
                       "prompt_tokens": int(lens[i]),
                       "batched": True, "batch": k})
            # the shared prefill's time range, nested in THIS member's
            # trace (same shape as the row path's admit→prefill)
            self.tracer.record(
                "engine.prefill", start=p0, end=p1, parent=adm,
                attrs={"prompt_tokens": int(lens[i]), "bucket": bucket,
                       "batched": True, "batch": k})
            self._finalize_admission(req, slot, int(toks[i]))

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception:  # noqa: BLE001
                log.exception("decode engine step failed; closing engine")
                # the step's donated cache is invalidated — this engine
                # can never step again. Close it: in-flight AND pending
                # requests fail with the retryable EngineClosed (503 /
                # UNAVAILABLE), later submits raise the same, and the
                # repository evicts closed engines so the next request
                # builds a fresh one instead of landing here forever.
                with self._lock:
                    self._stop.set()
                    failed = [s.req for s in self._active
                              if s is not None]
                    self._active = [None] * self.slots
                    while True:
                        try:
                            failed.append(self._pending.get_nowait())
                        except queue.Empty:
                            break
                for req in failed:
                    req.error = EngineClosed("decode engine step failed")
                    req.out.put(_END)
                return
