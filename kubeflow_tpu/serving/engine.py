"""Continuous-batching decode engine: concurrent generate requests share
one compiled decode step.

The reference platform's serving tier batches at the RPC layer
(TF-Serving's ``enable_batching`` scheduler,
``/root/reference/kubeflow/tf-serving/tf-serving-template.libsonnet:33-48``)
— whole requests queue for a fixed-shape batch. That is the wrong shape
for autoregressive decoding, where a request is a *sequence* of steps:
batching whole requests serializes callers behind the longest
generation. TPU-first, the engine instead owns a persistent device-side
KV cache with ``slots`` independent rows and runs ONE compiled
single-token step over all of them, forever:

- **submit** — a request (prompt + sampling params) joins the admission
  queue; its prompt is prefilled at batch 1 into a fresh cache row
  (one compiled prefill per power-of-two prompt bucket, exactly the
  unary path's bucketing) and the row is written into a free slot of
  the engine cache with one ``dynamic_update_slice`` (the compiled
  *insert* — cheap: it touches one row);
- **step** — every active slot advances one token under one jit:
  per-row cache positions (the decode core's ragged-batch contract,
  ``kubeflow_tpu/models/transformer.py:_decode_attend``), per-row
  sampling parameters, and per-row PRNG keys derived as
  ``fold_in(key(seed), step_index)`` so a request's tokens are
  reproducible regardless of which co-tenants share its batch;
- tokens stream to per-request queues the moment the host sees them —
  time-to-first-token is one prefill + one step, not one full
  generation.

Static shapes everywhere: the engine batch is fixed at ``slots``, idle
rows decode garbage that nothing reads (their writes land in rows the
next insert overwrites), and the compiled-program inventory is small
and bounded: prefill (per prompt bucket), the burst batch-prefill (per
batch-bucket × prompt-bucket — a burst of same-bucket requests admits
through ONE prefill instead of sequential row prefills), insert (whole
row and from-batch-row variants), the general sampled step, the
all-greedy argmax step (dispatched whenever no in-flight request
samples — it skips the per-row sampler entirely), and the
prefix-continuation (per suffix bucket). ``precompile=True`` builds
both STEP programs up front, so a greedy↔sampled workload shift never
pauses co-tenant decode on an XLA compile. Prefill programs (row and
batch) compile lazily on the first request of each shape, and since
admission and stepping share the engine thread that first-shape compile
does pause in-flight streams — pre-existing row-path behavior; the
batch path adds batch-bucket shapes to the inventory
(``KFTPU_ADMIT_BATCH=0`` pins admission back to the row path's one
program per prompt bucket if that matters more than burst TTFT).
"""
# tpulint: disable-file=TPU018 — the engine's per-bucket program
# inventory compiles lazily on first dispatch and is billed by the
# process-wide CompileLedger monitoring listener; routing these sites
# through timed_compile would AOT-compile via .lower().compile(),
# which does NOT populate jax's jit dispatch cache, so every program
# would compile twice. `precompile=True` is the engine's warm path.

from __future__ import annotations

import collections
import contextlib
import dataclasses
import logging
import os
import queue
import threading
import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.decode import (
    arm_slot,
    copy_page,
    decode_step,
    prefill,
    prefill_chunk,
    prefill_continue,
    sample_logits,
)
from kubeflow_tpu.serving.kvpool import (
    OutOfPages,
    PagePool,
    PrefixPageStore,
)
from kubeflow_tpu.obs import (
    SpanContext,
    Tracer,
    current_context,
    profiler_annotator,
)
from kubeflow_tpu.obs import requests as reqobs
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

_steps_total = DEFAULT_REGISTRY.counter(
    "kftpu_engine_steps_total", "shared decode steps executed")
_tokens_total = DEFAULT_REGISTRY.counter(
    "kftpu_engine_tokens_total", "tokens produced by the decode engine")
_occupancy = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_active_slots", "active slots in the decode batch")
_slots_g = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_slots",
    "decode-slot capacity of the engine (static; scrapers read it so "
    "queue depth can be priced in slot units without a config hint)")
_queue_depth = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_pending_requests", "requests waiting for a slot")
_prefix_hits = DEFAULT_REGISTRY.counter(
    "kftpu_engine_prefix_hits_total", "prefix-cache hits at admission")
_prefix_misses = DEFAULT_REGISTRY.counter(
    "kftpu_engine_prefix_misses_total", "prefix-cache misses at admission")
_prefix_bytes_g = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_prefix_cache_bytes",
    "HBM bytes held by cached prompt-prefix KV rows")
_prefix_budget_g = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_prefix_cache_budget_bytes",
    "prefix-cache byte budget (entries evict LRU to stay under it)")
_queue_wait_h = DEFAULT_REGISTRY.histogram(
    "engine_queue_wait_seconds",
    "time a generate request waits for a decode slot")
_kv_pages_g = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_kv_pages_in_use",
    "physical KV pages allocated out of the paged engine's pool")
_kv_pages_free_g = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_kv_pages_free",
    "unallocated KV pages left in the paged engine's pool (the "
    "engine-pages-exhausted alert rule watches this)")
_kv_pages_evictable_g = DEFAULT_REGISTRY.gauge(
    "kftpu_engine_kv_pages_evictable",
    "prefix-store pages no live slot shares: reclaimable cache, not "
    "load — occupancy/pressure consumers (autoscaler, fleet-edge "
    "admission gate) subtract these from the in-use count")
_prefill_chunks_c = DEFAULT_REGISTRY.counter(
    "kftpu_engine_prefill_chunks_total",
    "prompt chunks prefilled by the paged engine's interleaved scheduler")
_prefix_pages_shared_c = DEFAULT_REGISTRY.counter(
    "kftpu_engine_prefix_pages_shared_total",
    "KV pages mapped from the prefix trie into admitted slots "
    "(full shared pages + COW boundary pages)")
_cow_splits_c = DEFAULT_REGISTRY.counter(
    "kftpu_engine_cow_splits_total",
    "copy-on-write splits of shared boundary pages (one device-side "
    "page copy each, in place of a boundary re-prefill)")

_END = object()  # per-request stream sentinel


class EngineClosed(RuntimeError):
    """The engine was shut down (version rollover) — retryable."""


class _CacheInvalidated(RuntimeError):
    """A donating device call consumed the engine cache and then
    failed: the engine can never step again. Raised THROUGH run_once so
    the loop applies the same close-and-evict protocol as a step
    failure (row-path retries against a consumed cache would fail every
    request while keeping the corpse serving)."""


def pow2_bucket(n: int, cap: int) -> int:
    """Round ``n`` up to a power of two, capped at ``cap`` — the shared
    compiled-program bucketing rule for prompts (one compiled prefill
    per bucket, in both the unary path and engine admission).

    Total on its edges (chunked prefill makes bucket selection hot, so
    callers no longer pre-clamp): ``n <= 0`` buckets to the smallest
    program (1), ``n >= cap`` to exactly ``cap`` — even a non-power-of-
    two cap, which is its own terminal bucket (the max_seq_len program).
    """
    if cap < 1:
        raise ValueError(f"pow2_bucket cap must be >= 1, got {cap}")
    if n >= cap:
        return cap
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


def _batch_axis(leaf: jnp.ndarray) -> int:
    """Cache leaves are ``positions`` (B,)|(L, B) or ``k``/``v``
    (B, S, KH, Dh)|(L, B, S, KH, Dh) depending on whether layers are
    stacked by ``nn.scan`` — the batch axis is determined by rank."""
    return {1: 0, 2: 1, 4: 0, 5: 1}[leaf.ndim]


@dataclasses.dataclass
class _Request:
    prompt: np.ndarray           # (S,) int32, true length (no padding)
    max_new: int
    temperature: float
    top_k: int
    top_p: float
    seed: int
    eos_id: Optional[int]
    # first N prompt tokens are a reusable prefix (shared system
    # prompt): its prefill is served from the engine's prefix cache
    prefix_len: int = 0
    # trace context captured at submit() — the engine thread parents its
    # queue-wait/admit/decode spans onto the submitting request's span
    ctx: Optional[SpanContext] = None
    t_submit: float = 0.0
    # request-ledger key (docs/OBSERVABILITY.md "Request lifecycle"):
    # the propagated trace id when one exists — so the edge's record
    # and the engine's phases join — else a synthetic 32-hex id
    rid: str = ""
    # queue-wait recorded once: a failed batch admission retries members
    # through the row path, which must not observe the wait twice
    _wait_noted: bool = False
    out: "queue.Queue[Any]" = dataclasses.field(
        default_factory=queue.Queue)
    error: Optional[Exception] = None
    # consumed tokens, so stream()/result() are replayable (a second
    # call must not block on the drained queue)
    _seen: List[int] = dataclasses.field(default_factory=list)
    _done: bool = False

    def stream(self):
        """Yield token ids as the engine produces them (replayable:
        tokens already consumed are yielded first)."""
        yield from list(self._seen)
        while not self._done:
            tok = self.out.get()
            if tok is _END:
                self._done = True
                if self.error is not None:
                    raise self.error
                return
            self._seen.append(tok)
            yield tok
        if self.error is not None:
            raise self.error

    def result(self) -> List[int]:
        return list(self.stream())


@dataclasses.dataclass
class _Slot:
    req: _Request
    produced: int = 0  # tokens emitted so far (1 after the prefill sample);
    # the device-facing step/token state lives in the engine's host-side
    # arrays (_stepidx/_tokens) — the slot only tracks delivery
    t_decode0: float = 0.0  # decode-phase start (the decode span's start)
    # every token emitted, in order — the cache-recovery replay prompt
    # is (request prompt + emitted); delivery itself rides req.out
    emitted: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _PrefillJob:
    """A slot mid-chunked-prefill (paged engine): the prompt feeds the
    pool one fixed-width chunk per scheduler cycle, interleaved with
    co-tenant decode steps."""

    req: _Request
    slot: int
    tokens: np.ndarray        # full token sequence to prefill
    next: int                 # next position to feed (== start after arm)
    t_admit: float = 0.0
    chunks: int = 0
    # replay (cache-recovery) jobs resume a live stream: the first
    # sampled token continues at the preserved fold index and the
    # delivery counter, instead of starting a fresh request at fold 0
    fold0: int = 0
    produced0: int = 0
    store_prefix: int = 0     # prefix tokens to trie-pin after prefill
    last_tok: int = 0         # sampled next token, set by the final chunk


class DecodeEngine:
    """One engine per loaded transformer model version.

    ``submit()`` is thread-safe and returns a handle whose ``stream()``
    yields tokens as decode steps complete. The engine thread runs
    admit → step forever; ``close()`` drains it.
    """

    def __init__(self, config, params, *, slots: int = 8,
                 steps_per_sync: int = 1, mesh=None,
                 prefix_cache_entries: int = 4,
                 prefix_cache_bytes: Optional[int] = None,
                 sampler_bound: Optional[int] = None,
                 sampler_impl: Optional[str] = None,
                 admit_batch_max: Optional[int] = None,
                 paged: Optional[bool] = None,
                 kv_page_size: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 paged_attention_impl: Optional[str] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 prefill_chunks_per_cycle: int = 1,
                 recoveries: Optional[int] = None,
                 precompile: bool = False,
                 autostart: bool = True, name: str = "",
                 clock: Optional[Clock] = None,
                 tracer: Optional[Tracer] = None,
                 request_ledger: Optional["reqobs.RequestLedger"] = None,
                 hbm_sampler=None) -> None:
        self.config = config
        self.slots = slots
        # paged KV cache + chunked prefill (docs/SERVING.md). Dense mode
        # remains the parity oracle and the default; KFTPU_PAGED=1 flips
        # a deployment fleet-wide without code changes.
        if paged is None:
            paged = os.environ.get("KFTPU_PAGED", "0") not in ("0", "")
        self.paged = bool(paged)
        # cache-recovery budget: a donated-cache failure rebuilds the
        # pool and replays in-flight slots this many times before the
        # engine gives up and self-closes (the old, always-close path)
        if recoveries is None:
            recoveries = int(os.environ.get("KFTPU_ENGINE_RECOVERIES",
                                            "2"))
        self._recoveries_left = max(0, int(recoveries))
        # host-side timing source for queue-wait/admit/decode spans; a
        # fake clock makes engine span trees deterministic in tests
        self.clock: Clock = clock if clock is not None else time.monotonic
        # spans land in the shared collector; the profiler annotator
        # mirrors live admit/prefill spans onto the XLA host timeline
        # during a capture (docs/OBSERVABILITY.md)
        self.tracer = tracer if tracer is not None else Tracer(
            clock=self.clock, annotator=profiler_annotator())
        # the request-lifecycle ledger (docs/OBSERVABILITY.md "Request
        # lifecycle"): phase marks ride the clock reads this file
        # already takes; the process-wide default joins edge-side
        # phases for the same trace id
        self.rledger = (request_ledger if request_ledger is not None
                        else reqobs.DEFAULT_LEDGER)
        # lax.top_k-bounded sampler (models/decode.py:sample_logits
        # ``bound``): avoids the per-token full-vocab sort the exact
        # sampler pays at every sampled step — 0 selects the exact sort
        # path, None reads KFTPU_SAMPLER_BOUND (default 64)
        if sampler_bound is None:
            sampler_bound = int(os.environ.get("KFTPU_SAMPLER_BOUND",
                                               "64"))
        self.sampler_bound = int(sampler_bound)
        # sampler implementation: "bounded" (lax.top_k, truncating —
        # the historical fast path), "exact_sort" (full-vocab sort —
        # the historical exact path), "fused" (ops/sampling.py Pallas
        # kernel: exact support at bounded cost). "auto" keeps the
        # bounded path when a bound is set and upgrades the exact path
        # (bound 0) to the fused kernel, so sampler_bound stops being a
        # correctness/perf tradeoff.
        if sampler_impl is None:
            sampler_impl = os.environ.get("KFTPU_SAMPLER_IMPL", "auto")
        if sampler_impl == "auto":
            sampler_impl = ("bounded" if self.sampler_bound > 0
                            else "fused")
        if sampler_impl not in ("bounded", "exact_sort", "fused"):
            raise ValueError(
                f"unknown sampler_impl {sampler_impl!r}; valid: auto, "
                "bounded, exact_sort, fused")
        self.sampler_impl = sampler_impl
        # paged-cache geometry: page size defaults to the largest
        # power-of-two divisor of max_seq_len up to 64; the pool
        # defaults to full provisioning (slots × pages-per-row), and a
        # smaller kv_pages sizes HBM by LIVE tokens instead of
        # slots × max_len (admission then gates on free pages)
        Smax = config.max_seq_len
        if self.paged:
            if kv_page_size is None:
                env = os.environ.get("KFTPU_KV_PAGE_SIZE")
                kv_page_size = int(env) if env else 0
            if not kv_page_size:
                kv_page_size = 1
                while (kv_page_size < 64
                       and Smax % (kv_page_size * 2) == 0):
                    kv_page_size *= 2
            self.kv_page_size = int(kv_page_size)
            self._n_logical = Smax // self.kv_page_size
            if kv_pages is None:
                env = os.environ.get("KFTPU_KV_PAGES")
                kv_pages = int(env) if env else slots * self._n_logical
            self.kv_pages = int(kv_pages)
            if prefill_chunk_tokens is None:
                env = os.environ.get("KFTPU_PREFILL_CHUNK")
                prefill_chunk_tokens = int(env) if env else min(256, Smax)
            self.prefill_chunk_tokens = max(1, int(prefill_chunk_tokens))
            self.prefill_chunks_per_cycle = max(
                1, int(prefill_chunks_per_cycle))
            # device-side attention core for the paged decode STEP:
            # "kernel" streams K/V through the page table inside a
            # Pallas kernel (ops/paged_attention.py — HBM reads
            # proportional to live pages), "gather" materializes the
            # dense logical view (the bit-parity oracle and the
            # interpret-mode fallback), "auto" picks the kernel on the
            # TPU backend and the gather elsewhere. Greedy streams are
            # token-identical either way (test-gated).
            if paged_attention_impl is None:
                paged_attention_impl = os.environ.get(
                    "KFTPU_PAGED_ATTN", "auto")
            self.paged_attention_impl = paged_attention_impl
            # paged-kernel head-group compute block: default None =
            # the shape-keyed tile table (ops/autotune.py; safe
            # fallback is the per-head loop); KFTPU_PAGED_HEAD_BLOCK
            # pins an explicit override for a chip experiment
            head_block_env = os.environ.get("KFTPU_PAGED_HEAD_BLOCK")
            paged_head_block = (int(head_block_env) if head_block_env
                                else config.paged_head_block)
            self._cfg = dataclasses.replace(
                config, kv_page_size=self.kv_page_size,
                kv_pages=self.kv_pages,
                paged_attention_impl=paged_attention_impl,
                paged_head_block=paged_head_block)
            self._cfg.validate()
        else:
            self.kv_page_size = 0
            self.kv_pages = 0
            self.paged_attention_impl = "gather"
            self._cfg = config
        # burst admission: same-bucket pending requests prefill as ONE
        # batch of up to this many rows. The cap bounds the transient
        # HBM spike (a batch prefill materializes that many extra
        # full-context KV rows until their inserts land) and the
        # compiled-program inventory; <=1 disables batching entirely
        # (every request takes the row path). KFTPU_ADMIT_BATCH.
        if admit_batch_max is None:
            admit_batch_max = int(os.environ.get("KFTPU_ADMIT_BATCH",
                                                 "8"))
        self.admit_batch_max = int(admit_batch_max)
        # multi-chip serving: with a Mesh (params already placed with
        # tensor-parallel shardings, e.g. via models.param_partition_specs)
        # every compiled engine program runs under it, and the model's
        # logical-axis constraints shard the KV cache over the same axes
        self.mesh = mesh
        # decode steps executed on-device per host round-trip: >1 hides
        # dispatch/transfer latency (the dominant cost when the host is
        # remote from the chip) at the price of admission/EOS reacting
        # up to that many tokens late — tokens past a row's EOS or
        # budget are computed and discarded
        self.steps_per_sync = max(1, int(steps_per_sync))
        self.name = name or "model"
        # the NORMALIZED name: every engine series must share one model
        # label value or per-model joins (slots vs pages) find no row
        _slots_g.set(self.slots, model=self.name)
        # an obs.xprof.HbmSampler sampled once per admit cycle, so the
        # admission decision's watermark (weights + KV + transient
        # prefill spike) is what kftpu_hbm_bytes{model=...} shows; CPU
        # backends (memory_stats() is None) degrade to no series
        if hbm_sampler is not None and not getattr(
                hbm_sampler, "model", ""):
            hbm_sampler.model = self.name
        self.hbm_sampler = hbm_sampler
        self._params = params
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._active: List[Optional[_Slot]] = [None] * slots
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # guards _active between admit/step

        if mesh is not None:
            from kubeflow_tpu.parallel.mesh import mesh_context

            self._mesh_ctx = lambda: mesh_context(mesh)
        else:
            import contextlib

            self._mesh_ctx = contextlib.nullcontext

        Smax = config.max_seq_len
        impl = self.sampler_impl
        bnd = (self.sampler_bound
               if impl == "bounded" and self.sampler_bound > 0 else None)

        def sample_rows(logits, seeds, idx, temps, tks, tps):
            """Per-row sampling under the engine's fold_in(key(seed),
            step) reproducibility contract, dispatched to the
            configured sampler implementation. (B, V) logits in, (B,)
            int32 tokens out; every parameter is per-row."""
            if impl == "fused":
                from kubeflow_tpu.ops.sampling import fused_sample

                keys = jax.vmap(lambda s, i: jax.random.fold_in(
                    jax.random.key(s), i))(seeds, idx)
                return fused_sample(logits, keys, temperature=temps,
                                    top_k=tks, top_p=tps)

            def one(row_logits, seed, i, t, k, p):
                key = jax.random.fold_in(jax.random.key(seed), i)
                return sample_logits(row_logits[None], key,
                                     temperature=t, top_k=k, top_p=p,
                                     bound=bnd)[0]

            return jax.vmap(one)(logits, seeds, idx, temps, tks, tps)

        self._sample_rows = sample_rows

        def _sample1(logits, seed, fold, temperature, top_k, top_p):
            """One row through the shared sampler (prefill's first
            token; the paged path's post-chunk sample, where ``fold``
            continues a replayed stream's step index)."""
            return sample_rows(
                logits, jnp.reshape(seed, (1,)), jnp.reshape(fold, (1,)),
                jnp.reshape(temperature, (1,)), jnp.reshape(top_k, (1,)),
                jnp.reshape(top_p, (1,)))[0]

        @jax.jit
        def _prefill_and_sample(params, prompt, true_len, temperature,
                                top_k, top_p, seed, fold):
            logits, cache = prefill(config, params, prompt, true_len)
            tok = _sample1(logits, seed, fold, temperature, top_k, top_p)
            return tok, cache

        @jax.jit
        def _continue_and_sample(params, cache, suffix, suffix_len,
                                 total_len, temperature, top_k, top_p,
                                 seed):
            logits, cache = prefill_continue(
                config, params, cache, suffix, suffix_len, total_len)
            tok = _sample1(logits, seed, jnp.int32(0), temperature,
                           top_k, top_p)
            return tok, cache

        @jax.jit
        def _prefill_batch_and_sample(params, prompts, true_lens, temps,
                                      top_ks, top_ps, seeds):
            """Burst admission: same-bucket requests prefill TOGETHER —
            one compiled (B, S) prefill instead of B sequential row
            prefills, with per-row ragged lengths and sampling params
            (the decode core's contract). Burst time-to-first-token
            drops from B×prefill to ~one batched prefill."""
            logits, cache = prefill(config, params, prompts, true_lens)
            toks = sample_rows(logits, seeds,
                               jnp.zeros_like(seeds), temps, top_ks,
                               top_ps)
            return toks, cache

        self._prefill_batch = _prefill_batch_and_sample

        def _chunk_and_sample(params, cache, tokens, slot, start, true_n,
                              seed, fold, temperature, top_k, top_p):
            """One paged prefill chunk + the post-chunk sample. The
            sample is only consumed on a job's FINAL chunk (the logits
            feed the stream's next token); earlier chunks pay the one
            extra row-sample so the whole prompt path stays a single
            compiled program."""
            logits, cache = prefill_chunk(self._cfg, params, cache,
                                          tokens, slot, start, true_n)
            tok = _sample1(logits, seed, fold, temperature, top_k, top_p)
            return tok, cache

        self._chunk = jax.jit(_chunk_and_sample, donate_argnums=(1,))

        # page-map surgery program (models/decode.py:arm_slot — the
        # paged-cache leaf contract lives in ONE module)
        self._arm = jax.jit(arm_slot, donate_argnums=(0,))
        # COW-split page copy (models/decode.py:copy_page, same leaf
        # contract): one physical page duplicated device-side
        self._copy_page = jax.jit(copy_page, donate_argnums=(0,))

        def _insert_rows(engine_cache, batch_cache, slot_ids, valid):
            """Insert every valid batch-prefill row into its engine slot
            in ONE device dispatch (a scan of per-row dynamic updates).
            Burst admission used to pay one dispatch per member; on
            high-dispatch-latency transports those per-row launches
            dominated admission wall time (measured round 5: 48 inserts
            ≈ 1.4 s of the engine bench's 4.2 s). Pad rows (``valid``
            False) write a slot's current contents back — a no-op."""

            def put(big, small, row, slot, ok):
                ax = _batch_axis(big)
                piece = jax.lax.dynamic_slice_in_dim(
                    small, row, 1, axis=ax).astype(big.dtype)
                idx = tuple(slot if a == ax else 0
                            for a in range(big.ndim))
                cur = jax.lax.dynamic_slice(big, idx, piece.shape)
                return jax.lax.dynamic_update_slice(
                    big, jnp.where(ok, piece, cur), idx)

            def body(cache, xs):
                row, slot, ok = xs
                return jax.tree_util.tree_map(
                    lambda big, small: put(big, small, row, slot, ok),
                    cache, batch_cache), None

            cache, _ = jax.lax.scan(
                body, engine_cache,
                (jnp.arange(slot_ids.shape[0]), slot_ids, valid))
            return cache

        self._insert_rows = jax.jit(_insert_rows, donate_argnums=(0,))

        self._continue = _continue_and_sample
        # LRU of prefilled prompt prefixes: (len, token bytes) →
        # 1-row cache, BYTE-budgeted (every entry is a full-context row,
        # so the HBM cost scales with max_seq_len × layers — an entry
        # count hides it from the operator). Budget resolution: the
        # explicit ``prefix_cache_bytes`` arg, else KFTPU_PREFIX_CACHE_
        # BYTES, else ``prefix_cache_entries`` × the per-row byte size
        # (computed below once the cache layout is known). _continue
        # never mutates a stored entry (functional apply, no donation).
        self._prefix_store: "collections.OrderedDict" = \
            collections.OrderedDict()
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_cache_bytes = 0  # bytes currently held

        def _insert(engine_cache, row_cache, slot):
            return jax.tree_util.tree_map(
                lambda big, row: jax.lax.dynamic_update_slice(
                    big, row.astype(big.dtype),
                    tuple(slot if a == _batch_axis(big) else 0
                          for a in range(big.ndim))),
                engine_cache, row_cache)

        self._insert = jax.jit(_insert, donate_argnums=(0,))

        K = self.steps_per_sync

        def _step(params, cache, tokens, seeds, step_idx, temps, top_k,
                  top_p):
            """K decode steps under one jit; returns (cache, (K, B))."""

            def body(carry, t):
                cache, tokens = carry
                logits, cache = decode_step(self._cfg, params, cache,
                                            tokens)
                nxt = sample_rows(logits, seeds, step_idx + t, temps,
                                  top_k, top_p)
                return (cache, nxt), nxt

            (cache, _), toks = jax.lax.scan(
                body, (cache, tokens), jnp.arange(K))
            return cache, toks

        def _step_greedy(params, cache, tokens):
            """The all-greedy fast path: no per-row sampler, no vocab
            sort — argmax only. Dispatched when every in-flight request
            is greedy (the host knows each slot's sampling params), the
            common serving load and the bench configuration."""

            def body(carry, _):
                cache, tokens = carry
                logits, cache = decode_step(self._cfg, params, cache,
                                            tokens)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (cache, nxt), nxt

            (cache, _), toks = jax.lax.scan(
                body, (cache, tokens), None, length=K)
            return cache, toks

        self._step = jax.jit(_step, donate_argnums=(1,))
        self._step_greedy = jax.jit(_step_greedy, donate_argnums=(1,))
        self._prefill = _prefill_and_sample

        # engine cache: the decode cache shape at batch = slots. eval_
        # shape on prefill gives the layout without running it. Paged
        # mode: only positions/pages carry the batch axis — the k/v POOL
        # is batch-free (kv_pages blocks shared by every slot), which is
        # exactly how cache HBM decouples from slots × max_len.
        probe = jnp.zeros((1, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda p: prefill(self._cfg, p, probe)[1], params)

        def _leaf_kind(path) -> str:
            key = getattr(path[-1], "key", None)
            return key if key in ("positions", "pages") else "kv"

        def _engine_shape(path, s):
            if self.paged:
                kind = _leaf_kind(path)
                if kind == "positions":
                    return s.shape[:-1] + (slots,)
                if kind == "pages":
                    return s.shape[:-2] + (slots,) + s.shape[-1:]
                return s.shape
            return tuple(slots if a == _batch_axis(s) else d
                         for a, d in enumerate(s.shape))

        def _init_leaf(path, s):
            shape = _engine_shape(path, s)
            if self.paged:
                kind = _leaf_kind(path)
                if kind == "positions":
                    # disarmed: writes past max_seq_len scatter-drop
                    return jnp.full(shape, Smax, s.dtype)
                if kind == "pages":
                    return jnp.full(shape, self.kv_pages, s.dtype)
            return jnp.zeros(shape, s.dtype)

        def _zeros_tree():
            return jax.tree_util.tree_map_with_path(_init_leaf, shapes)

        if self.paged:
            # one physical page's bytes across the stacked k/v pool
            # leaves — the paged prefix store budgets in PAGES
            self._page_bytes = int(sum(
                int(np.prod(s.shape)) // self.kv_pages
                * jnp.dtype(s.dtype).itemsize
                for p, s in jax.tree_util.tree_leaves_with_path(shapes)
                if _leaf_kind(p) == "kv"))
            self._prefix_row_bytes = self._page_bytes * self._n_logical
        else:
            # a stored prefix row IS this batch-1 full-context cache —
            # its byte size anchors the prefix-cache budget
            self._prefix_row_bytes = int(sum(
                int(np.prod(s.shape)) * jnp.dtype(s.dtype).itemsize
                for s in jax.tree_util.tree_leaves(shapes)))
        if prefix_cache_bytes is None:
            env = os.environ.get("KFTPU_PREFIX_CACHE_BYTES")
            prefix_cache_bytes = int(env) if env else None
        if prefix_cache_bytes is None:
            prefix_cache_bytes = (max(0, int(prefix_cache_entries))
                                  * self._prefix_row_bytes)
        self._prefix_budget_bytes = max(0, int(prefix_cache_bytes))
        _prefix_budget_g.set(self._prefix_budget_bytes, model=self.name)

        if mesh is None:
            self._fresh_cache = _zeros_tree
            self._cache = _zeros_tree()
        else:
            # k/v leaves shard their kv-heads axis (rank-2 from the end)
            # per the model's logical rules, so the full-context cache
            # never materializes on one device; shape_aware_spec drops
            # the axis when it doesn't divide (GQA kv heads < tp)
            from jax.sharding import NamedSharding

            from kubeflow_tpu.parallel.mesh import (
                logical_to_mesh_axes,
                shape_aware_spec,
            )

            def _sharding(path, s):
                shape = _engine_shape(path, s)
                names = [None] * len(shape)
                if len(shape) >= 4:
                    names[-2] = "heads"
                spec = shape_aware_spec(
                    logical_to_mesh_axes(names, config.rules), shape,
                    mesh)
                return NamedSharding(mesh, spec)

            sharded_zeros = jax.jit(
                _zeros_tree,
                out_shardings=jax.tree_util.tree_map_with_path(
                    _sharding, shapes))

            def _fresh_sharded():
                with self._mesh_ctx():
                    return sharded_zeros()

            self._fresh_cache = _fresh_sharded
            self._cache = _fresh_sharded()
        # host-side per-slot sampling state, padded to the batch
        self._tokens = np.zeros((slots,), np.int32)
        self._seeds = np.zeros((slots,), np.int32)
        self._stepidx = np.zeros((slots,), np.int32)
        self._temps = np.zeros((slots,), np.float32)
        self._topk = np.zeros((slots,), np.int32)
        self._topp = np.ones((slots,), np.float32)
        self.steps_total = 0
        self.tokens_total = 0
        self.greedy_steps = 0  # steps served by the argmax fast path
        self.batch_prefills = 0  # burst admissions served batched
        self.prefill_chunks = 0  # chunk programs run (paged scheduler)
        self.recoveries = 0      # cache rebuild-and-replay events
        self.prefix_pages_shared = 0  # pages mapped from the trie
        self.cow_splits = 0      # boundary-page copy-on-write splits
        if self.paged:
            self._pool = PagePool(self.kv_pages, self.kv_page_size,
                                  slots, self._n_logical)
            budget_pages = self._prefix_budget_bytes // max(
                1, self._page_bytes)
            self._prefix_pages = PrefixPageStore(self._pool, budget_pages)
            # slots mid-chunked-prefill, oldest first (insertion order)
            self._prefilling: "collections.OrderedDict[int, _PrefillJob]" \
                = collections.OrderedDict()
            # head-of-line requests admission popped but could not place
            # (no free slot pages yet) — FIFO order is preserved
            self._waiting: "collections.deque[_Request]" = \
                collections.deque()
            # host-authoritative per-slot position (the device value
            # drifts for idle/prefilling rows by design)
            self._pos_host = np.zeros((slots,), np.int64)
            self._slot_budget = np.zeros((slots,), np.int64)
        if precompile:
            self._precompile_steps()
        if autostart:
            self.start()

    def _precompile_steps(self) -> None:
        """Run BOTH step programs once on the empty batch so the
        greedy↔sampled dispatch switch never stalls in-flight streams
        on a mid-serving XLA compile. Every slot is idle, so the junk
        tokens land in rows the next insert fully overwrites."""
        B = self.slots
        toks = jnp.zeros((B,), jnp.int32)
        vec_i = jnp.zeros((B,), jnp.int32)
        ones_f = jnp.ones((B,), jnp.float32)
        with self._mesh_ctx():
            self._cache, _ = self._step_greedy(
                self._params, self._cache, toks)
            self._cache, _ = self._step(
                self._params, self._cache, toks, vec_i, vec_i, ones_f,
                vec_i, ones_f)

    # -- public API --------------------------------------------------------

    def submit(self, prompt, *, max_new: int, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0, seed: int = 0,
               eos_id: Optional[int] = None,
               prefix_len: int = 0) -> _Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size + max_new > self.config.max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new} exceeds "
                f"context {self.config.max_seq_len}")
        if self.paged:
            # a request whose worst case exceeds the whole pool can
            # NEVER reserve (even with every prefix entry evicted) —
            # admitting it would wedge the strict-FIFO head of line
            # forever, so reject it here instead
            need = self._pool.pages_needed(prompt.size + max_new)
            if need > self._pool.pages_total:
                raise ValueError(
                    f"prompt {prompt.size} + max_new {max_new} needs "
                    f"{need} KV pages but the pool holds only "
                    f"{self._pool.pages_total} — raise kv_pages or "
                    f"shrink the request")
        prefix_len = int(prefix_len)
        if prefix_len and not 0 < prefix_len < prompt.size:
            raise ValueError(
                f"prefix_len {prefix_len} must be in (0, prompt length "
                f"{prompt.size}) — the suffix may not be empty")
        if (not self.paged
                and self._prefix_budget_bytes < self._prefix_row_bytes):
            # cache disabled, or one full-context row alone would bust
            # the byte budget: honor it by serving the full prefill.
            # (Paged mode shares at PAGE granularity — its store
            # enforces the page budget per entry itself.)
            prefix_len = 0
        req = _Request(prompt=prompt, max_new=max_new,
                       temperature=float(temperature), top_k=int(top_k),
                       top_p=float(top_p), seed=int(seed), eos_id=eos_id,
                       prefix_len=prefix_len,
                       # the submitting thread's active span (serving
                       # handler) — engine spans parent onto it
                       ctx=current_context(), t_submit=self.clock())
        # the lock orders this against close()'s drain: a submit must
        # either land before the drain (and be failed by it) or see the
        # stop flag and raise — never sit in a queue nobody reads
        # ledger key: join the propagated trace's record (the edge may
        # already have started it) or open a fresh engine-only record.
        # Started BEFORE the queue put — the engine thread may admit
        # the request immediately, and its marks must find the record
        req.rid = (req.ctx.trace_id if req.ctx is not None
                   else reqobs.synthetic_rid())
        self.rledger.start(req.rid, t=req.t_submit, model=self.name)
        with self._lock:
            if self._stop.is_set():
                # the request is over (503 to the caller): close its
                # record — whichever tier opened it
                self.rledger.finish(req.rid, req.t_submit)
                raise EngineClosed("decode engine closed")
            self._pending.put(req)
        _queue_depth.set(self._pending.qsize(), model=self.name)
        return req

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"decode-engine-{self.name}")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # fail whatever is still in flight — a hung client is worse than
        # a retried request (version retirement path). The lock pairs
        # with submit(): after this drain no new request can enqueue.
        with self._lock:
            active = [s.req for s in self._active if s is not None]
            self._active = [None] * self.slots
            if self.paged:
                active.extend(j.req for j in self._prefilling.values())
                self._prefilling.clear()
                active.extend(self._waiting)
                self._waiting.clear()
            while True:
                try:
                    active.append(self._pending.get_nowait())
                except queue.Empty:
                    break
        t_close = self.clock()
        for req in active:
            req.error = EngineClosed("decode engine closed")
            req.out.put(_END)
            # the stream is over for its client: fold what we know
            self.rledger.finish(req.rid, t_close)

    @property
    def closed(self) -> bool:
        """True once the engine can no longer serve (explicit close or
        a step failure that invalidated the donated cache)."""
        return self._stop.is_set()

    @property
    def active_count(self) -> int:
        """Slots serving a stream: decoding, plus (paged) slots whose
        prompt is still chunk-prefilling — they hold pages and a slot
        either way."""
        with self._lock:
            n = sum(s is not None for s in self._active)
        if self.paged:
            n += len(self._prefilling)
        return n

    @property
    def pending_count(self) -> int:
        """Requests admitted to submit() but not yet holding a slot."""
        n = self._pending.qsize()
        if self.paged:
            n += len(self._waiting)
        return n

    def snapshot(self) -> dict:
        """Occupancy snapshot for the autoscaler's engine poll
        (:meth:`kubeflow_tpu.autoscale.metrics.MetricsAggregator
        .observe_engine`): active slots are the concurrency the proxy
        can't see (one HTTP generate call hides a whole decode stream),
        pending is the admission-queue depth. Paged engines add the
        page-pool fields the capacity planner reads — token-level
        occupancy, which saturates long before slot count when contexts
        run long."""
        snap = {"active_slots": self.active_count,
                "pending": self.pending_count,
                "slots": self.slots,
                "closed": self.closed}
        if self.paged:
            snap.update({
                "paged": True,
                "page_size": self.kv_page_size,
                "pages_total": self._pool.pages_total,
                "pages_free": self._pool.pages_free,
                "pages_in_use": self._pool.pages_in_use,
                "pages_reserved": self._pool.reserved_total,
                # reclaimable prefix-store pins: occupancy consumers
                # (autoscaler) subtract these — cache is not load
                "pages_evictable": self._prefix_pages.pages_evictable,
                "prefill_slots": len(self._prefilling),
                "paged_attention_impl": self.paged_attention_impl,
                # prefix-trie + copy-on-write effectiveness counters
                # (docs/OBSERVABILITY.md; served by /api/metrics/engine)
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_pages_shared": self.prefix_pages_shared,
                "cow_splits": self.cow_splits,
            })
        return snap

    # -- engine internals --------------------------------------------------

    def _prefix_cache_row(self, prefix: np.ndarray):
        """The 1-row cache holding this prefilled prefix (LRU)."""
        key = (prefix.size, prefix.tobytes())
        cached = self._prefix_store.get(key)
        if cached is not None:
            self._prefix_store.move_to_end(key)
            self.prefix_hits += 1
            _prefix_hits.inc(model=self.name)
            return cached
        self.prefix_misses += 1
        _prefix_misses.inc(model=self.name)
        N = prefix.size
        bucket = pow2_bucket(N, self.config.max_seq_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :N] = prefix
        # sampling args are dummies — only the cache is kept
        _, pcache = self._prefill(
            self._params, jnp.asarray(padded),
            jnp.asarray([N], jnp.int32), jnp.float32(0.0),
            jnp.int32(0), jnp.float32(1.0), jnp.int32(0), jnp.int32(0))
        # byte-budget admission: evict LRU until the new row fits
        # (submit() already routed away callers that can never fit)
        while (self._prefix_store and self.prefix_cache_bytes
                + self._prefix_row_bytes > self._prefix_budget_bytes):
            self._prefix_store.popitem(last=False)
            self.prefix_cache_bytes -= self._prefix_row_bytes
        if (self.prefix_cache_bytes + self._prefix_row_bytes
                <= self._prefix_budget_bytes):
            self._prefix_store[key] = pcache
            self.prefix_cache_bytes += self._prefix_row_bytes
        _prefix_bytes_g.set(self.prefix_cache_bytes, model=self.name)
        return pcache

    def _note_queue_wait(self, req: _Request) -> float:
        """Close out the request's queue phase: one span + the
        ``engine_queue_wait_seconds`` histogram. Returns now. Idempotent
        per request — the row-path retry after a failed batch admission
        must not observe the wait twice."""
        now = self.clock()
        if req._wait_noted:
            return now
        req._wait_noted = True
        wait = max(0.0, now - req.t_submit)
        # exemplar: the request's propagated trace, so a slow queue-wait
        # bucket opens the trace that actually waited
        _queue_wait_h.observe(
            wait,
            exemplar_trace_id=(req.ctx.trace_id
                               if req.ctx is not None else None),
            model=self.name)
        self.tracer.record("engine.queue_wait", start=req.t_submit,
                           end=now, parent=req.ctx,
                           attrs={"model": self.name})
        # the ledger's queue phase closes on the same timestamp: slot
        # placement / batch assembly time is admission from here on
        self.rledger.mark(req.rid, reqobs.ADMISSION, now)
        return now

    def _admit_one(self, req: _Request, slot: int) -> None:
        """Prefill the request's prompt and write it into ``slot``."""
        self._note_queue_wait(req)
        S = req.prompt.size
        with self.tracer.span("engine.admit", parent=req.ctx, attrs={
                "model": self.name, "slot": slot,
                "prompt_tokens": int(S), "batched": False}), \
                self._mesh_ctx():
            # prefill phase opens here (prefix-row prep IS prefill
            # work); admission was the gap since _note_queue_wait
            self.rledger.mark(req.rid, reqobs.PREFILL, self.clock())
            if req.prefix_len:
                N = req.prefix_len
                pcache = self._prefix_cache_row(req.prompt[:N])
                suf = S - N
                sbucket = pow2_bucket(suf, self.config.max_seq_len)
                if N + sbucket > self.config.max_seq_len:
                    # a padded suffix would start-clamp its cache write
                    # past the context end; serve the exact length (a
                    # rare boundary compile, like the unary tail case)
                    sbucket = suf
                padded = np.zeros((1, sbucket), np.int32)
                padded[0, :suf] = req.prompt[N:]
                with self.tracer.span("engine.prefill", attrs={
                        "prompt_tokens": int(S),
                        "prefix_len": int(N)}):
                    tok, row_cache = self._continue(
                        self._params, pcache, jnp.asarray(padded),
                        jnp.asarray([suf], jnp.int32),
                        jnp.asarray([S], jnp.int32),
                        jnp.float32(req.temperature),
                        jnp.int32(req.top_k),
                        jnp.float32(req.top_p), jnp.int32(req.seed))
            else:
                bucket = pow2_bucket(S, self.config.max_seq_len)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :S] = req.prompt
                with self.tracer.span("engine.prefill", attrs={
                        "prompt_tokens": int(S), "bucket": bucket}):
                    tok, row_cache = self._prefill(
                        self._params, jnp.asarray(padded),
                        jnp.asarray([S], jnp.int32),
                        jnp.float32(req.temperature),
                        jnp.int32(req.top_k), jnp.float32(req.top_p),
                        jnp.int32(req.seed), jnp.int32(0))
            self._cache = self._insert(self._cache, row_cache,
                                       jnp.int32(slot))
        # the prefill-sampled first token must surface NOW — emitting it
        # is what makes TTFT one prefill + one step
        self._finalize_admission(req, slot, int(tok))  # tpulint: disable=TPU017

    def _finalize_admission(self, req: _Request, slot: int, first: int,
                            t: Optional[float] = None) -> None:
        """Emit the prefill-sampled first token and arm the slot's
        host-side step state — shared by the row and batch admission
        paths so their slot initialization can never diverge. ``t`` is
        the caller's already-read timestamp (the batch path stamps the
        whole chunk once); the row path reads its own, as before."""
        t = t if t is not None else self.clock()
        st = _Slot(req=req, t_decode0=t)
        # the TTFT span: one per request, edge-to-first-token visible
        # in the trace tree the dashboard exemplar opens
        self.tracer.record(
            "engine.first_token", start=req.t_submit, end=t,
            parent=req.ctx,
            attrs={"model": self.name,
                   "ttft_ms": round((t - req.t_submit) * 1000.0, 3)})
        self._emit(st, first, t)
        if not self._finished(st, first, t):
            with self._lock:
                self._active[slot] = st
        self._tokens[slot] = first
        self._seeds[slot] = req.seed
        self._stepidx[slot] = 1
        self._temps[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p

    def _emit(self, slot: _Slot, token: int, t: float) -> None:
        """The per-token hot path. ``t`` is a timestamp the caller
        ALREADY read (run_once stamps one step-end time for every token
        of the sync batch — the moment the host actually saw them);
        neither this method nor the ledger reads a clock here."""
        slot.produced += 1
        slot.emitted.append(token)
        self.tokens_total += 1
        _tokens_total.inc(model=self.name)
        self.rledger.emit(slot.req.rid, t)
        slot.req.out.put(token)

    def _finished(self, slot: _Slot, token: int, t: float) -> bool:
        done = (slot.produced >= slot.req.max_new or
                (slot.req.eos_id is not None and token == slot.req.eos_id))
        if done:
            slot.req.out.put(_END)
            # last token: fold the request's record (histograms +
            # flight ring) on the same already-read timestamp
            self.rledger.finish(slot.req.rid, t)
        return done

    def run_once(self, timeout: float = 0.1) -> bool:
        """One admit + prefill-chunk + step cycle; returns True if any
        work happened. The background loop calls this forever; tests
        call it directly (``autostart=False``) for deterministic
        schedules. A donating device call that fails mid-decode is
        recovered in place (cache rebuild + slot replay) while the
        recovery budget lasts."""
        if self.paged:
            # admission arms slots (donating) and chunks donate the
            # cache: every paged device call recovers under the same
            # budget. Dense admission keeps its own per-request error
            # handling (and _CacheInvalidated keeps the close protocol).
            try:
                worked = self._admit(timeout)
                worked = self._prefill_tick() or worked
            except _CacheInvalidated:
                raise
            except Exception:  # noqa: BLE001 — donated cache consumed
                log.exception("paged admission/prefill failed")
                if self._maybe_recover("paged admission/prefill"):
                    return True
                raise
        else:
            worked = self._admit(timeout)
        with self._lock:
            active = [(i, s) for i, s in enumerate(self._active)
                      if s is not None]
        if not active:
            return worked
        # greedy rows ignore seeds/filters entirely, so when EVERY
        # active slot is greedy the cheap argmax step is bit-identical
        # — and skips the per-row sampler (vocab sort) each token
        all_greedy = all(s.req.temperature <= 0.0 for _, s in active)
        t_step0 = self.clock()
        try:
            if self.paged:
                # page growth arms device rows (donating) — same
                # recovery scope as the step itself
                self._ensure_pages(i for i, _ in active)
            with self._mesh_ctx():
                if all_greedy:
                    self._cache, toks = self._step_greedy(
                        self._params, self._cache,
                        jnp.asarray(self._tokens))
                else:
                    self._cache, toks = self._step(
                        self._params, self._cache,
                        jnp.asarray(self._tokens),
                        jnp.asarray(self._seeds),
                        jnp.asarray(self._stepidx),
                        jnp.asarray(self._temps), jnp.asarray(self._topk),
                        jnp.asarray(self._topp))
            toks = np.asarray(toks)  # (K, B); the transfer surfaces
            # device-side failures HERE, while recovery can still replay
        except Exception:  # noqa: BLE001 — donated cache consumed
            log.exception("decode step failed")
            if self._maybe_recover("decode step"):
                return True
            raise
        # ONE wall-clock read per sync batch, after the host transfer:
        # the moment every token of this chunk became user-visible. The
        # emit loop below stamps K×B tokens with it — per-token emit
        # takes zero additional clock reads (the ledger contract)
        t_step_end = self.clock()
        K = toks.shape[0]
        self.steps_total += K
        if all_greedy:
            self.greedy_steps += K
        _steps_total.inc(K, model=self.name)
        self._stepidx += K
        self._tokens = toks[-1].copy()
        if self.paged:
            self._pos_host[[i for i, _ in active]] += K
            # one span per shared step: the burst-interleave evidence
            # (chunk spans between step spans bound any decode stall)
            self.tracer.record(
                "engine.step", start=t_step0, end=t_step_end,
                attrs={"model": self.name, "rows": len(active), "k": K})
        retired: List[int] = []
        for i, slot in active:
            for t in range(K):
                tok = int(toks[t, i])
                self._emit(slot, tok, t_step_end)
                if self._finished(slot, tok, t_step_end):
                    # tokens past EOS/budget in this chunk are discarded
                    with self._lock:
                        self._active[i] = None
                    if self.paged:
                        retired.append(i)
                    # the request's decode phase is over: one span with
                    # the token count — the per-request cost record
                    self.tracer.record(
                        "engine.decode", start=slot.t_decode0,
                        end=t_step_end, parent=slot.req.ctx,
                        attrs={"model": self.name,
                               "tokens": slot.produced})
                    break
        if retired:
            # retirement disarms rows with a donating _arm call: run
            # the batch's retirements AFTER the emit loop so a device
            # failure lands with emitted/fold accounting already
            # complete — recovery replays the surviving streams instead
            # of the close protocol failing them all
            try:
                for i in retired:
                    self._retire_paged(i)
            except Exception:  # noqa: BLE001 — donated cache consumed
                log.exception("paged retirement failed")
                if not self._maybe_recover("paged retirement"):
                    raise
        _occupancy.set(self.active_count, model=self.name)
        return True

    def _admit(self, timeout: float) -> bool:
        if self.hbm_sampler is not None:
            try:
                self.hbm_sampler.sample()
            except Exception:  # noqa: BLE001 — watermarks never gate admits
                log.debug("hbm sample failed (continuing)", exc_info=True)
        if self.paged:
            return self._admit_paged(timeout)
        return self._admit_dense(timeout)

    # -- paged engine internals --------------------------------------------

    def _admit_paged(self, timeout: float) -> bool:
        """Paged admission: placing a request is page-map surgery (a
        reservation + one tiny arm program), then the prompt streams
        into the pool through the chunked-prefill scheduler — there is
        no whole-row insert and no per-prompt-bucket program. FIFO is
        strict: a request that cannot reserve pages yet holds the line
        (head-of-line wait) rather than being overtaken."""
        admitted = False
        with self._lock:
            busy = {i for i, s in enumerate(self._active)
                    if s is not None}
        busy |= set(self._prefilling)
        free = [i for i in range(self.slots) if i not in busy]
        block = not busy and not self._waiting
        for slot in free:
            if not self._waiting:
                try:
                    self._waiting.append(self._pending.get(
                        block=block and not admitted, timeout=timeout))
                except queue.Empty:
                    break
            if not self._place_paged(self._waiting[0], slot):
                break  # no pages yet: keep FIFO, retry next cycle
            self._waiting.popleft()
            admitted = True
        _queue_depth.set(self.pending_count, model=self.name)
        _occupancy.set(self.active_count, model=self.name)
        return admitted

    def _place_paged(self, req: _Request, slot: int) -> bool:
        """Reserve + map pages for a request and arm its slot; False
        when the pool cannot cover it yet (caller retries).

        Prefix sharing is trie-matched per PAGE: the longest stored
        chain of full pages maps in read-only, and when the WHOLE
        aligned prefix matched, the partial boundary page maps in
        copy-on-write. The COW split (one device page copy) runs HERE,
        before the slot is armed: the shared decode step advances and
        writes through EVERY armed row (a mid-prefill row's device
        position drifts by design), so a slot may never sit armed while
        its table points a writable logical page at KV someone else
        reads."""
        S = req.prompt.size
        pool = self._pool
        store = self._prefix_pages
        match = (store.match(req.prompt, req.prefix_len)
                 if req.prefix_len else None)
        shared = match.pages if match else []
        # the COW boundary page is NOT subtracted: its split draws a
        # fresh page from this very reservation
        n_res = pool.pages_needed(S + req.max_new) - len(shared)
        # idle prefix pages are reclaimable capacity: evict LRU leaves
        # (never a page this request is about to share) before refusing
        protect = set(shared)
        if match is not None and match.tail_page is not None:
            protect.add(match.tail_page)
        while not pool.can_reserve(n_res) and store.evict_lru(
                protect=protect):
            pass
        if not pool.can_reserve(n_res):
            return False
        pool.reserve(slot, n_res)
        if req.prefix_len:
            # count on the admission that LANDS (placement may retry
            # the same head-of-line request across cycles)
            if match.hit:
                self.prefix_hits += 1
                _prefix_hits.inc(model=self.name)
                n_shared = len(shared) + (match.tail_page is not None)
                self.prefix_pages_shared += n_shared
                _prefix_pages_shared_c.inc(n_shared, model=self.name)
            else:
                self.prefix_misses += 1
                _prefix_misses.inc(model=self.name)
        for logical, page in enumerate(shared):
            pool.map_shared(slot, logical, page)
        start = len(shared) * self.kv_page_size
        if match is not None and match.tail_page is not None:
            # map_cow FIRST: the slot's ref keeps the boundary page
            # alive even if store eviction (racing this placement for
            # pages) unpins the entry; then split immediately — the
            # split is the "first write" boundary, since arming makes
            # the row writable by the very next shared step
            logical = len(shared)
            pool.map_cow(slot, logical, match.tail_page)
            src, dst = pool.cow_split(slot, logical)
            with self._mesh_ctx():
                self._cache = self._copy_page(
                    self._cache, jnp.int32(src), jnp.int32(dst))
            self.cow_splits += 1
            _cow_splits_c.inc(model=self.name)
            start += match.tail_len
        pool.ensure(slot, S)  # prompt pages; decode pages grow lazily
        now = self._note_queue_wait(req)
        with self._mesh_ctx():
            self._cache = self._arm(
                self._cache, jnp.int32(slot), jnp.int32(start),
                jnp.asarray(pool.table_row(slot)))
        job = _PrefillJob(
            req=req, slot=slot, tokens=req.prompt, next=start,
            t_admit=now, store_prefix=req.prefix_len)
        self._prefilling[slot] = job
        self._pos_host[slot] = start
        self._slot_budget[slot] = S + req.max_new
        self._export_page_gauges()
        _prefix_bytes_g.set(store.pages_held * self._page_bytes,
                            model=self.name)
        return True

    def _prefill_tick(self) -> bool:
        """Run chunked-prefill work for this cycle.

        With co-tenant decode in flight, at most ``prefill_chunks_per_
        cycle`` chunk programs run before the next shared decode step —
        the scheduling policy that bounds any decode stall to one chunk
        during a burst admit. On an idle engine the oldest job runs to
        completion (nobody to stall, and its stream's TTFT wins), then
        decode starts while later jobs interleave."""
        if not self._prefilling:
            return False
        with self._lock:
            has_active = any(s is not None for s in self._active)
        budget = self.prefill_chunks_per_cycle if has_active else None
        for slot in list(self._prefilling):
            job = self._prefilling[slot]
            while True:
                done = self._run_chunk(job)
                if budget is not None:
                    budget -= 1
                if done:
                    del self._prefilling[slot]
                    self._finalize_paged(job)
                    break
                if budget is not None and budget <= 0:
                    return True
            if budget is None:
                # idle-engine fast path: first stream is live; decode
                # now interleaves with the remaining jobs
                return True
            if budget <= 0:
                return True
        return True

    def _run_chunk(self, job: _PrefillJob) -> bool:
        """One chunk program for one slot; True when the job's token
        stream is fully prefilled (``job.last_tok`` then holds the
        sampled next token)."""
        req = job.req
        C = self.prefill_chunk_tokens
        total = int(job.tokens.size)
        n = min(C, total - job.next)
        padded = np.zeros((1, C), np.int32)
        padded[0, :n] = job.tokens[job.next:job.next + n]
        final = job.next + n >= total
        t0 = self.clock()
        if job.chunks == 0:
            # first chunk: the record's prefill phase opens here (the
            # span below evidences each chunk; the ledger's prefill
            # interval runs from this mark to the first token)
            self.rledger.mark(req.rid, reqobs.PREFILL, t0)
        with self._mesh_ctx():
            tok, self._cache = self._chunk(
                self._params, self._cache, jnp.asarray(padded),
                jnp.int32(job.slot), jnp.int32(job.next), jnp.int32(n),
                jnp.int32(req.seed), jnp.int32(job.fold0),
                jnp.float32(req.temperature), jnp.int32(req.top_k),
                jnp.float32(req.top_p))
            if final:
                # host transfer forces completion while the failure is
                # still recoverable in this cycle
                job.last_tok = int(tok)
        job.next += n
        job.chunks += 1
        self.prefill_chunks += 1
        _prefill_chunks_c.inc(model=self.name)
        self.rledger.note_chunk(req.rid)
        self.tracer.record(
            "engine.prefill_chunk", start=t0, end=self.clock(),
            parent=req.ctx,
            attrs={"model": self.name, "slot": job.slot,
                   "tokens": int(n), "final": final})
        return final

    def _finalize_paged(self, job: _PrefillJob) -> None:
        """Prompt fully in the pool: emit the sampled token, arm the
        slot's host-side decode state, pin shareable prefix pages."""
        req, slot = job.req, job.slot
        now = self.clock()
        if job.store_prefix:
            # idempotent trie insert: already-stored chain pages are
            # only LRU-touched; a partial-chain hit pins the NEW pages
            # extending the chain, plus the COW boundary tail
            self._prefix_pages.store(req.prompt, job.store_prefix, slot)
            _prefix_bytes_g.set(
                self._prefix_pages.pages_held * self._page_bytes,
                model=self.name)
        self.tracer.record(
            "engine.admit", start=job.t_admit, end=now, parent=req.ctx,
            attrs={"model": self.name, "slot": slot,
                   "prompt_tokens": int(req.prompt.size),
                   "chunked": True, "chunks": job.chunks})
        st = _Slot(req=req, produced=job.produced0, t_decode0=now,
                   emitted=[int(t) for t in
                            job.tokens[req.prompt.size:]])
        if job.produced0 == 0:
            # not on the recovery-replay path: a replayed stream's
            # first token reached the client long ago
            self.tracer.record(
                "engine.first_token", start=req.t_submit, end=now,
                parent=req.ctx,
                attrs={"model": self.name,
                       "ttft_ms": round((now - req.t_submit) * 1000.0,
                                        3)})
        self._emit(st, job.last_tok, now)
        self._tokens[slot] = job.last_tok
        self._seeds[slot] = req.seed
        self._stepidx[slot] = job.fold0 + 1
        self._temps[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p
        self._pos_host[slot] = job.tokens.size
        if self._finished(st, job.last_tok, now):
            self._retire_paged(slot)
        else:
            with self._lock:
                self._active[slot] = st

    def _ensure_pages(self, slots) -> None:
        """Map pages covering the next K decode writes for each active
        slot (drawing down its admission reservation) and re-arm rows
        whose tables changed — page growth tracks LIVE tokens."""
        K = self.steps_per_sync
        Smax = self.config.max_seq_len
        for i in slots:
            need = min(int(self._pos_host[i]) + K,
                       int(self._slot_budget[i]), Smax)
            if self._pool.ensure(i, need):
                # page growth stalls THIS stream's decode: the arm call
                # is a device round-trip the step waits behind. Clock
                # reads happen only on growth (every ~page_size/K
                # steps), never on the per-token emit path
                t0 = self.clock()
                with self._mesh_ctx():
                    self._cache = self._arm(
                        self._cache, jnp.int32(i),
                        jnp.int32(self._pos_host[i]),
                        jnp.asarray(self._pool.table_row(i)))
                self._export_page_gauges()
                with self._lock:
                    st = self._active[i]
                if st is not None:
                    self.rledger.stall(st.req.rid, reqobs.KV_FAULT,
                                       t0, self.clock())

    def _export_page_gauges(self) -> None:
        """One write site for the pool-occupancy gauges, so in_use /
        free / evictable can never drift apart between call sites."""
        _kv_pages_g.set(self._pool.pages_in_use, model=self.name)
        _kv_pages_free_g.set(self._pool.pages_free, model=self.name)
        _kv_pages_evictable_g.set(self._prefix_pages.pages_evictable,
                                  model=self.name)

    def _retire_paged(self, slot: int) -> None:
        """Free the slot's pages (shared prefix pages drop one ref) and
        disarm its device row so post-retirement garbage decode writes
        scatter-drop instead of landing in reallocated pages."""
        self._pool.release_slot(slot)
        with self._mesh_ctx():
            self._cache = self._arm(
                self._cache, jnp.int32(slot),
                jnp.int32(self.config.max_seq_len),
                jnp.asarray(self._pool.table_row(slot)))
        self._pos_host[slot] = 0
        self._slot_budget[slot] = 0
        self._export_page_gauges()

    # -- cache recovery ----------------------------------------------------

    def _maybe_recover(self, where: str) -> bool:
        """A donating device call failed: the engine cache is consumed.
        While the recovery budget lasts, rebuild the cache/pool from
        scratch and REPLAY every in-flight stream (prompt + emitted
        tokens re-prefill; sampling resumes at the preserved fold
        index) — the engine keeps serving instead of failing every
        subsequent call against a corpse."""
        if self._recoveries_left <= 0:
            return False
        self._recoveries_left -= 1
        try:
            self._rebuild_and_replay()
        except Exception:  # noqa: BLE001 — recovery itself failed
            log.exception("cache recovery after %s failure failed; "
                          "closing engine", where)
            return False
        self.recoveries += 1
        log.warning("recovered engine cache after %s failure "
                    "(%d recover(s) left)", where, self._recoveries_left)
        return True

    def _rebuild_and_replay(self) -> None:
        with self._lock:
            live = [(i, s) for i, s in enumerate(self._active)
                    if s is not None]
            self._active = [None] * self.slots
        self._cache = self._fresh_cache()
        replays: List[tuple] = []
        for i, st in live:
            replays.append((i, st.req,
                            np.concatenate([st.req.prompt,
                                            np.asarray(st.emitted,
                                                       np.int32)]),
                            st.produced, int(self._stepidx[i])))
        if self.paged:
            # the old pool maps a consumed cache; prefix pages died with
            # it. Interrupted prefill jobs restart from token 0.
            jobs = list(self._prefilling.values())
            self._prefilling = collections.OrderedDict()
            self._pool = PagePool(self.kv_pages, self.kv_page_size,
                                  self.slots, self._n_logical)
            self._prefix_pages = PrefixPageStore(
                self._pool, self._prefix_pages.budget_pages)
            self._pos_host[:] = 0
            self._slot_budget[:] = 0
            # fresh pool: in_use is 0 and the rebuilt store holds
            # nothing yet
            self._export_page_gauges()
            # replays reserve WITHOUT prefix sharing (the store died
            # with the old pool), so a load that only fit shared may
            # not fully fit the fresh pool: fail just those streams
            # retryably instead of giving up the whole recovery
            for args in (replays
                         + [(j.slot, j.req, j.tokens, j.produced0,
                             j.fold0) for j in jobs]):
                i, req = args[0], args[1]
                try:
                    self._replay_paged(*args)
                except OutOfPages:
                    log.warning(
                        "slot %d replay does not fit the rebuilt pool "
                        "(prefix sharing lost); failing it retryably", i)
                    req.error = EngineClosed(
                        "engine cache recovered; stream evicted — retry")
                    req.out.put(_END)
                    self.rledger.finish(req.rid, self.clock())
        else:
            for i, req, tokens, produced, fold in replays:
                self._replay_dense(i, req, tokens, produced, fold)

    def _replay_paged(self, slot: int, req: _Request,
                      tokens: np.ndarray, produced: int,
                      fold: int) -> None:
        pool = self._pool
        budget = req.prompt.size + req.max_new
        pool.reserve(slot, pool.pages_needed(budget))
        pool.ensure(slot, int(tokens.size))
        with self._mesh_ctx():
            self._cache = self._arm(
                self._cache, jnp.int32(slot), jnp.int32(0),
                jnp.asarray(pool.table_row(slot)))
        self._prefilling[slot] = _PrefillJob(
            req=req, slot=slot, tokens=tokens, next=0,
            t_admit=self.clock(), fold0=fold, produced0=produced)
        self._pos_host[slot] = 0
        self._slot_budget[slot] = budget
        self._export_page_gauges()

    def _replay_dense(self, slot: int, req: _Request,
                      tokens: np.ndarray, produced: int,
                      fold: int) -> None:
        """Dense replay: one bucketed prefill of (prompt + emitted)
        re-fills the row, sampling the stream's NEXT token at the
        preserved fold index."""
        L = int(tokens.size)
        bucket = pow2_bucket(L, self.config.max_seq_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :L] = tokens
        with self._mesh_ctx():
            tok, row_cache = self._prefill(
                self._params, jnp.asarray(padded),
                jnp.asarray([L], jnp.int32),
                jnp.float32(req.temperature), jnp.int32(req.top_k),
                jnp.float32(req.top_p), jnp.int32(req.seed),
                jnp.int32(fold))
            self._cache = self._insert(self._cache, row_cache,
                                       jnp.int32(slot))
        t_now = self.clock()
        st = _Slot(req=req, produced=produced, t_decode0=t_now,
                   emitted=[int(t) for t in tokens[req.prompt.size:]])
        self._emit(st, int(tok), t_now)
        self._tokens[slot] = int(tok)
        self._seeds[slot] = req.seed
        self._stepidx[slot] = fold + 1
        self._temps[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p
        if not self._finished(st, int(tok), t_now):
            with self._lock:
                self._active[slot] = st

    def _admit_dense(self, timeout: float) -> bool:
        """Move pending requests into free slots.

        A BURST of pending requests sharing a prompt bucket admits
        through ONE compiled batch prefill (``_admit_batch``) instead of
        sequential row prefills; singletons and prefix-cached requests
        keep the row path (its compiled programs already exist)."""
        admitted = False
        with self._lock:
            free = [i for i, s in enumerate(self._active) if s is None]
        block = not any(s is not None for s in self._active)
        batchable: List[tuple] = []  # (req, slot) — no prefix reuse
        for slot in free:
            try:
                req = self._pending.get(block=block and not admitted,
                                        timeout=timeout)
            except queue.Empty:
                break
            admitted = True
            if req.prefix_len or self.admit_batch_max <= 1:
                self._admit_row_safe(req, slot)
            else:
                batchable.append((req, slot))
        if batchable:
            groups: dict = {}
            for req, slot in batchable:
                b = pow2_bucket(req.prompt.size, self.config.max_seq_len)
                groups.setdefault(b, []).append((req, slot))
            for bucket, members in groups.items():
                # chunk to the batch cap (bounds the transient HBM of
                # the extra full-context rows the batch prefill holds)
                for i in range(0, len(members), self.admit_batch_max):
                    chunk = members[i:i + self.admit_batch_max]
                    if len(chunk) == 1:
                        self._admit_row_safe(*chunk[0])
                        continue
                    try:
                        self._admit_batch(bucket, chunk)
                    except _CacheInvalidated:
                        raise  # run_once/_loop closes the engine
                    except Exception:  # noqa: BLE001
                        # the burst shares one device call; don't let it
                        # share the failure — retry each member through
                        # the row path, which fails (or succeeds)
                        # per-request (the engine cache is intact: the
                        # prefill materialized before any donation)
                        log.exception(
                            "batched admission failed; retrying %d "
                            "request(s) individually", len(chunk))
                        for req, slot in chunk:
                            self._admit_row_safe(req, slot)
        _queue_depth.set(self._pending.qsize(), model=self.name)
        _occupancy.set(self.active_count, model=self.name)
        return admitted

    def _admit_row_safe(self, req: _Request, slot: int) -> None:
        """Row-path admission that surfaces failure to THIS caller only."""
        try:
            self._admit_one(req, slot)
        except Exception as e:  # noqa: BLE001 — surface to the caller
            req.error = e
            req.out.put(_END)
            self.rledger.finish(req.rid, self.clock())

    def _admit_batch(self, bucket: int, members: List[tuple]) -> None:
        """One shared prefill for same-bucket requests, then per-row
        inserts into their slots. Rows pad to a power-of-two batch
        (bounded compiled-program inventory: batch buckets × prompt
        buckets); pad rows are length-1 junk nothing reads or inserts.
        Token-identical to the row path: same ragged per-row lengths,
        same ``fold_in(key(seed), 0)`` sampling."""
        k = len(members)
        t0 = self.clock()
        for req, _slot in members:
            self._note_queue_wait(req)
        bb = pow2_bucket(k, min(self.slots, self.admit_batch_max))
        prompts = np.zeros((bb, bucket), np.int32)
        lens = np.ones((bb,), np.int32)
        temps = np.zeros((bb,), np.float32)
        tks = np.zeros((bb,), np.int32)
        tps = np.ones((bb,), np.float32)
        seeds = np.zeros((bb,), np.int32)
        slot_ids = np.zeros((bb,), np.int32)
        valid = np.zeros((bb,), bool)
        for i, (req, slot) in enumerate(members):
            S = req.prompt.size
            prompts[i, :S] = req.prompt
            lens[i] = S
            temps[i] = req.temperature
            tks[i] = req.top_k
            tps[i] = req.top_p
            seeds[i] = req.seed
            slot_ids[i] = slot
            valid[i] = True
        with self._mesh_ctx():
            # annotate the shared device call on the profiler timeline;
            # span-wise it is recorded below as a per-member child of
            # each admit span (a context-managed span here would be an
            # orphan root — the engine thread has no active span — and
            # would crowd the dashboard's trace list)
            ann = (self.tracer.annotator("engine.prefill")
                   if self.tracer.annotator is not None
                   else contextlib.nullcontext())
            p0 = self.clock()
            for req, _slot in members:
                # the shared device call opens every member's prefill
                # phase on the same already-read timestamp
                self.rledger.mark(req.rid, reqobs.PREFILL, p0)
            with ann:
                toks, bcache = self._prefill_batch(
                    self._params, jnp.asarray(prompts),
                    jnp.asarray(lens),
                    jnp.asarray(temps), jnp.asarray(tks),
                    jnp.asarray(tps), jnp.asarray(seeds))
            # force completion (host transfer — block_until_ready is not
            # enough on every transport) BEFORE the donating inserts: a
            # device-side prefill failure must surface while self._cache
            # is still intact, so _admit's row-path fallback retries
            # against a live engine instead of a consumed cache
            toks = np.asarray(toks)  # tpulint: disable=TPU017 — deliberate barrier, see above
            p1 = self.clock()
            try:
                self._cache = self._insert_rows(
                    self._cache, bcache, jnp.asarray(slot_ids),
                    jnp.asarray(valid))
            except Exception as e:  # noqa: BLE001 — donation consumed
                # the cache; fail the chunk retryably and escalate so
                # the loop closes the engine (no row-path retry can
                # succeed against a consumed cache)
                t_fail = self.clock()
                for req, _ in members:
                    req.error = EngineClosed(
                        "engine cache invalidated during admission")
                    req.out.put(_END)
                    self.rledger.finish(req.rid, t_fail)
                raise _CacheInvalidated(str(e)) from e
        self.batch_prefills += 1
        t1 = self.clock()
        for i, (req, slot) in enumerate(members):
            adm = self.tracer.record(
                "engine.admit", start=t0, end=t1, parent=req.ctx,
                attrs={"model": self.name, "slot": slot,
                       "prompt_tokens": int(lens[i]),
                       "batched": True, "batch": k})
            # the shared prefill's time range, nested in THIS member's
            # trace (same shape as the row path's admit→prefill)
            self.tracer.record(
                "engine.prefill", start=p0, end=p1, parent=adm,
                attrs={"prompt_tokens": int(lens[i]), "bucket": bucket,
                       "batched": True, "batch": k})
            self._finalize_admission(req, slot, int(toks[i]), t1)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception:  # noqa: BLE001
                log.exception("decode engine step failed; closing engine")
                # the step's donated cache is invalidated — this engine
                # can never step again. Close it: in-flight AND pending
                # requests fail with the retryable EngineClosed (503 /
                # UNAVAILABLE), later submits raise the same, and the
                # repository evicts closed engines so the next request
                # builds a fresh one instead of landing here forever.
                with self._lock:
                    self._stop.set()
                    failed = [s.req for s in self._active
                              if s is not None]
                    self._active = [None] * self.slots
                    if self.paged:
                        # mid-chunked-prefill and head-of-line requests
                        # must fail too — a stream nobody ends hangs its
                        # client forever in result()
                        failed.extend(j.req
                                      for j in self._prefilling.values())
                        self._prefilling.clear()
                        failed.extend(self._waiting)
                        self._waiting.clear()
                    while True:
                        try:
                            failed.append(self._pending.get_nowait())
                        except queue.Empty:
                            break
                t_fail = self.clock()
                for req in failed:
                    req.error = EngineClosed("decode engine step failed")
                    req.out.put(_END)
                    self.rledger.finish(req.rid, t_fail)
                return
