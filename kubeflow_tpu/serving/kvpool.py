"""Host-side page allocator for the paged decode KV cache.

The device side is a pool of ``pages_total`` HBM blocks of
``page_size`` tokens (``models/transformer.py:_paged_decode_attend``);
everything about WHO owns a page lives here, on the host, as plain
integers — slot admission/retirement is page-map surgery on a table
the engine ships to the device as one tiny int32 array, never a
whole-row KV copy.

Ownership model:

- every physical page has a refcount; 0 = free (on the free list);
- a slot's page-table row maps logical pages (position // page_size)
  to physical ids, ``SENTINEL`` (== pages_total) for unmapped entries
  — the model drops writes through the sentinel;
- prefix sharing is refcounting: a stored prompt prefix pins its pages
  (one ref for the store), and every slot serving that prefix adds a
  ref to each shared page. Pages are writable only while exactly one
  slot maps them ABOVE its own start position; shared prefix pages sit
  below every sharer's start, so they are read-only by construction;
- admission RESERVES the slot's worst case up front
  (``ceil((prompt + max_new)/page_size)`` minus shared pages) and
  allocation draws the reservation down as the sequence actually grows
  — ``pages_in_use`` tracks live tokens, while the reservation
  guarantees a slot admitted can always finish (no mid-decode
  out-of-pages deadlock to preempt around).

Deterministic by design: the free list hands out ascending ids from a
fixed initial order, so tests can assert exact page maps.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np


class OutOfPages(RuntimeError):
    """An allocation exceeded the slot's reservation or the pool —
    an engine accounting bug, never a load condition (admission gates
    on :meth:`PagePool.can_reserve`)."""


@dataclasses.dataclass
class _SlotState:
    reserved: int = 0        # pages promised but not yet allocated
    mapped: List[int] = dataclasses.field(default_factory=list)


class PagePool:
    """Refcounted page allocator + per-slot page tables."""

    def __init__(self, pages_total: int, page_size: int, slots: int,
                 pages_per_slot: int) -> None:
        if pages_total < 1:
            raise ValueError("pages_total must be >= 1")
        self.pages_total = int(pages_total)
        self.page_size = int(page_size)
        self.slots = int(slots)
        self.pages_per_slot = int(pages_per_slot)
        self.sentinel = self.pages_total
        # pop() hands out ascending ids: 0, 1, 2, ...
        self._free: List[int] = list(range(self.pages_total - 1, -1, -1))
        self.ref = np.zeros((self.pages_total,), np.int32)
        self.tables = np.full((self.slots, self.pages_per_slot),
                              self.sentinel, np.int32)
        self._slot = [_SlotState() for _ in range(self.slots)]
        self.reserved_total = 0

    # -- capacity ----------------------------------------------------------

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.pages_total - len(self._free)

    def pages_needed(self, tokens: int) -> int:
        return -(-max(0, int(tokens)) // self.page_size)

    def can_reserve(self, n: int) -> bool:
        """True when ``n`` more pages can be promised without risking a
        mid-decode allocation failure for any already-admitted slot."""
        return len(self._free) - self.reserved_total >= n

    # -- slot lifecycle ----------------------------------------------------

    def reserve(self, slot: int, n: int) -> None:
        if not self.can_reserve(n):
            raise OutOfPages(
                f"reserve({n}) with {len(self._free)} free / "
                f"{self.reserved_total} already promised")
        self._slot[slot].reserved += n
        self.reserved_total += n

    def map_shared(self, slot: int, logical: int, page_id: int) -> None:
        """Point a slot's logical page at an existing (prefix) page."""
        assert self.tables[slot, logical] == self.sentinel
        self.ref[page_id] += 1
        self.tables[slot, logical] = page_id
        self._slot[slot].mapped.append(page_id)

    def alloc(self, slot: int, logical: int) -> int:
        """Allocate a fresh writable page for a slot's logical page,
        drawing down its reservation."""
        st = self._slot[slot]
        if st.reserved <= 0:
            raise OutOfPages(f"slot {slot} exhausted its reservation")
        if not self._free:
            raise OutOfPages("free list empty despite reservation")
        page = self._free.pop()
        st.reserved -= 1
        self.reserved_total -= 1
        self.ref[page] = 1
        self.tables[slot, logical] = page
        st.mapped.append(page)
        return page

    def ensure(self, slot: int, tokens: int) -> bool:
        """Map every logical page covering positions [0, tokens);
        returns True when the table row changed (the engine must re-arm
        the device copy)."""
        changed = False
        for logical in range(self.pages_needed(tokens)):
            if self.tables[slot, logical] == self.sentinel:
                self.alloc(slot, logical)
                changed = True
        return changed

    def release_slot(self, slot: int) -> None:
        """Retire a slot: unref every mapped page (pages reaching 0 go
        back on the free list) and return its unused reservation."""
        st = self._slot[slot]
        for page in st.mapped:
            self._unref(page)
        st.mapped = []
        self.reserved_total -= st.reserved
        st.reserved = 0
        self.tables[slot, :] = self.sentinel

    def table_row(self, slot: int) -> np.ndarray:
        return self.tables[slot].copy()

    # -- prefix sharing ----------------------------------------------------

    def pin(self, slot: int, n_logical: int) -> List[int]:
        """Take a store-side reference on a slot's first ``n_logical``
        pages (they must all be mapped) — the prefix store's claim,
        which outlives the slot."""
        pages = [int(p) for p in self.tables[slot, :n_logical]]
        assert all(p != self.sentinel for p in pages)
        for p in pages:
            self.ref[p] += 1
        return pages

    def unpin(self, pages: List[int]) -> None:
        for p in pages:
            self._unref(p)

    def _unref(self, page: int) -> None:
        assert self.ref[page] > 0, f"double free of page {page}"
        self.ref[page] -= 1
        if self.ref[page] == 0:
            self._free.append(page)

    def check_idle(self) -> None:
        """Assert the pool is fully reclaimed (smoke-gate invariant)."""
        if self.pages_in_use or self.reserved_total:
            raise AssertionError(
                f"pool not idle: {self.pages_in_use} pages in use, "
                f"{self.reserved_total} reserved; refs "
                f"{np.flatnonzero(self.ref).tolist()}")


class PrefixPageStore:
    """LRU store of shared prompt-prefix pages, budgeted in PAGES.

    Only FULL pages are shared (``aligned_len = prefix_len // page_size
    * page_size`` tokens): the page straddling the prefix/suffix
    boundary also holds per-request tokens and can never be shared, so
    a hit re-prefills at most ``page_size - 1`` boundary tokens instead
    of copying a row. Entries hold store-side refs on their pages
    (``PagePool.pin``); eviction unpins, and pages free once the last
    sharing slot retires.
    """

    def __init__(self, pool: PagePool, budget_pages: int) -> None:
        self.pool = pool
        self.budget_pages = max(0, int(budget_pages))
        self._entries: "Dict[Tuple[int, bytes], List[int]]" = {}
        self._order: List[Tuple[int, bytes]] = []

    @property
    def pages_held(self) -> int:
        return sum(len(v) for v in self._entries.values())

    @property
    def pages_evictable(self) -> int:
        """Store-held pages no live slot shares (refcount 1 = only the
        store's pin): reclaimable cache, not load — the autoscaler must
        not hold replicas for them.

        Read from the autoscaler's snapshot() poll thread while the
        engine thread inserts/evicts entries, so take a GIL-atomic copy
        of the values first (``list()`` on the view runs in C with no
        interleaved bytecode; the page lists themselves are never
        mutated in place) — a bare generator over ``_entries`` can die
        with "dictionary changed size during iteration"."""
        return sum(1 for pages in list(self._entries.values())
                   for p in pages if self.pool.ref[p] == 1)

    def aligned_len(self, prefix_len: int) -> int:
        return (int(prefix_len) // self.pool.page_size
                ) * self.pool.page_size

    @staticmethod
    def key(tokens: np.ndarray) -> Tuple[int, bytes]:
        return (int(tokens.size), tokens.tobytes())

    def lookup(self, tokens: np.ndarray) -> Optional[List[int]]:
        """Page ids for an aligned prefix, or None (LRU-touches hits).
        Hit/miss accounting is the caller's: placement can retry the
        same request several cycles while pages free up, and only the
        admission that LANDS should count."""
        return self.get(self.key(tokens))

    def get(self, k: Tuple[int, bytes]) -> Optional[List[int]]:
        """:meth:`lookup` by precomputed key — placement retries the
        same head-of-line request across cycles and already holds the
        key for eviction exemption; serializing the prefix once per
        attempt instead of twice keeps the scheduler loop cheap."""
        pages = self._entries.get(k)
        if pages is None:
            return None
        self._order.remove(k)
        self._order.append(k)
        return pages

    def store(self, tokens: np.ndarray, slot: int) -> None:
        """Pin a slot's pages covering ``tokens`` (page-aligned) as a
        shared prefix entry, evicting LRU entries to stay in budget."""
        n_logical = tokens.size // self.pool.page_size
        if n_logical == 0 or n_logical > self.budget_pages:
            return
        k = self.key(tokens)
        if k in self._entries:
            return
        while self.pages_held + n_logical > self.budget_pages:
            self._evict_one()
        self._entries[k] = self.pool.pin(slot, n_logical)
        self._order.append(k)

    def _evict_one(self) -> None:
        k = self._order.pop(0)
        self.pool.unpin(self._entries.pop(k))

    def evict_lru(self, except_key: Optional[Tuple[int, bytes]] = None
                  ) -> bool:
        """Evict the least-recently-used entry other than
        ``except_key`` (the entry an in-flight admission is about to
        share — evicting it would free pages out from under the slot
        being placed). Returns False when nothing is evictable."""
        for k in self._order:
            if k != except_key:
                self._order.remove(k)
                self.pool.unpin(self._entries.pop(k))
                return True
        return False

    def clear(self) -> None:
        while self._order:
            self._evict_one()

    def __len__(self) -> int:
        return len(self._entries)
