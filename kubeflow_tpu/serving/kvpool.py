"""Host-side page allocator for the paged decode KV cache.

The device side is a pool of ``pages_total`` HBM blocks of
``page_size`` tokens (``models/transformer.py:_paged_decode_attend``);
everything about WHO owns a page lives here, on the host, as plain
integers — slot admission/retirement is page-map surgery on a table
the engine ships to the device as one tiny int32 array, never a
whole-row KV copy.

Ownership model:

- every physical page has a refcount; 0 = free (on the free list);
- a slot's page-table row maps logical pages (position // page_size)
  to physical ids, ``SENTINEL`` (== pages_total) for unmapped entries
  — the model drops writes through the sentinel;
- prefix sharing is refcounting: a stored prompt prefix pins its pages
  (one ref for the store), and every slot serving that prefix adds a
  ref to each shared page. **Exactly one slot may ever write a page**
  (its allocator-recorded *writer*): pages a slot allocates are its
  own; pages mapped via :meth:`map_shared` or :meth:`map_cow` are
  read-only for the mapper. Shared full prefix pages sit below every
  sharer's start position, so the read-only rule costs nothing; a
  shared PARTIAL boundary page (copy-on-write, :meth:`map_cow`) must
  be :meth:`cow_split` into a fresh writable copy before the sharer's
  first write can land in it;
- admission RESERVES the slot's worst case up front
  (``ceil((prompt + max_new)/page_size)`` minus fully-shared pages —
  a COW boundary page is NOT subtracted, its split draws a fresh page)
  and allocation draws the reservation down as the sequence actually
  grows — ``pages_in_use`` tracks live tokens, while the reservation
  guarantees a slot admitted can always finish (no mid-decode
  out-of-pages deadlock to preempt around).

Deterministic by design: the free list hands out ascending ids from a
fixed initial order, so tests can assert exact page maps.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

import numpy as np


class OutOfPages(RuntimeError):
    """An allocation exceeded the slot's reservation or the pool —
    an engine accounting bug, never a load condition (admission gates
    on :meth:`PagePool.can_reserve`)."""


@dataclasses.dataclass
class _SlotState:
    reserved: int = 0        # pages promised but not yet allocated
    mapped: List[int] = dataclasses.field(default_factory=list)
    owned: List[int] = dataclasses.field(default_factory=list)
    # logical page -> shared physical page the slot maps read-only and
    # must cow_split before writing
    cow: Dict[int, int] = dataclasses.field(default_factory=dict)


class PagePool:
    """Refcounted page allocator + per-slot page tables."""

    def __init__(self, pages_total: int, page_size: int, slots: int,
                 pages_per_slot: int) -> None:
        if pages_total < 1:
            raise ValueError("pages_total must be >= 1")
        self.pages_total = int(pages_total)
        self.page_size = int(page_size)
        self.slots = int(slots)
        self.pages_per_slot = int(pages_per_slot)
        self.sentinel = self.pages_total
        # pop() hands out ascending ids: 0, 1, 2, ...
        self._free: List[int] = list(range(self.pages_total - 1, -1, -1))
        self.ref = np.zeros((self.pages_total,), np.int32)
        # store-side pins (PrefixPageStore), counted separately so the
        # invariant ref == table references + pins is checkable
        self.pins = np.zeros((self.pages_total,), np.int32)
        self.tables = np.full((self.slots, self.pages_per_slot),
                              self.sentinel, np.int32)
        self._slot = [_SlotState() for _ in range(self.slots)]
        # page -> the ONE slot allowed to write it (docstring invariant)
        self._writer: Dict[int, int] = {}
        self.reserved_total = 0
        self.cow_splits = 0

    # -- capacity ----------------------------------------------------------

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.pages_total - len(self._free)

    def pages_needed(self, tokens: int) -> int:
        return -(-max(0, int(tokens)) // self.page_size)

    def can_reserve(self, n: int) -> bool:
        """True when ``n`` more pages can be promised without risking a
        mid-decode allocation failure for any already-admitted slot."""
        return len(self._free) - self.reserved_total >= n

    # -- slot lifecycle ----------------------------------------------------

    def reserve(self, slot: int, n: int) -> None:
        if not self.can_reserve(n):
            raise OutOfPages(
                f"reserve({n}) with {len(self._free)} free / "
                f"{self.reserved_total} already promised")
        self._slot[slot].reserved += n
        self.reserved_total += n

    def map_shared(self, slot: int, logical: int, page_id: int) -> None:
        """Point a slot's logical page at an existing (prefix) page —
        read-only for this slot (it is below the slot's start)."""
        assert self.tables[slot, logical] == self.sentinel
        self.ref[page_id] += 1
        self.tables[slot, logical] = page_id
        self._slot[slot].mapped.append(page_id)

    def map_cow(self, slot: int, logical: int, page_id: int) -> None:
        """Share a PARTIAL boundary page copy-on-write: the slot maps it
        read-only (taking a ref that outlives any store eviction racing
        the placement) and must :meth:`cow_split` before its first
        write into the page can land."""
        self.map_shared(slot, logical, page_id)
        self._slot[slot].cow[logical] = page_id

    def cow_split(self, slot: int, logical: int) -> Tuple[int, int]:
        """Split a COW mapping: allocate a fresh writable page for the
        slot (drawing its reservation down) and drop the read-only ref
        on the shared one. Returns ``(src, dst)`` — the caller must
        copy the page device-side BEFORE any write lands in ``dst``
        (the split itself moves no KV bytes)."""
        st = self._slot[slot]
        src = st.cow.pop(logical)
        assert self.tables[slot, logical] == src, "cow map out of sync"
        self.tables[slot, logical] = self.sentinel
        dst = self.alloc(slot, logical)
        st.mapped.remove(src)
        self._unref(src)
        self.cow_splits += 1
        return src, dst

    def alloc(self, slot: int, logical: int) -> int:
        """Allocate a fresh writable page for a slot's logical page,
        drawing down its reservation."""
        st = self._slot[slot]
        if st.reserved <= 0:
            raise OutOfPages(f"slot {slot} exhausted its reservation")
        if not self._free:
            raise OutOfPages("free list empty despite reservation")
        page = self._free.pop()
        assert page not in self._writer, (
            f"free page {page} still has writer {self._writer[page]}")
        st.reserved -= 1
        self.reserved_total -= 1
        self.ref[page] = 1
        self.tables[slot, logical] = page
        st.mapped.append(page)
        st.owned.append(page)
        self._writer[page] = slot
        return page

    def ensure(self, slot: int, tokens: int) -> bool:
        """Map every logical page covering positions [0, tokens);
        returns True when the table row changed (the engine must re-arm
        the device copy)."""
        changed = False
        for logical in range(self.pages_needed(tokens)):
            if self.tables[slot, logical] == self.sentinel:
                self.alloc(slot, logical)
                changed = True
        return changed

    def release_slot(self, slot: int) -> None:
        """Retire a slot: unref every mapped page (pages reaching 0 go
        back on the free list) and return its unused reservation."""
        st = self._slot[slot]
        for page in st.owned:
            # the page may outlive the slot (store pin / other sharers)
            # but nobody writes it anymore
            self._writer.pop(page, None)
        for page in st.mapped:
            self._unref(page)
        st.mapped = []
        st.owned = []
        st.cow.clear()
        self.reserved_total -= st.reserved
        st.reserved = 0
        self.tables[slot, :] = self.sentinel

    def table_row(self, slot: int) -> np.ndarray:
        return self.tables[slot].copy()

    # -- prefix sharing ----------------------------------------------------

    def pin_one(self, slot: int, logical: int) -> int:
        """Take a store-side reference on ONE of a slot's mapped pages
        — the prefix store's claim, which outlives the slot."""
        page = int(self.tables[slot, logical])
        assert page != self.sentinel
        self.ref[page] += 1
        self.pins[page] += 1
        return page

    def unpin(self, pages: List[int]) -> None:
        for p in pages:
            assert self.pins[p] > 0, f"unpin of never-pinned page {p}"
            self.pins[p] -= 1
            self._unref(p)

    def _unref(self, page: int) -> None:
        assert self.ref[page] > 0, f"double free of page {page}"
        self.ref[page] -= 1
        if self.ref[page] == 0:
            self._writer.pop(page, None)
            self._free.append(page)

    def writer_of(self, page: int) -> Optional[int]:
        """The one slot allowed to write ``page`` (None = read-only
        everywhere: freed, store-only, or every mapper is a sharer)."""
        return self._writer.get(page)

    def check_invariants(self) -> None:
        """Assert the full ownership model (property-test hook):

        - every non-sentinel table entry references a live page;
        - ``ref`` == table references + store pins, per page;
        - free-list pages have ref 0 and no writer;
        - at most ONE slot may write any page, and that slot actually
          maps it — every other mapper is read-only (their mapping came
          from map_shared/map_cow, i.e. is not in their ``owned``).
        """
        table_refs = np.zeros_like(self.ref)
        for s in range(self.slots):
            for page in self.tables[s]:
                if page != self.sentinel:
                    assert self.ref[page] > 0, (
                        f"slot {s} maps dead page {page}")
                    table_refs[page] += 1
        if not (self.ref == table_refs + self.pins).all():
            bad = np.flatnonzero(self.ref != table_refs + self.pins)
            raise AssertionError(
                f"refcount drift on pages {bad.tolist()}: ref "
                f"{self.ref[bad].tolist()} != table {table_refs[bad].tolist()}"
                f" + pins {self.pins[bad].tolist()}")
        for page in self._free:
            assert self.ref[page] == 0 and page not in self._writer
        owners: Dict[int, Set[int]] = {}
        for s, st in enumerate(self._slot):
            for page in st.owned:
                owners.setdefault(page, set()).add(s)
        for page, slots in owners.items():
            assert len(slots) == 1, (
                f"page {page} writable by slots {sorted(slots)}")
            (s,) = slots
            assert self._writer.get(page) == s
            assert page in self.tables[s], (
                f"writer slot {s} no longer maps page {page}")
        for page, s in self._writer.items():
            assert page in self._slot[s].owned

    def check_idle(self) -> None:
        """Assert the pool is fully reclaimed (smoke-gate invariant)."""
        if self.pages_in_use or self.reserved_total:
            raise AssertionError(
                f"pool not idle: {self.pages_in_use} pages in use, "
                f"{self.reserved_total} reserved; refs "
                f"{np.flatnonzero(self.ref).tolist()}")
        assert not self._writer and not self.pins.any()


@dataclasses.dataclass
class PrefixMatch:
    """A prefix-trie lookup result: the longest stored chain of full
    pages matching the request's page-aligned prefix, plus (when the
    WHOLE aligned prefix matched) an optional copy-on-write candidate
    for the partial boundary page."""

    pages: List[int]                 # full pages, logical order
    tail_page: Optional[int] = None  # boundary page to map COW
    tail_len: int = 0                # boundary tokens it carries

    @property
    def hit(self) -> bool:
        return bool(self.pages) or self.tail_page is not None


class _TrieNode:
    __slots__ = ("page", "children", "tails", "parent", "key", "tick")

    def __init__(self, page: Optional[int], parent: "Optional[_TrieNode]",
                 key: bytes, tick: int) -> None:
        self.page = page             # None only for the root
        self.children: Dict[bytes, _TrieNode] = {}
        self.tails: Dict[bytes, _Tail] = {}
        self.parent = parent
        self.key = key
        self.tick = tick


class _Tail:
    __slots__ = ("page", "node", "key", "tick")

    def __init__(self, page: int, node: _TrieNode, key: bytes,
                 tick: int) -> None:
        self.page = page
        self.node = node
        self.key = key
        self.tick = tick


class PrefixPageStore:
    """Page-granular prefix **trie**, budgeted in PAGES.

    Each node is ONE full page of prompt tokens, keyed by its token
    content and chained under its predecessor page — the per-page
    content-hash chain (python's bytes hashing; keys compare exact, so
    a hash collision can never alias two different pages). A lookup
    walks the request's prefix page by page and shares the LONGEST
    stored chain: any page-aligned common prefix hits, not just exact
    full-prefix matches (the pre-trie store keyed on the entire aligned
    prefix, so two prompts sharing their first page but not their
    second shared nothing).

    Boundary pages: the page straddling the prefix/suffix boundary
    holds ``prefix_len % page_size`` shareable tokens plus per-request
    suffix garbage. It hangs off the last full-page node as a *tail*
    keyed by the boundary tokens, and is shared **copy-on-write**
    (`PagePool.map_cow`): the sharer maps it read-only, and the engine
    splits it into a fresh writable copy before the sharer's first
    write — one device-side page copy instead of re-prefilling up to
    ``page_size − 1`` tokens through every model layer.

    Entries hold store-side refs on their pages (``PagePool.pin_one``);
    eviction (leaf-first LRU — an interior page is only evictable once
    nothing chains below it) unpins, and pages free once the last
    sharing slot retires.
    """

    def __init__(self, pool: PagePool, budget_pages: int) -> None:
        self.pool = pool
        self.budget_pages = max(0, int(budget_pages))
        self._root = _TrieNode(None, None, b"", 0)
        self._tick = 0
        # flat view of held page ids for cross-thread reads
        # (pages_evictable runs on the autoscaler's snapshot() poll
        # thread; ``list()`` of a list is a GIL-atomic copy)
        self._held: List[int] = []

    @property
    def pages_held(self) -> int:
        return len(self._held)

    @property
    def pages_evictable(self) -> int:
        """Store-held pages no live slot shares (refcount 1 = only the
        store's pin): reclaimable cache, not load — the autoscaler must
        not hold replicas for them. One vectorized probe over the held
        ids: this also runs from the engine's per-admit/retire gauge
        export now, not just the autoscaler's snapshot() poll, so a
        Python-loop scan of a thousand-page trie would tax the decode
        host thread."""
        held = list(self._held)  # GIL-atomic copy (cross-thread read)
        if not held:
            return 0
        return int((self.pool.ref[np.asarray(held)] == 1).sum())

    def aligned_len(self, prefix_len: int) -> int:
        return (int(prefix_len) // self.pool.page_size
                ) * self.pool.page_size

    # -- lookup ------------------------------------------------------------

    def match(self, tokens: np.ndarray, prefix_len: int) -> PrefixMatch:
        """Longest stored page chain for ``tokens[:prefix_len]``
        (LRU-touches the path). Hit/miss accounting is the caller's:
        placement can retry the same request several cycles while pages
        free up, and only the admission that LANDS should count."""
        ps = self.pool.page_size
        prefix_len = min(int(prefix_len), int(tokens.size))
        aligned = self.aligned_len(prefix_len)
        self._tick += 1
        node = self._root
        pages: List[int] = []
        for i in range(aligned // ps):
            child = node.children.get(tokens[i * ps:(i + 1) * ps]
                                      .tobytes())
            if child is None:
                break
            child.tick = self._tick
            pages.append(child.page)
            node = child
        tail_len = prefix_len - aligned
        if tail_len and len(pages) == aligned // ps:
            tail = node.tails.get(tokens[aligned:prefix_len].tobytes())
            if tail is not None:
                tail.tick = self._tick
                return PrefixMatch(pages, tail.page, tail_len)
        return PrefixMatch(pages)

    # -- insertion ---------------------------------------------------------

    def store(self, tokens: np.ndarray, prefix_len: int,
              slot: int) -> None:
        """Pin a slot's prefix pages into the trie (idempotent: pages
        whose content chain is already stored are only LRU-touched —
        on a full hit the slot's pages ARE the stored ones). The chain
        truncates at the page budget; a partial boundary page registers
        as a COW tail on the last full node."""
        if self.budget_pages <= 0:
            return
        ps = self.pool.page_size
        prefix_len = min(int(prefix_len), int(tokens.size))
        aligned = self.aligned_len(prefix_len)
        self._tick += 1
        node = self._root
        path_pages: Set[int] = set()
        for i in range(aligned // ps):
            key = tokens[i * ps:(i + 1) * ps].tobytes()
            child = node.children.get(key)
            if child is None:
                if not self._make_room(path_pages):
                    return
                child = _TrieNode(self.pool.pin_one(slot, i), node, key,
                                  self._tick)
                node.children[key] = child
                self._held.append(child.page)
            child.tick = self._tick
            path_pages.add(child.page)
            node = child
        tail_len = prefix_len - aligned
        if not tail_len:
            return
        key = tokens[aligned:prefix_len].tobytes()
        if key in node.tails:
            node.tails[key].tick = self._tick
            return
        if not self._make_room(path_pages):
            return
        tail = _Tail(self.pool.pin_one(slot, aligned // ps), node, key,
                     self._tick)
        node.tails[key] = tail
        self._held.append(tail.page)

    def _make_room(self, protect: Set[int]) -> bool:
        while self.pages_held + 1 > self.budget_pages:
            if not self.evict_lru(protect=protect):
                return False
        return True

    # -- eviction ----------------------------------------------------------

    def _evictable(self, protect: Optional[Set[int]]):
        """Leaf-first candidates: tails, and nodes nothing chains
        under. ``protect`` excludes pages an in-flight admission is
        about to share (evicting them would free pages out from under
        the slot being placed)."""
        def walk(node: _TrieNode):
            for tail in node.tails.values():
                if not protect or tail.page not in protect:
                    yield tail
            for child in node.children.values():
                if (not child.children and not child.tails
                        and (not protect or child.page not in protect)):
                    yield child
                yield from walk(child)

        return walk(self._root)

    def evict_lru(self, protect: Optional[Set[int]] = None) -> bool:
        """Evict the least-recently-used evictable LEAF (tail pages
        and chain ends — an interior node's page is meaningless without
        its parent chain, so eviction never orphans a descendant).
        Returns False when nothing is evictable."""
        victim = min(self._evictable(protect),
                     key=lambda n: n.tick, default=None)
        if victim is None:
            return False
        if isinstance(victim, _Tail):
            del victim.node.tails[victim.key]
        else:
            del victim.parent.children[victim.key]
        self._held.remove(victim.page)
        self.pool.unpin([victim.page])
        return True

    def clear(self) -> None:
        while self.evict_lru():
            pass

    def __len__(self) -> int:
        """Stored pages (nodes + tails) — the budget's unit."""
        return self.pages_held
