"""Versioned model store: export/load for the serving server.

TF-Serving parity layout (``/root/reference/kubeflow/tf-serving/
tf-serving-template.libsonnet``: modelBasePath with numeric version
subdirectories, newest served): ``<base>/<version>/`` holds ``model.yaml``
(architecture + config) and ``params.npz`` (flattened param leaves). The
store is format-native to the framework's own models — the tf-serving
SavedModel role without protobufs.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import yaml

MODEL_FILE = "model.yaml"
PARAMS_FILE = "params.npz"


def _flatten(params: Any) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = "/".join(
            str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p))))
            for p in path
        )
        flat[key] = np.asarray(leaf)
    return flat


def _unflatten(flat: Dict[str, np.ndarray], *,
               to_device: bool = True) -> Dict[str, Any]:
    """``to_device=False`` keeps leaves as host arrays — the sharded
    load path must go host → per-device shards without ever committing
    the full tree to the default device."""
    out: Dict[str, Any] = {}
    for key, val in flat.items():
        parts = key.split("/")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(val) if to_device else val
    return out


def shard_lm_params(params, mesh):
    """Place an LM's params on ``mesh`` with the models' logical
    partition specs (tensor-parallel serving). Works from host arrays:
    each device receives only its shard — the full tree is never
    materialized on one chip (the whole point when the model doesn't
    fit one HBM)."""
    import jax
    from jax.sharding import NamedSharding

    from kubeflow_tpu.models import param_partition_specs
    from kubeflow_tpu.parallel.mesh import shape_aware_spec

    specs = param_partition_specs(params)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(
            x, NamedSharding(mesh, shape_aware_spec(s, np.shape(x), mesh))),
        params, specs, is_leaf=lambda x: not isinstance(x, dict))


def build_model(kind: str, config: Dict[str, Any]):
    """Instantiate a servable model by kind name."""
    if kind == "mnist":
        from kubeflow_tpu.models import MnistCnn

        return MnistCnn(), lambda m, p, x: m.apply({"params": p}, x)
    if kind == "resnet":
        from kubeflow_tpu.models.resnet import ResNet, ResNetConfig

        # stem defaults to "conv" HERE (not ResNetConfig's default): models
        # exported before the space_to_depth stem existed have "stem_conv"
        # params, and the stem choice decides the param tree — a saved model
        # must deserialize against the architecture it was trained with
        cfg = ResNetConfig(**{**config,
                              "stem": config.get("stem", "conv"),
                              "stage_sizes":
                              tuple(config.get("stage_sizes", (3, 4, 6, 3)))})
        return ResNet(cfg), lambda m, p, x: m.apply(
            {"params": p["params"], "batch_stats": p["batch_stats"]},
            x, train=False)
    if kind == "bert":
        from kubeflow_tpu.models.bert import Bert, BertConfig

        cfg = BertConfig(**config)
        return Bert(cfg), lambda m, p, x: m.apply({"params": p}, x)
    if kind == "transformer":
        from kubeflow_tpu.models import Transformer, TransformerConfig

        cfg = TransformerConfig(**config)
        return Transformer(cfg), lambda m, p, x: m.apply({"params": p}, x)
    raise ValueError(f"unknown model kind {kind!r}")


def transformer_export_config(config, **overrides) -> Dict[str, Any]:
    """The serving-relevant TransformerConfig fields as an export dict.

    One source of truth for what ``export_model(..., "transformer")``
    must record — hand-copied field lists silently drop serving-relevant
    fields (a soft-capped model exported without ``logits_softcap``
    reloads with different logits).
    """
    import jax.numpy as jnp

    out: Dict[str, Any] = {
        "vocab_size": config.vocab_size,
        "d_model": config.d_model,
        "n_layers": config.n_layers,
        "n_heads": config.n_heads,
        "n_kv_heads": config.n_kv_heads,
        "d_ff": config.d_ff,
        "max_seq_len": config.max_seq_len,
        "n_experts": config.n_experts,
        "experts_per_token": config.experts_per_token,
        "logits_softcap": config.logits_softcap,
        "rope_theta": config.rope_theta,
        "scan_layers": config.scan_layers,
        "dtype": jnp.dtype(config.dtype).name,
        "remat": False,  # serving never trains
    }
    out.update(overrides)
    return out


# artifact quantization: leaves at least this large get int8 storage
# (small leaves — norms, biases — stay exact; their bytes don't matter)
_QUANT_MIN_ELEMS = 4096
_QUANT_SCALE_SUFFIX = "::scale"


def _is_float_dtype(dtype: np.dtype) -> bool:
    """np.floating misses ml_dtypes.bfloat16 (registered kind 'V')."""
    if np.issubdtype(dtype, np.floating):
        return True
    try:
        import ml_dtypes

        return dtype == np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover
        return False


def _quantize_leaf(arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8 (last axis = channels)."""
    flat = arr.reshape(-1, arr.shape[-1]).astype(np.float32)
    scale = np.maximum(np.abs(flat).max(axis=0), 1e-12) / 127.0
    q = np.clip(np.rint(flat / scale), -127, 127).astype(np.int8)
    return q.reshape(arr.shape), scale.astype(np.float32)


def _dequantize_leaf(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    return (q.astype(np.float32) * scale).astype(np.float32)


def export_model(
    path: str,
    kind: str,
    params: Any,
    *,
    config: Optional[Dict[str, Any]] = None,
    version: int = 1,
    input_shape: Optional[Tuple[int, ...]] = None,
    input_dtype: str = "float32",
    quantize: bool = False,
    draft_of: Optional[str] = None,
) -> str:
    """Write ``<path>/<version>/{model.yaml,params.npz}``; returns the dir.

    ``input_shape`` (without the batch dim) lets the server warm up every
    padded batch bucket at load time, so no client request ever pays the
    XLA compile (tf-serving's warmup-assets role; SURVEY §7 hard part (d)).

    ``quantize=True`` stores large float leaves as symmetric
    per-output-channel int8 (+f32 scales): ~4× smaller artifacts, so
    model pulls from GCS and server cold-starts shrink accordingly.
    Dequantized to float at load — a storage/transfer optimization with
    a small, bounded numeric delta (weights round to 1/127 of their
    per-channel max), not a changed serving dtype.

    ``draft_of="<model>"`` or ``"<model>@<version>"`` marks this export
    as a speculative-decoding DRAFT for the named target model (same
    store): the serving repository pairs it with its target at load and
    routes ``speculative: true`` generate requests through the pair
    (``kubeflow_tpu/train/distill.py`` is the recipe that produces
    drafts; an unversioned pairing follows the target's served version).
    """
    vdir = os.path.join(path, str(version))
    os.makedirs(vdir, exist_ok=True)
    meta: Dict[str, Any] = {"kind": kind, "config": config or {}}
    if draft_of:
        meta["draft_of"] = str(draft_of)
    if input_shape is None:
        input_shape = _DEFAULT_INPUT_SHAPES.get(kind)
    if input_shape is not None:
        meta["input_shape"] = [int(d) for d in input_shape]
        meta["input_dtype"] = input_dtype
    flat = _flatten(params)
    if quantize:
        stored: Dict[str, np.ndarray] = {}
        quantized: Dict[str, str] = {}  # key -> original dtype name
        for key, leaf in flat.items():
            arr = np.asarray(leaf)
            if (_is_float_dtype(arr.dtype)
                    and arr.size >= _QUANT_MIN_ELEMS and arr.ndim >= 2):
                q, scale = _quantize_leaf(arr)
                stored[key] = q
                stored[key + _QUANT_SCALE_SUFFIX] = scale
                quantized[key] = arr.dtype.name
            else:
                stored[key] = arr
        meta["quantized_leaves"] = quantized
        flat = stored
    # npz cannot represent ml_dtypes (bf16 writes as raw void and loads
    # as an invalid V2): store such leaves as float32 with their dtype
    # recorded, restored at load
    cast_leaves: Dict[str, str] = {}
    for key, leaf in list(flat.items()):
        arr = np.asarray(leaf)
        if arr.dtype.kind == "V":
            cast_leaves[key] = arr.dtype.name
            flat[key] = arr.astype(np.float32)
    if cast_leaves:
        meta["cast_leaves"] = cast_leaves
    # params first, meta last and ATOMICALLY: list_versions keys on
    # model.yaml's existence, so its rename publishes the version only
    # once the artifact is complete (and a concurrent draft scan never
    # reads a half-written yaml)
    from kubeflow_tpu.workflows.archive import _atomic_write

    np.savez(os.path.join(vdir, PARAMS_FILE), **flat)
    _atomic_write(os.path.join(vdir, MODEL_FILE),
                  yaml.safe_dump(meta).encode())
    return vdir


# per-sample input shapes for warmup when the exporter doesn't say
_DEFAULT_INPUT_SHAPES: Dict[str, Tuple[int, ...]] = {
    "mnist": (28, 28, 1),
    "resnet": (224, 224, 3),
}


@dataclasses.dataclass(frozen=True)
class DraftPair:
    """A paired speculative draft. Immutable and swapped through ONE
    ``LoadedModel.draft`` reference, so request threads snapshot
    config+params+ref atomically (no torn reads across a repair/detach
    by the poll thread)."""

    config: Any
    params: Any
    ref: str  # "<draft name>@<version>"


@dataclasses.dataclass
class LoadedModel:
    kind: str
    version: int
    predict: Callable[[jnp.ndarray], jnp.ndarray]  # jitted, closed over params
    input_shape: Optional[Tuple[int, ...]] = None  # per-sample, for warmup
    input_dtype: str = "float32"
    # autoregressive path (transformer kind): (prompt, true_len, max_new,
    # temperature, rng_seed, greedy=, top_k=, top_p=, filtered=) ->
    # (B, max_new) int32; None for non-LM kinds. greedy/filtered are
    # static (compile-splitting) flags; top_k/top_p are traced and only
    # honored when filtered=True. max_seq_len bounds prompt + new tokens;
    # vocab_size bounds token ids (both would silently clamp otherwise).
    generate: Optional[Callable[..., jnp.ndarray]] = None
    max_seq_len: Optional[int] = None
    vocab_size: Optional[int] = None
    # transformer kind: the config + params the continuous-batching
    # decode engine builds its compiled prefill/insert/step from
    # (kubeflow_tpu/serving/engine.py); None for non-LM kinds
    lm_config: Any = None
    lm_params: Any = None
    # speculative-decoding pair: a store sibling exporting
    # ``draft_of: <this model>[@<version>]`` attaches here at load
    # (ModelRepository._attach_draft) and ``speculative: true`` generate
    # requests route through models/decode.py:speculative_generate.
    # One attribute = one atomic swap (see DraftPair).
    draft: Optional[DraftPair] = None

    def warmup(self, batch_sizes) -> int:
        """Precompile predict for each batch bucket; returns count warmed."""
        if self.input_shape is None:
            return 0
        warmed = 0
        for b in batch_sizes:
            x = jnp.zeros((int(b), *self.input_shape),
                          jnp.dtype(self.input_dtype))
            jax.block_until_ready(self.predict(x))
            warmed += 1
        return warmed


def list_versions(base_path: str) -> List[int]:
    if not os.path.isdir(base_path):
        return []
    return sorted(
        int(d) for d in os.listdir(base_path)
        if d.isdigit() and os.path.isfile(os.path.join(base_path, d, MODEL_FILE))
    )


def load_version(base_path: str, version: int,
                 mesh=None) -> LoadedModel:
    """``mesh`` (transformer kind only): params land SHARDED over it at
    load — the serving tier's tensor-parallel path. One copy in HBM,
    shared by the decode engine and the unary fallback (jit follows
    input shardings)."""
    vdir = os.path.join(base_path, str(version))
    with open(os.path.join(vdir, MODEL_FILE)) as f:
        meta = yaml.safe_load(f)
    kind = meta["kind"]
    with np.load(os.path.join(vdir, PARAMS_FILE)) as npz:
        raw = {k: npz[k] for k in npz.files}
    quantized = meta.get("quantized_leaves") or {}
    if isinstance(quantized, list):  # early artifacts: no dtype record
        quantized = {k: "float32" for k in quantized}
    if quantized:
        flat = {}
        for k, v in raw.items():
            if k.endswith(_QUANT_SCALE_SUFFIX):
                continue
            if k in quantized:
                deq = _dequantize_leaf(v, raw[k + _QUANT_SCALE_SUFFIX])
                flat[k] = deq.astype(np.dtype(quantized[k]))
            else:
                flat[k] = v
        raw = flat
    for k, dtype_name in (meta.get("cast_leaves") or {}).items():
        if k in raw:
            raw[k] = raw[k].astype(np.dtype(dtype_name))
    sharded_load = mesh is not None and kind == "transformer"
    params = _unflatten(raw, to_device=not sharded_load)
    model, apply_fn = build_model(kind, meta.get("config", {}) or {})
    if sharded_load:
        params = shard_lm_params(params, mesh)

    # one program per loaded model, compiled lazily on the first
    # request and billed by the CompileLedger listener; there is no
    # example input at load time to AOT-compile against
    @jax.jit
    def predict(x: jnp.ndarray) -> jnp.ndarray:  # tpulint: disable=TPU018
        return apply_fn(model, params, x)

    generate = None
    max_seq_len = vocab_size = None
    if kind == "transformer":
        from kubeflow_tpu.models.decode import generate as _generate

        import functools

        max_seq_len = model.config.max_seq_len
        vocab_size = model.config.vocab_size

        # greedy and filtered are the only static sampling decisions:
        # every temperature/top_k/top_p shares one compiled sampling
        # program (a client sweeping them must not mint unbounded XLA
        # cache entries); the unfiltered path stays sort-free
        # same listener-only contract as predict: shapes arrive with
        # requests, so the bounded sampling-program inventory compiles
        # lazily per (max_new, greedy, filtered) key
        @functools.partial(jax.jit,
                           static_argnames=("max_new", "greedy", "filtered"))
        def generate(prompt, true_len, max_new, temperature, rng_seed, *,  # tpulint: disable=TPU018
                     greedy, top_k=0, top_p=1.0, filtered=False):
            return _generate(
                model.config, params, prompt,
                max_new_tokens=max_new, true_len=true_len,
                temperature=0.0 if greedy else temperature,
                top_k=top_k if filtered else 0,
                top_p=top_p if filtered else 1.0,
                rng=jax.random.key(rng_seed))

    shape = meta.get("input_shape")
    return LoadedModel(
        kind=kind, version=version, predict=predict,
        input_shape=tuple(shape) if shape else None,
        input_dtype=meta.get("input_dtype", "float32"),
        generate=generate, max_seq_len=max_seq_len, vocab_size=vocab_size,
        lm_config=model.config if kind == "transformer" else None,
        lm_params=params if kind == "transformer" else None)


def find_draft_for(store_root: str, target_name: str,
                   target_version: int) -> Optional[Tuple[str, int]]:
    """The store sibling declaring itself this target's speculative
    draft: ``model.yaml`` carries ``draft_of: "<target>"`` (follows the
    target across versions) or ``"<target>@<version>"`` (pinned).
    Returns ``(draft_name, draft_version)`` — the newest matching
    version of the first matching model name — or None."""
    if not os.path.isdir(store_root):
        return None
    want = {target_name, f"{target_name}@{target_version}"}
    for d in sorted(os.listdir(store_root)):
        mdir = os.path.join(store_root, d)
        if d == target_name or not os.path.isdir(mdir):
            continue
        for v in reversed(list_versions(mdir)):
            try:
                with open(os.path.join(mdir, str(v), MODEL_FILE)) as f:
                    meta = yaml.safe_load(f) or {}
            except (OSError, yaml.YAMLError):
                # a mid-write or corrupt sibling must not abort the scan
                continue
            if not isinstance(meta, dict):
                continue
            if meta.get("draft_of") in want:
                return d, v
    return None


def load_latest(base_path: str) -> Optional[LoadedModel]:
    versions = list_versions(base_path)
    if not versions:
        return None
    return load_version(base_path, versions[-1])
