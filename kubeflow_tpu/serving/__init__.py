"""JAX model serving: versioned model store + REST server (tf-serving parity)."""

from kubeflow_tpu.serving.model_store import (  # noqa: F401
    LoadedModel,
    export_model,
    list_versions,
    load_latest,
    load_version,
)
from kubeflow_tpu.serving.server import ModelRepository, ModelServer  # noqa: F401
