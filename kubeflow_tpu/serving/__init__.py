"""JAX model serving: versioned model store + REST server (tf-serving parity)."""

from kubeflow_tpu.serving.model_store import (  # noqa: F401
    LoadedModel,
    export_model,
    transformer_export_config,
    list_versions,
    load_latest,
    load_version,
)
from kubeflow_tpu.serving.server import ModelRepository, ModelServer  # noqa: F401
from kubeflow_tpu.serving.engine import DecodeEngine  # noqa: F401
from kubeflow_tpu.serving.proxy import PredictProxy  # noqa: F401
from kubeflow_tpu.serving.batch_predict import (  # noqa: F401
    batch_predict_job,
    run_batch_predict,
)
from kubeflow_tpu.serving.graph import (  # noqa: F401
    GraphExecutor,
    GraphNode,
    HttpNodeCaller,
)
from kubeflow_tpu.serving.graph_controller import (  # noqa: F401
    InferenceGraphController,
    inference_graph,
)
from kubeflow_tpu.serving.registry import (  # noqa: F401
    ModelRegistry,
    RegistryService,
    register_export,
)
