"""JAX model server: REST predict API with tf-serving-parity surface.

Replaces TF-Serving / TensorRT-IS (reference surface: gRPC :9000 + REST
:8500, ``tf-serving-template.libsonnet:33-48``; JSON→gRPC bridge
``components/k8s-model-server/http-proxy/server.py``). Endpoints:

- ``GET /v1/models``                       list models + versions
- ``GET /v1/models/<name>``                per-model version status
- ``POST /v1/models/<name>:predict``       ``{"instances": [...]}``
- ``POST /v1/models/<name>/versions/<v>:predict``  pin a version
- ``GET /metrics`` / ``GET /healthz``

TPU-minded serving details: inputs are padded to fixed batch shapes so XLA
never recompiles per request; version hot-reload polls the base path the way
TF-Serving watches its model dir.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.obs import TRACER, extract
from kubeflow_tpu.obs import requests as reqobs
from kubeflow_tpu.serving.engine import EngineClosed, pow2_bucket
from kubeflow_tpu.serving.model_store import (
    LoadedModel,
    list_versions,
    load_version,
)
from kubeflow_tpu.utils import DEFAULT_REGISTRY

log = logging.getLogger(__name__)

_requests = DEFAULT_REGISTRY.counter(
    "kftpu_serving_requests_total", "predict requests")
_latency = DEFAULT_REGISTRY.gauge(
    "kftpu_serving_last_latency_seconds", "last predict latency")
_gen_requests = DEFAULT_REGISTRY.counter(
    "kftpu_serving_generate_requests_total", "generate requests")
_gen_latency = DEFAULT_REGISTRY.gauge(
    "kftpu_serving_generate_last_latency_seconds", "last generate latency")
# a streamed-generate yield suspended longer than this charges the
# request ledger's stream_stall phase; below it is scheduling jitter
STREAM_STALL_MIN_S = 0.05
_spec_requests = DEFAULT_REGISTRY.counter(
    "kftpu_serving_speculative_requests_total",
    "generate requests served through a speculative draft pair")
_spec_draft_tokens = DEFAULT_REGISTRY.counter(
    "kftpu_serving_speculative_draft_tokens_total",
    "draft tokens proposed to the target verifier")
_spec_accepted_tokens = DEFAULT_REGISTRY.counter(
    "kftpu_serving_speculative_accepted_tokens_total",
    "draft tokens the target verifier accepted")
_spec_rate = DEFAULT_REGISTRY.gauge(
    "kftpu_serving_speculative_last_acceptance_rate",
    "acceptance rate (accepted/proposed) of the last speculative request")

_PAD_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


def run_generate(model, body: Dict[str, Any], max_batch_size: int, *,
                 model_name: str = "", stream: bool = False,
                 engine=None) -> Tuple[int, Dict[str, Any]]:
    """The generate core shared by the REST ``:generate`` endpoint and
    the gRPC ``Generate`` RPC: validation, prompt/new-token bucketing,
    the compiled decode call. Returns (http-style status, payload).

    With ``stream=True`` the payload carries ``token_stream`` — an
    iterator of per-step token lists (one ``(B,)`` row per decode
    position) — instead of the dense ``tokens`` matrix.

    With ``engine`` set (a :class:`~kubeflow_tpu.serving.engine.DecodeEngine`),
    each prompt row becomes an engine request sharing the engine's
    decode batch with every other in-flight caller: tokens stream as
    steps complete, ``eos_id`` stops a row early (the dense response
    right-pads finished rows with their final token), and row *i*
    samples reproducibly from ``seed + i`` regardless of co-tenants.
    Greedy output is identical to the bucketed batch path; sampled
    output is reproducible but not bitwise-equal to it."""
    if model.generate is None:
        return 400, {"error": f"model {model_name!r} (kind "
                              f"{model.kind!r}) does not support generate"}
    prompts = body.get("prompt_tokens")
    if prompts is None:
        return 400, {"error": "request must carry 'prompt_tokens' "
                              "(batch of int token lists)"}
    try:
        max_new = int(body.get("max_new_tokens", 16))
        temperature = float(body.get("temperature", 0.0))
        top_k = int(body.get("top_k", 0))
        top_p = float(body.get("top_p", 1.0))
        seed = int(body.get("seed", 0))
        # RAGGED batches are first-class: each row keeps its own length
        # (per-row cache positions in the decode core); iterating also
        # rejects scalars/0-d tensors (TypeError → 400)
        row_lens = [len(p) for p in prompts]
        if not row_lens:
            return 400, {"error": "prompt_tokens batch is empty"}
        if min(row_lens) < 1:
            return 400, {"error": "empty prompt row"}
        width = max(row_lens)
        if isinstance(prompts, np.ndarray):
            arr = prompts.astype(np.int32)
        else:
            arr = np.zeros((len(prompts), width), np.int32)
            for i, p in enumerate(prompts):
                arr[i, :row_lens[i]] = np.asarray(p, dtype=np.int32)
        # an explicit scalar true_len marks the shared real length of
        # every (right-padded) row — the gRPC tensor convention, also
        # honored for REST clients that pad client-side. The array is
        # sliced to it so the prompt bucket never undershoots the data.
        explicit = int(body.get("true_len", 0))
        if explicit:
            if not 1 <= explicit <= width:
                return 400, {"error": f"true_len {explicit} must be in "
                                      f"[1, {width}]"}
            row_lens = [explicit] * arr.shape[0]
            width = explicit
            arr = arr[:, :explicit]
    except (TypeError, ValueError) as e:
        return 400, {"error": f"bad prompt_tokens: {e}"}
    if max_new < 1:
        return 400, {"error": "max_new_tokens must be >= 1"}
    if temperature < 0:
        # a negative temperature silently inverts the distribution
        return 400, {"error": "temperature must be >= 0"}
    if not 0 <= top_k < 2**31:
        return 400, {"error": "top_k must be in [0, 2**31) (0 = no filter)"}
    if not 0.0 < top_p <= 1.0:
        return 400, {"error": "top_p must be in (0, 1]"}
    if not -2**31 <= seed < 2**31:
        # the seed is a traced int32 in the compiled sampler
        return 400, {"error": "seed must fit in int32"}
    try:
        prefix_len = int(body.get("prefix_len", 0))
    except (TypeError, ValueError):
        return 400, {"error": "prefix_len must be an int"}
    if prefix_len:
        if engine is None:
            return 400, {"error": "prefix_len requires the decode "
                                  "engine (server started with "
                                  "decode_slots=0)"}
        if not 0 < prefix_len < min(row_lens):
            return 400, {"error": f"prefix_len {prefix_len} must be in "
                                  f"(0, shortest prompt row "
                                  f"{min(row_lens)})"}
    eos_id = body.get("eos_id")
    if eos_id is not None:
        try:
            eos_id = int(eos_id)
        except (TypeError, ValueError):
            return 400, {"error": "eos_id must be an int token id"}
        if model.vocab_size and not 0 <= eos_id < model.vocab_size:
            return 400, {"error": f"eos_id must be in [0, "
                                  f"{model.vocab_size})"}
        if engine is None:
            # only the engine path watches for EOS; honoring it half the
            # time silently would be worse than refusing
            return 400, {"error": "eos_id requires the decode engine "
                                  "(server started with decode_slots=0)"}
    if arr.ndim != 2:
        return 400, {"error": f"prompt_tokens must be a 2-D batch of "
                              f"token lists, got shape {arr.shape}"}
    if arr.shape[0] > max_batch_size:
        return 400, {"error": f"batch {arr.shape[0]} exceeds max "
                              f"{max_batch_size}"}
    lens_arr = np.asarray(row_lens, np.int32)
    # pad columns never reach the model — check only real tokens
    col = np.arange(width)[None, :]
    real_mask = col < lens_arr[:, None]
    real_vals = arr[real_mask]
    if model.vocab_size and real_vals.size and (
            real_vals.min() < 0 or real_vals.max() >= model.vocab_size):
        # out-of-range ids would silently clamp in the embedding take
        return 400, {"error": f"token ids must be in [0, "
                              f"{model.vocab_size})"}
    true_len = int(lens_arr.max())
    ctx = model.max_seq_len or 0

    if body.get("speculative"):
        # draft-assisted greedy decoding through the paired draft
        # (models/decode.py:speculative_generate); bypasses the engine —
        # speculation optimizes single-stream latency, the engine
        # optimizes aggregate throughput
        try:
            draft_len = int(body.get("draft_len", 4))
        except (TypeError, ValueError):
            return 400, {"error": "draft_len must be an int"}
        if not 1 <= draft_len <= 16:
            return 400, {"error": "draft_len must be in [1, 16]"}
        draft = model.draft  # one atomic snapshot (see DraftPair)
        if draft is None:
            return 400, {"error": f"model {model_name!r} has no paired "
                                  "speculative draft (export one with "
                                  "export_model(..., draft_of=...); see "
                                  "kubeflow_tpu/train/distill.py)"}
        if temperature != 0.0:
            return 400, {"error": "speculative decoding is greedy-only "
                                  "(temperature must be 0)"}
        if stream:
            return 400, {"error": "speculative decoding does not "
                                  "stream (tokens emit in verified "
                                  "chunks)"}
        if eos_id is not None or prefix_len:
            return 400, {"error": "eos_id/prefix_len require the "
                                  "engine path; drop 'speculative' to "
                                  "use them"}
        return _run_generate_speculative(
            model, draft, arr, lens_arr, max_new=max_new, ctx=ctx,
            draft_len=draft_len, model_name=model_name)

    if engine is not None:
        return _run_generate_engine(
            engine, arr, row_lens, max_new=max_new, ctx=ctx,
            temperature=temperature, top_k=top_k, top_p=top_p,
            seed=seed, eos_id=eos_id, prefix_len=prefix_len,
            stream=stream,
            model_name=model_name, model_version=model.version)

    # prompt bucket: one compiled prefill per bucket, capped at the
    # model context (3072-context models serve 2100-token prompts) —
    # the same rule engine admission uses (pow2_bucket)
    bucket = pow2_bucket(true_len, ctx)
    # new-token bucket likewise (a client sweeping max_new_tokens
    # must not mint unbounded compiled programs); decode the bucket,
    # return the first max_new. Decode writes start at true_len (the
    # cache index resets there), so the budget is ctx - true_len —
    # NOT ctx - bucket, which would reject any prompt past half the
    # context. The clamped value is rounded DOWN to a power of two:
    # a raw ctx - true_len clamp would mint one compiled program per
    # distinct prompt length near the context end.
    budget = max(ctx - true_len, 0)
    new_bucket = pow2_bucket(max_new, 1 << 30)
    while new_bucket > budget:
        new_bucket //= 2
    if new_bucket < max_new <= budget:
        # the pow2 bucket doesn't fit but the exact ask does (prompt
        # 29 + max_new 3 in a 32-context model): serve it exactly —
        # a rare tail case, so the per-value compile is acceptable
        new_bucket = max_new
    if bucket < true_len or new_bucket < max_new:
        return 400, {"error": f"prompt ({true_len}) + max_new_tokens "
                              f"({max_new}) exceed the model context "
                              f"({ctx}); cache writes past it would "
                              "silently clamp"}
    padded = np.zeros((arr.shape[0], bucket), np.int32)
    padded[:, :width] = arr
    # batch padded like the predict path: one compiled shape; filler
    # rows get length 1 (length 0 would index position -1 at prefill)
    padded, n = _pad_batch(padded, max_batch_size)
    lens_padded = np.ones((padded.shape[0],), np.int32)
    lens_padded[:n] = lens_arr
    t0 = time.perf_counter()
    try:
        greedy = temperature == 0.0
        out = np.asarray(model.generate(
            jnp.asarray(padded), jnp.asarray(lens_padded), new_bucket,
            jnp.float32(temperature), seed,
            greedy=greedy,
            top_k=jnp.int32(top_k), top_p=jnp.float32(top_p),
            # greedy ignores the filters — don't mint a second compiled
            # program for greedy+filtered requests
            filtered=(top_k > 0 or top_p < 1.0) and not greedy,
            ))[:n, :max_new]
    except (TypeError, ValueError) as e:
        # JAX surfaces shape/dtype mismatches as TypeError/ValueError —
        # request-data problems the schema checks above can't see
        return 400, {"error": f"generate failed: "
                              f"{type(e).__name__}: {e}"}
    except Exception as e:  # noqa: BLE001
        # anything else is the model / runtime (XLA faults, OOM) — a
        # server error, not a client one
        return 500, {"error": f"generate failed: "
                              f"{type(e).__name__}: {e}"}
    dt = time.perf_counter() - t0
    _gen_requests.inc(model=model_name)
    _gen_latency.set(dt, model=model_name)
    if stream:
        return 200, {"token_stream": (out[:, t].tolist()
                                      for t in range(out.shape[1])),
                     "model_version": str(model.version)}
    return 200, {"tokens": out.tolist(),
                 "model_version": str(model.version),
                 "tokens_per_sec": round(out.size / dt, 1)}


def _run_generate_speculative(model, draft, arr, lens_arr, *, max_new,
                              ctx, draft_len,
                              model_name) -> Tuple[int, Dict[str, Any]]:
    """Speculative half of :func:`run_generate`: the paired draft
    proposes ``draft_len`` tokens per round, the target verifies them in
    one multi-token forward. Greedy output matches the plain path token
    for token (at f32 exactly; at bf16 up to argmax tie-breaks); the
    response and /metrics carry the acceptance stats that decide whether
    the draft pays for itself. Batches are served at their exact size
    (no filler-row padding — filler would contaminate the acceptance
    rate)."""
    # the FUSED variant: the whole propose-verify loop is one compiled
    # program per (configs, draft_len, max_new, shape bucket) — the
    # host-loop variant pays a device dispatch per round, which
    # dominates request latency on remote-transport deployments
    from kubeflow_tpu.models.decode import speculative_generate_jit

    true_len = int(lens_arr.max())
    bucket = pow2_bucket(true_len, ctx)
    # max_new buckets like the plain path (server.py:237) — the fused
    # program is keyed by (configs, draft_len, max_new, shapes), so a
    # client sweeping max_new_tokens must not mint unbounded compiled
    # two-model while_loop programs. The budget subtracts draft_len
    # from BOTH contexts: speculation keeps up to draft_len in-flight
    # proposals past the output.
    budget = max(min(ctx, draft.config.max_seq_len)
                 - true_len - draft_len, 0)
    new_bucket = pow2_bucket(max_new, 1 << 30)
    while new_bucket > budget:
        new_bucket //= 2
    if new_bucket < max_new <= budget:
        # exact ask fits but its pow2 bucket doesn't — rare tail, the
        # per-value compile is acceptable
        new_bucket = max_new
    if bucket < true_len or new_bucket < max_new:
        return 400, {"error": f"prompt ({true_len}) + max_new_tokens "
                              f"({max_new}) + draft_len ({draft_len}) "
                              f"exceed the model context ({ctx}); "
                              "speculation needs slack for in-flight "
                              "proposals"}
    padded = np.zeros((arr.shape[0], bucket), np.int32)
    padded[:, :arr.shape[1]] = arr
    t0 = time.perf_counter()
    try:
        toks, stats = speculative_generate_jit(
            model.lm_config, model.lm_params,
            draft.config, draft.params,
            jnp.asarray(padded), max_new_tokens=new_bucket,
            draft_len=draft_len, true_len=jnp.asarray(lens_arr))
    except ValueError as e:
        # the context-slack check (prompt + max_new + draft_len must fit
        # BOTH models) raises eagerly — a request-shape problem
        return 400, {"error": f"generate failed: {e}"}
    except Exception as e:  # noqa: BLE001
        return 500, {"error": f"generate failed: "
                              f"{type(e).__name__}: {e}"}
    dt = time.perf_counter() - t0
    # stats (rounds/draft/accepted) describe the bucket-width run — the
    # actual work done — while tokens return only the requested width
    out = np.asarray(toks)[:, :max_new]
    rate = stats["accepted"] / max(stats["draft_tokens"], 1)
    _gen_requests.inc(model=model_name)
    _gen_latency.set(dt, model=model_name)
    _spec_requests.inc(model=model_name)
    _spec_draft_tokens.inc(stats["draft_tokens"], model=model_name)
    _spec_accepted_tokens.inc(stats["accepted"], model=model_name)
    _spec_rate.set(rate, model=model_name)
    return 200, {"tokens": out.tolist(),
                 "model_version": str(model.version),
                 "tokens_per_sec": round(out.size / dt, 1),
                 "speculative": {
                     "draft": draft.ref,
                     "draft_len": draft_len,
                     "rounds": stats["rounds"],
                     "draft_tokens": stats["draft_tokens"],
                     "accepted": stats["accepted"],
                     "acceptance_rate": round(rate, 3),
                 }}


def parse_serving_mesh(raw: Optional[str]):
    """``"tp=4"`` / ``"dp=2,tp=4"`` → a device mesh (None when unset).
    The env-facing twin of the trainer's MeshConfig."""
    if not raw:
        return None
    from kubeflow_tpu.parallel import MeshConfig, create_mesh

    kw = {}
    for part in raw.split(","):
        k, _, v = part.partition("=")
        k = k.strip()
        if k not in ("dcn", "dp", "pp", "tp"):
            raise ValueError(f"KFTPU_SERVING_MESH axis {k!r} (want "
                             "dcn/dp/pp/tp)")
        if k in kw:
            raise ValueError(f"KFTPU_SERVING_MESH repeats axis {k!r}")
        try:
            kw[k] = int(v)
        except ValueError:
            raise ValueError(
                f"KFTPU_SERVING_MESH axis {k!r} needs an integer size, "
                f"got {v.strip()!r} (format: 'tp=4' or 'dp=2,tp=4')"
            ) from None
    return create_mesh(MeshConfig(**kw))


def _run_generate_engine(engine, arr, row_lens, *, max_new, ctx,
                         temperature, top_k, top_p, seed, eos_id,
                         prefix_len, stream, model_name,
                         model_version) -> Tuple[int, Dict[str, Any]]:
    """Engine half of :func:`run_generate`: one engine request per
    prompt row, sharing the decode batch with all other callers."""
    over = [l for l in row_lens if l + max_new > ctx]
    if over:
        return 400, {"error": f"prompt ({max(over)}) + max_new_tokens "
                              f"({max_new}) exceed the model context "
                              f"({ctx})"}
    t0 = time.perf_counter()
    try:
        # per-row seeds derive from the request seed; int32 wraparound
        # keeps row seeds valid for any validated base seed
        reqs = [engine.submit(arr[i, :row_lens[i]], max_new=max_new,
                              temperature=temperature, top_k=top_k,
                              top_p=top_p,
                              seed=int((np.int64(seed) + i) & 0x7FFFFFFF),
                              eos_id=eos_id, prefix_len=prefix_len)
                for i in range(arr.shape[0])]
    except ValueError as e:
        return 400, {"error": str(e)}
    except EngineClosed as e:
        # engine closed mid-request (version rollover) — retryable
        return 503, {"error": str(e)}
    _gen_requests.inc(model=model_name)

    if stream:
        def steps():
            # time suspended at each yield is the CLIENT not draining:
            # the writer thread is parked in wfile.write/flush, so the
            # gap charges the rows' lifecycle records as stream_stall
            # (threshold-gated; sub-threshold scheduling jitter is not
            # a stall). Same clock domain as the engine's ledger marks.
            rledger = getattr(engine, "rledger", None)
            clock = getattr(engine, "clock", time.monotonic)
            try:
                iters = [r.stream() for r in reqs]
                lasts = [0] * len(iters)
                done = [False] * len(iters)
                while True:
                    fresh = False
                    for i, it in enumerate(iters):
                        if done[i]:
                            continue
                        try:
                            lasts[i] = next(it)
                            fresh = True
                        except StopIteration:
                            done[i] = True
                    if not fresh:
                        return
                    # finished rows repeat their final token (EOS) so
                    # the line stays a full (B,) row
                    ty0 = clock()
                    yield [int(t) for t in lasts]
                    ty1 = clock()
                    if (rledger is not None
                            and ty1 - ty0 >= STREAM_STALL_MIN_S):
                        for r in reqs:
                            rledger.stall(getattr(r, "rid", ""),
                                          reqobs.STREAM_STALL, ty0, ty1)
            finally:
                _gen_latency.set(time.perf_counter() - t0,
                                 model=model_name)

        return 200, {"token_stream": steps(),
                     "model_version": str(model_version)}

    try:
        rows = [r.result() for r in reqs]
    except ValueError as e:
        return 400, {"error": f"generate failed: {e}"}
    except EngineClosed as e:
        # rollover killed the in-flight generation — retryable, not a
        # server fault
        return 503, {"error": f"generate failed: {e}"}
    except Exception as e:  # noqa: BLE001 — engine/runtime fault
        return 500, {"error": f"generate failed: "
                              f"{type(e).__name__}: {e}"}
    dt = time.perf_counter() - t0
    produced = sum(len(r) for r in rows)
    # EOS-terminated rows are right-padded with their final token so the
    # response keeps the dense (B, max_new) contract
    out = [row + [row[-1]] * (max_new - len(row)) for row in rows]
    _gen_latency.set(dt, model=model_name)
    return 200, {"tokens": out,
                 "model_version": str(model_version),
                 "tokens_per_sec": round(produced / dt, 1)}


def _pad_batch(arr: np.ndarray, max_batch: int) -> Tuple[np.ndarray, int]:
    """Pad the leading dim up to a fixed bucket to keep XLA shapes stable."""
    n = arr.shape[0]
    bucket = next((b for b in _PAD_BUCKETS if b >= n and b <= max_batch),
                  max_batch)
    if n == bucket:
        return arr, n
    pad = np.zeros((bucket - n,) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0), n


class ModelRepository:
    """Models under ``<base>/<model_name>/<version>/`` with hot reload."""

    def __init__(self, base_path: str, *, poll_interval_s: float = 10.0,
                 pin_version: Optional[int] = None,
                 warmup_batches: Tuple[int, ...] = (),
                 decode_slots: int = 0,
                 decode_steps_per_sync: int = 1,
                 decode_mesh=None) -> None:
        self.base_path = base_path
        self.poll_interval_s = poll_interval_s
        # padded batch buckets to precompile at load time, before the new
        # version is swapped in — no client request pays the XLA compile
        self.warmup_batches = tuple(warmup_batches)
        # When set (KFTPU_MODEL_VERSION from the per-version traffic-split
        # Deployment), serve exactly this version instead of hot-loading the
        # latest — otherwise every canary backend converges on the same model
        # and the Istio weight split is a no-op.
        self.pin_version = pin_version
        # > 0: transformer models serve :generate through a shared
        # continuous-batching DecodeEngine with this many slots
        # (concurrent callers share one compiled decode step)
        self.decode_slots = decode_slots
        self.decode_steps_per_sync = decode_steps_per_sync
        # a jax.sharding.Mesh: LMs too big for one chip serve through
        # the engine with tensor-parallel-sharded params + KV cache
        # (KFTPU_SERVING_MESH, e.g. "tp=4"); params are sharded once at
        # engine creation via the models' logical partition specs
        self.decode_mesh = decode_mesh
        self._models: Dict[str, LoadedModel] = {}
        self._pinned: Dict[Tuple[str, int], LoadedModel] = {}
        self._engines: Dict[Tuple[str, int], Any] = {}
        # (version, store signature) of the last draft scan per model —
        # the poll loop skips unchanged stores (see _attach_draft)
        self._draft_scans: Dict[str, Any] = {}
        self._lock = threading.Lock()
        # engine construction allocates a full KV cache on device —
        # serialize it so racing first-callers can't transiently double
        # the HBM footprint
        self._engine_create_lock = threading.Lock()
        self._stop = threading.Event()
        self.refresh()

    def engine_for(self, name: str, model: LoadedModel):
        """The continuous-batching engine for this model version (created
        lazily), or None when disabled / not an LM. None also during a
        version rollover race (the model handed in is no longer served),
        so the caller falls back to the unary bucketed path rather than
        resurrecting a just-retired engine's KV cache."""
        if self.decode_slots <= 0 or model.lm_config is None:
            return None
        key = (name, model.version)

        def allowed_locked() -> bool:
            current = self._models.get(name)
            return ((current is not None and
                     current.version == model.version) or
                    key in self._pinned)

        with self._lock:
            eng = self._engines.get(key)
            if eng is not None and eng.closed:
                # a step failure self-closed it (its donated KV cache is
                # invalid) — evict so a fresh engine replaces it
                self._engines.pop(key, None)
                eng = None
            if eng is None and not allowed_locked():
                return None
        if eng is not None:
            return eng
        from kubeflow_tpu.serving.engine import DecodeEngine

        with self._engine_create_lock:
            with self._lock:
                eng = self._engines.get(key)  # a racer built it first
                if eng is not None and not eng.closed:
                    return eng
            # lm_params were sharded over decode_mesh at LOAD time
            # (load_version), so the engine shares the one in-HBM copy
            eng = DecodeEngine(model.lm_config, model.lm_params,
                               slots=self.decode_slots,
                               steps_per_sync=self.decode_steps_per_sync,
                               mesh=self.decode_mesh,
                               # same opt-in as predict bucket warmup:
                               # compile both step programs up front
                               precompile=bool(self.warmup_batches),
                               name=name)
            with self._lock:
                if not allowed_locked():
                    race = None  # retired while we were building
                else:
                    prior = self._engines.get(key)
                    if prior is not None and prior.closed:
                        self._engines.pop(key, None)  # evict the corpse
                    race = self._engines.setdefault(key, eng)
        if race is not eng:
            eng.close()
        return race

    def model_names(self) -> list:
        if not os.path.isdir(self.base_path):
            return []
        return sorted(
            d for d in os.listdir(self.base_path)
            if os.path.isdir(os.path.join(self.base_path, d)) and
            list_versions(os.path.join(self.base_path, d))
        )

    def refresh(self) -> None:
        for name in self.model_names():
            mdir = os.path.join(self.base_path, name)
            versions = list_versions(mdir)
            if not versions:
                continue
            if self.pin_version is not None:
                if self.pin_version not in versions:
                    log.warning("pinned version %d absent for model %s "
                                "(have %s); waiting", self.pin_version, name,
                                versions)
                    continue
                latest = self.pin_version
            else:
                latest = versions[-1]
            with self._lock:
                current = self._models.get(name)
            if current is not None and current.version == latest:
                # drafts pair/replace/detach on later polls without a
                # target version bump (cheap: _attach_draft gates on
                # the store signature and no-ops when nothing changed)
                if current.lm_config is not None:
                    self._attach_draft(name, current)
                continue
            # load + warm up outside the lock (disk read + jit can take
            # seconds); only the swap is serialized, so predicts never
            # stall on reload
            log.info("loading model %s version %d", name, latest)
            loaded = load_version(mdir, latest, mesh=self.decode_mesh)
            if loaded.lm_config is not None:
                self._attach_draft(name, loaded)
            self._warmup(name, loaded)
            with self._lock:
                self._models[name] = loaded
                # retire the outgoing version's decode engine (it holds a
                # full KV cache) — but keep engines for versions still
                # served from _pinned (explicit-version canary clients).
                # close() fails that engine's in-flight requests; clients
                # retry against the new version.
                stale = [k for k in self._engines
                         if k[0] == name and k[1] != latest
                         and k not in self._pinned]
                retired = [self._engines.pop(k) for k in stale]
            for eng in retired:
                eng.close()

    def _store_signature(self) -> Any:
        """A cheap change marker for the store: one stat per model dir
        (a new export touches its model dir's mtime). Lets the poll loop
        skip the O(models × versions) model.yaml walk of a draft scan
        when nothing was exported since the last scan."""
        try:
            names = sorted(os.listdir(self.base_path))
            return tuple(
                (d, os.path.getmtime(os.path.join(self.base_path, d)))
                for d in names
                if os.path.isdir(os.path.join(self.base_path, d)))
        except OSError:
            return None

    def _attach_draft(self, name: str, loaded: LoadedModel) -> None:
        """Pair a speculative-decoding draft from the same store (a
        sibling model whose ``model.yaml`` declares ``draft_of`` this
        model, exported by the ``train/distill.py`` recipe). Pairing is
        best-effort: a broken draft must never stop its target from
        serving. Negative results are cached against the store
        signature so a draft-less store isn't re-walked every poll."""
        from kubeflow_tpu.serving.model_store import find_draft_for

        sig = (loaded.version, self._store_signature())
        if self._draft_scans.get(name) == sig:
            return
        self._draft_scans[name] = sig
        try:
            pair = find_draft_for(self.base_path, name, loaded.version)
        except Exception:  # noqa: BLE001 — a broken store entry must
            # never abort the poll round that swaps in new versions
            log.warning("draft scan failed for %s", name, exc_info=True)
            return
        if pair is None:
            if loaded.draft is not None:
                # the draft export was deleted: one atomic detach
                log.info("draft %s for model %s removed — detaching",
                         loaded.draft.ref, name)
                loaded.draft = None
            return
        dname, dver = pair
        if loaded.draft is not None and \
                loaded.draft.ref == f"{dname}@{dver}":
            return  # unchanged pairing
        try:
            # the draft stays replicated (no mesh): it is small by
            # construction, and speculative_generate runs it alongside
            # the (possibly sharded) target
            d = load_version(os.path.join(self.base_path, dname), dver)
        except Exception:  # noqa: BLE001
            log.exception("failed to load draft %s@%d for %s",
                          dname, dver, name)
            return
        if d.lm_config is None:
            log.warning("draft %s@%d for %s is not a transformer — "
                        "ignoring", dname, dver, name)
            return
        if d.lm_config.vocab_size != loaded.lm_config.vocab_size:
            log.warning("draft %s@%d vocab %d != target %s vocab %d — "
                        "ignoring", dname, dver, d.lm_config.vocab_size,
                        name, loaded.lm_config.vocab_size)
            return
        # one atomic reference swap: request threads snapshot the whole
        # pair, so attach/replace can never expose torn config/params
        from kubeflow_tpu.serving.model_store import DraftPair

        loaded.draft = DraftPair(config=d.lm_config, params=d.lm_params,
                                 ref=f"{dname}@{dver}")
        log.info("paired speculative draft %s with model %s@%d",
                 loaded.draft.ref, name, loaded.version)

    def _warmup(self, name: str, loaded: LoadedModel) -> None:
        if not self.warmup_batches:
            return
        t0 = time.perf_counter()
        try:
            n = loaded.warmup(self.warmup_batches)
        except Exception:  # noqa: BLE001 — warmup is best-effort
            log.exception("warmup failed for %s v%d", name, loaded.version)
            return
        if n:
            log.info("warmed %d batch buckets for %s v%d in %.1fs",
                     n, name, loaded.version, time.perf_counter() - t0)

    def get(self, name: str, version: Optional[int] = None) -> Optional[LoadedModel]:
        with self._lock:
            model = self._models.get(name)
        if model is None:
            return None
        if version is not None and model.version != version:
            with self._lock:
                cached = self._pinned.get((name, version))
            if cached is not None:
                return cached
            mdir = os.path.join(self.base_path, name)
            if version in list_versions(mdir):
                # no warmup here: this runs inside a client request, and
                # compiling every bucket synchronously would multiply the
                # first-request latency it is meant to prevent — the request
                # compiles just its own bucket
                loaded = load_version(mdir, version,
                                      mesh=self.decode_mesh)
                with self._lock:
                    self._pinned[(name, version)] = loaded
                return loaded
            return None
        return model

    def status(self, name: str) -> Optional[Dict[str, Any]]:
        mdir = os.path.join(self.base_path, name)
        versions = list_versions(mdir)
        if not versions:
            return None
        with self._lock:
            served = self._models.get(name)
        out: Dict[str, Any] = {
            "model_version_status": [
                {"version": str(v),
                 "state": "AVAILABLE" if served and served.version == v
                 else "END_OF_LIFE"}
                for v in versions
            ]
        }
        draft = served.draft if served is not None else None
        if draft is not None:
            # the paired speculative draft is part of the serving
            # surface — operators must be able to see the pairing
            out["speculative_draft"] = draft.ref
        return out

    def start_polling(self) -> None:
        def loop():
            while not self._stop.wait(self.poll_interval_s):
                try:
                    self.refresh()
                except Exception:  # noqa: BLE001
                    log.exception("model refresh failed")

        threading.Thread(target=loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            engines = list(self._engines.values())
            self._engines.clear()
        for eng in engines:
            eng.close()


class ModelServer:
    def __init__(self, base_path: str, *, port: int = 8500,
                 max_batch_size: int = 8, poll_interval_s: float = 10.0,
                 pin_version: Optional[int] = None,
                 warmup: bool = False, decode_slots: int = 0,
                 decode_steps_per_sync: int = 1,
                 decode_mesh=None) -> None:
        buckets = tuple(b for b in _PAD_BUCKETS if b <= max_batch_size)
        self.repo = ModelRepository(base_path, poll_interval_s=poll_interval_s,
                                    pin_version=pin_version,
                                    warmup_batches=buckets if warmup else (),
                                    decode_slots=decode_slots,
                                    decode_steps_per_sync=decode_steps_per_sync,
                                    decode_mesh=decode_mesh)
        self.port = port
        self.max_batch_size = max_batch_size
        self._httpd: Optional[ThreadingHTTPServer] = None

    # -- request handling --------------------------------------------------

    def handle_predict(self, name: str, version: Optional[int],
                       body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        model = self.repo.get(name, version)
        if model is None:
            return 404, {"error": f"model {name!r}"
                         f"{f' version {version}' if version else ''} not found"}
        instances = body.get("instances")
        if instances is None:
            return 400, {"error": "request body must contain 'instances'"}
        try:
            arr = np.asarray(instances)
            if arr.ndim == 0 or arr.dtype == object:
                raise ValueError("instances must be a non-empty array")
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
        except Exception as e:  # noqa: BLE001
            return 400, {"error": f"bad instances: {e}"}
        if arr.shape[0] > self.max_batch_size:
            return 400, {"error": f"batch {arr.shape[0]} exceeds max "
                                  f"{self.max_batch_size}"}
        if model.input_shape and tuple(arr.shape[1:]) != tuple(model.input_shape):
            # catch shape mismatches here so they stay client errors —
            # inside the jitted predict they'd surface as opaque 500s
            return 400, {"error": f"instance shape {tuple(arr.shape[1:])} "
                                  f"!= model input {tuple(model.input_shape)}"}
        t0 = time.perf_counter()
        padded, n = _pad_batch(arr, self.max_batch_size)
        try:
            out = np.asarray(model.predict(jnp.asarray(padded)))[:n]
        except (TypeError, ValueError) as e:
            # JAX surfaces shape/dtype mismatches as TypeError/ValueError;
            # models without input_shape metadata can't be pre-checked
            return 400, {"error": f"predict failed: {type(e).__name__}: {e}"}
        except Exception as e:  # noqa: BLE001
            # anything else is an execution fault (XLA runtime, OOM)
            return 500, {"error": f"predict failed: {type(e).__name__}: {e}"}
        dt = time.perf_counter() - t0
        _requests.inc(model=name)
        _latency.set(dt, model=name)
        return 200, {"predictions": out.tolist(),
                     "model_version": str(model.version)}

    def handle_generate(self, name: str, version: Optional[int],
                        body: Dict[str, Any],
                        stream: bool = False) -> Tuple[int, Dict[str, Any]]:
        """Autoregressive generation (transformer models): prompts are
        right-padded to a power-of-two bucket, so the compiled prefill is
        reused across prompt lengths (one compile per bucket, like the
        predict path's padded batch buckets)."""
        model = self.repo.get(name, version)
        if model is None:
            return 404, {"error": f"model {name!r} not found"}
        return run_generate(model, body, self.max_batch_size,
                            model_name=name, stream=stream,
                            engine=self.repo.engine_for(name, model))

    # -- HTTP plumbing -----------------------------------------------------

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            # chunked transfer (the streaming generate path) needs 1.1;
            # every non-streamed response still sets Content-Length
            protocol_version = "HTTP/1.1"

            def _send(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.rstrip("/")
                if path == "/healthz":
                    self._send(200, {"status": "ok"})
                elif path == "/metrics":
                    from kubeflow_tpu.utils.metrics import exposition

                    # the one exposition policy: exemplar suffixes only
                    # for a scraper that requested the extension — a
                    # classic prometheus must get a clean 0.0.4 body
                    body, ctype = exposition(DEFAULT_REGISTRY,
                                             dict(self.headers))
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/v1/models":
                    self._send(200, {"models": server.repo.model_names()})
                elif path.startswith("/v1/models/"):
                    name = path[len("/v1/models/"):]
                    status = server.repo.status(name)
                    if status is None:
                        self._send(404, {"error": f"model {name!r} not found"})
                    else:
                        self._send(200, status)
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError:
                    self._send(400, {"error": "invalid JSON"})
                    return
                path = self.path
                handlers = {":predict": server.handle_predict,
                            ":generate": server.handle_generate}
                verb = next((s for s in handlers if path.endswith(s)), None)
                if verb and path.startswith("/v1/models/"):
                    target = path[len("/v1/models/"):-len(verb)]
                    version: Optional[int] = None
                    if "/versions/" in target:
                        name, _, v = target.partition("/versions/")
                        if not v.isdigit():
                            self._send(400, {"error": f"bad version {v!r}"})
                            return
                        version = int(v)
                    else:
                        name = target
                    # continue the edge proxy's trace (or start one for
                    # direct in-mesh callers); engine submits made inside
                    # inherit this span via the context-local current span
                    remote = extract(dict(self.headers))
                    span_name = "serving" + verb.replace(":", ".")
                    if verb == ":generate" and body.get("stream"):
                        with TRACER.span(span_name, remote=remote,
                                         attrs={"model": name,
                                                "stream": True}) as sp:
                            code, payload = server.handle_generate(
                                name, version, body, stream=True)
                            sp.attrs["http.status"] = code
                        if code != 200:
                            self._send(code, payload)
                            return
                        # JSON-lines over chunked transfer: one line per
                        # decode step, flushed as the generation core
                        # yields it
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/jsonlines")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()

                        def chunk(obj):
                            line = json.dumps(obj).encode() + b"\n"
                            self.wfile.write(
                                f"{len(line):x}\r\n".encode() + line +
                                b"\r\n")
                            self.wfile.flush()

                        try:
                            for toks in payload["token_stream"]:
                                chunk({"tokens": toks})
                            chunk({"done": True,
                                   "model_version":
                                       payload["model_version"]})
                        except Exception as e:  # noqa: BLE001
                            # mid-stream failure: the 200 is already on
                            # the wire, so the error becomes a line
                            chunk({"error": f"{type(e).__name__}: {e}"})
                        self.wfile.write(b"0\r\n\r\n")
                        return
                    with TRACER.span(span_name, remote=remote,
                                     attrs={"model": name}) as sp:
                        code, payload = handlers[verb](name, version, body)
                        sp.attrs["http.status"] = code
                    self._send(code, payload)
                else:
                    self._send(404, {"error": "not found"})

            def log_message(self, *a):
                pass

        return Handler

    def start(self) -> int:
        """Start serving on a daemon thread; returns the bound port."""
        self._httpd = ThreadingHTTPServer(("0.0.0.0", self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        self.repo.start_polling()
        log.info("model server on :%d (base_path=%s)", self.port,
                 self.repo.base_path)
        return self.port

    def stop(self) -> None:
        self.repo.stop()
        if self._httpd:
            self._httpd.shutdown()


def parse_pin_version(raw: Optional[str]) -> Optional[int]:
    """``"3"`` or the manifest's version label ``"v3"`` → 3; empty → None."""
    if not raw:
        return None
    digits = raw[1:] if raw[:1] in ("v", "V") else raw
    if not digits.isdigit():
        raise ValueError(f"KFTPU_MODEL_VERSION must be N or vN, got {raw!r}")
    return int(digits)


def enable_compile_cache(base_path: str) -> None:
    """Persistent XLA compile cache: version reloads and server restarts
    reuse compiled executables instead of paying cold XLA compiles
    (SURVEY §7 hard part (d): serving cold-start)."""
    cache_dir = os.environ.get(
        "KFTPU_COMPILE_CACHE_DIR",
        os.path.join(base_path, ".xla-compile-cache"))
    if not cache_dir or cache_dir.lower() == "off":
        return
    import tempfile

    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        # model volumes are commonly mounted read-only (tf-serving-style
        # PVC); fall back to local scratch rather than crashlooping —
        # restarts lose the cache but version reloads within the pod keep it
        cache_dir = os.path.join(tempfile.gettempdir(), "kftpu-xla-cache")
        os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # serving recompiles are per-bucket and small; cache them all
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    log.info("XLA compile cache at %s", cache_dir)


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    base = os.environ.get("KFTPU_MODEL_BASE_PATH", "/models")
    port = int(os.environ.get("KFTPU_REST_PORT", "8500"))
    grpc_port = int(os.environ.get("KFTPU_GRPC_PORT", "9000"))
    max_batch = int(os.environ.get("KFTPU_MAX_BATCH_SIZE", "8"))
    enable_compile_cache(base)
    server = ModelServer(base, port=port, max_batch_size=max_batch,
                         pin_version=parse_pin_version(
                             os.environ.get("KFTPU_MODEL_VERSION")),
                         warmup=os.environ.get("KFTPU_WARMUP", "1") != "0",
                         # continuous batching is the production default;
                         # 0 falls back to whole-request bucketed batches
                         decode_slots=int(
                             os.environ.get("KFTPU_DECODE_SLOTS", "8")),
                         decode_steps_per_sync=int(
                             os.environ.get("KFTPU_DECODE_STEPS_PER_SYNC",
                                            "4")),
                         # "tp=4": serve LMs tensor-parallel over the
                         # pod's chips (params + KV cache sharded)
                         decode_mesh=parse_serving_mesh(
                             os.environ.get("KFTPU_SERVING_MESH")))
    server.start()
    grpc_server = None  # keep the reference: grpc.Server dies when GC'd
    if grpc_port:
        try:
            from kubeflow_tpu.serving.grpc_server import serve_grpc

            grpc_server, _ = serve_grpc(server.repo, grpc_port,
                                        max_batch_size=max_batch)
        except ImportError as e:
            log.warning("gRPC disabled (grpc not importable: %s); "
                        "serving REST only", e)
    try:
        while True:  # serve forever; Ctrl-C / SIGTERM end the pod
            time.sleep(3600)  # tpulint: disable=TPU003,TPU005
    except KeyboardInterrupt:
        server.stop()
        if grpc_server is not None:
            grpc_server.stop(grace=1.0)


if __name__ == "__main__":
    main()
