"""InferenceGraph controller — SeldonDeployment's cluster-manager role.

The reference's seldon package deploys a cluster manager that turns a
SeldonDeployment CR (predictor graph) into per-model Deployments plus an
injected service-orchestrator engine
(``/root/reference/kubeflow/seldon/core.libsonnet``, CRD + manager
Deployment + RBAC). Same shape here: an ``InferenceGraph`` CR declares a
node tree (:mod:`kubeflow_tpu.serving.graph`) and per-backend model
configs; the controller materializes

- one model-server Deployment + Service per ``model``/``transformer``
  node (the framework's own server, pinned to the node's model path);
- one orchestrator Deployment + Service running
  :mod:`kubeflow_tpu.serving.graph_server` with the graph and the
  node→Service URL map in env.

Everything is owner-referenced to the CR for cascade delete.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from kubeflow_tpu.k8s import helpers
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.k8s.client import KubeClient, register_plural
from kubeflow_tpu.manifests.components.tpujob_operator import GROUP, VERSION
from kubeflow_tpu.operators.controller import (
    Controller,
    make_condition,
    set_phase_status,
)
from kubeflow_tpu.serving.graph import GraphError, GraphNode

log = logging.getLogger(__name__)

API_VERSION = f"{GROUP}/{VERSION}"
GRAPH_KIND = "InferenceGraph"
GRAPH_PLURAL = "inferencegraphs"
register_plural(GRAPH_KIND, GRAPH_PLURAL)

GRAPH_LABEL = "kubeflow-tpu.org/inference-graph"

PHASE_PENDING = "Pending"
PHASE_READY = "Ready"
PHASE_FAILED = "Failed"

REST_PORT = 8500


@dataclass
class InferenceGraphSpec:
    graph: Dict[str, Any]
    # node name -> {"basePath": str, "tpuChips": int, "replicas": int,
    #               "maxBatchSize": int}
    models: Dict[str, Dict[str, Any]]
    image: str = "kubeflow-tpu/serving:v1alpha1"
    orchestrator_image: str = "kubeflow-tpu/serving:v1alpha1"
    port: int = 8600

    root: Optional[GraphNode] = field(init=False, default=None)

    @classmethod
    def from_dict(cls, spec: Dict[str, Any]) -> "InferenceGraphSpec":
        out = cls(
            graph=spec.get("graph") or {},
            models=dict(spec.get("models", {}) or {}),
            image=spec.get("image", cls.image),
            orchestrator_image=spec.get("orchestratorImage",
                                        cls.orchestrator_image),
            port=int(spec.get("port", cls.port)),
        )
        out.validate()
        return out

    def validate(self) -> None:
        if not self.graph:
            raise ValueError("spec.graph is required")
        try:
            self.root = GraphNode.from_dict(self.graph)
        except GraphError as e:
            raise ValueError(str(e)) from e
        missing = [n for n in self.root.backend_nodes()
                   if n not in self.models
                   or not self.models[n].get("basePath")]
        if missing:
            raise ValueError(
                f"spec.models missing basePath for backend node(s) {missing}")
        # the controller names the engine's objects "<graph>-orchestrator";
        # a backend node by that name would collide and route into itself
        if "orchestrator" in self.root.backend_nodes():
            raise ValueError("node name 'orchestrator' is reserved")


def inference_graph_crd() -> o.Obj:
    return o.crd(
        GRAPH_PLURAL, GROUP, GRAPH_KIND,
        versions=(VERSION,),
        short_names=("igraph",),
        printer_columns=(
            {"name": "Phase", "type": "string", "jsonPath": ".status.phase"},
            {"name": "Nodes", "type": "integer",
             "jsonPath": ".status.backendCount"},
        ),
    )


def inference_graph(name: str, ns: str, spec: Dict[str, Any]) -> o.Obj:
    InferenceGraphSpec.from_dict(spec)  # validate at submit time
    return {
        "apiVersion": API_VERSION,
        "kind": GRAPH_KIND,
        "metadata": {"name": name, "namespace": ns},
        "spec": spec,
    }


class InferenceGraphController:
    """Reconciles InferenceGraph CRs into model servers + orchestrator."""

    def __init__(self, client: KubeClient,
                 namespace: Optional[str] = None) -> None:
        self.client = client
        self.namespace = namespace

    def reconcile(self, ns: str, name: str) -> Optional[float]:
        ig = self.client.get_or_none(API_VERSION, GRAPH_KIND, ns, name)
        if ig is None:
            return None
        try:
            spec = InferenceGraphSpec.from_dict(ig["spec"])
        except ValueError as e:
            self._set_status(ig, PHASE_FAILED, conditions=[
                make_condition("Failed", "InvalidSpec", str(e))])
            return None

        backends = spec.root.backend_nodes()
        for node in backends:
            self.client.apply(self._model_deploy(ig, spec, node))
            self.client.apply(self._model_service(ig, node))
        self.client.apply(self._orchestrator_deploy(ig, spec, backends))
        self.client.apply(self._orchestrator_service(ig, spec))

        # prune backends dropped by a graph edit — otherwise replaced
        # nodes keep serving (and burning chips) forever
        keep = ({f"{name}-{n}" for n in backends}
                | {f"{name}-orchestrator", name})
        sel = {GRAPH_LABEL: name}
        for kind in ("Deployment", "Service"):
            api = "apps/v1" if kind == "Deployment" else "v1"
            for obj in self.client.list(api, kind, ns, label_selector=sel):
                oname = obj["metadata"]["name"]
                if oname not in keep:
                    helpers.delete_ignore_missing(self.client, api, kind, ns,
                                                  oname)

        self._set_status(
            ig, PHASE_READY, backendCount=len(backends),
            backends=sorted(backends),
            conditions=[make_condition("Ready", "GraphMaterialized",
                                       f"{len(backends)} backend(s)")])
        return 30.0

    # -- object builders ---------------------------------------------------

    def _labels(self, ig: o.Obj) -> Dict[str, str]:
        return {GRAPH_LABEL: ig["metadata"]["name"]}

    def _model_deploy(self, ig: o.Obj, spec: InferenceGraphSpec,
                      node: str) -> o.Obj:
        name = ig["metadata"]["name"]
        ns = ig["metadata"]["namespace"]
        cfg = spec.models[node]
        resources: Dict[str, Any] = {}
        if int(cfg.get("tpuChips", 0)):
            resources = {"limits": {"google.com/tpu": int(cfg["tpuChips"])}}
        env = {
            "KFTPU_MODEL_BASE_PATH": cfg["basePath"],
            "KFTPU_REST_PORT": str(REST_PORT),
            "KFTPU_MAX_BATCH_SIZE": str(cfg.get("maxBatchSize", 8)),
        }
        pod = o.pod_spec([o.container(
            "server", spec.image,
            command=["python", "-m", "kubeflow_tpu.serving.server"],
            env=env, ports=[REST_PORT], resources=resources or None,
        )])
        dep = o.deployment(
            f"{name}-{node}", ns, pod,
            replicas=int(cfg.get("replicas", 1)),
            labels={**self._labels(ig), "app": f"{name}-{node}"})
        return o.set_owner(dep, ig)

    def _model_service(self, ig: o.Obj, node: str) -> o.Obj:
        name = ig["metadata"]["name"]
        ns = ig["metadata"]["namespace"]
        svc = o.service(
            f"{name}-{node}", ns, {"app": f"{name}-{node}"},
            [{"name": "rest", "port": REST_PORT, "targetPort": REST_PORT}],
            labels=self._labels(ig))
        return o.set_owner(svc, ig)

    def _orchestrator_deploy(self, ig: o.Obj, spec: InferenceGraphSpec,
                             backends: List[str]) -> o.Obj:
        name = ig["metadata"]["name"]
        ns = ig["metadata"]["namespace"]
        urls = {n: f"http://{name}-{n}.{ns}.svc:{REST_PORT}"
                for n in backends}
        # model-server paths are /v1/models/<name>:predict; the node name
        # inside the pod is the model name, so point each node's URL at a
        # server whose repository holds that node's model under basePath
        env = {
            "KFTPU_GRAPH": json.dumps(spec.root.to_dict(), sort_keys=True),
            "KFTPU_GRAPH_BACKENDS": json.dumps(urls, sort_keys=True),
            "KFTPU_GRAPH_PORT": str(spec.port),
        }
        pod = o.pod_spec([o.container(
            "orchestrator", spec.orchestrator_image,
            command=["python", "-m", "kubeflow_tpu.serving.graph_server"],
            env=env, ports=[spec.port],
        )])
        dep = o.deployment(
            f"{name}-orchestrator", ns, pod,
            labels={**self._labels(ig), "app": f"{name}-orchestrator"})
        return o.set_owner(dep, ig)

    def _orchestrator_service(self, ig: o.Obj,
                              spec: InferenceGraphSpec) -> o.Obj:
        name = ig["metadata"]["name"]
        ns = ig["metadata"]["namespace"]
        svc = o.service(
            name, ns, {"app": f"{name}-orchestrator"},
            [{"name": "http", "port": spec.port, "targetPort": spec.port}],
            labels=self._labels(ig))
        return o.set_owner(svc, ig)

    # -- status ------------------------------------------------------------

    def _set_status(self, ig: o.Obj, phase: str, *,
                    conditions: Optional[List[Dict[str, Any]]] = None,
                    **fields: Any) -> None:
        set_phase_status(self.client, ig, phase, conditions=conditions,
                         **fields)

    def controller(self) -> Controller:
        return Controller(self.client, API_VERSION, GRAPH_KIND,
                          self.reconcile, namespace=self.namespace,
                          name="inferencegraph-controller")


def main() -> None:  # pragma: no cover - container entrypoint
    import os

    from kubeflow_tpu.k8s.client import HttpKubeClient

    client = HttpKubeClient.in_cluster()
    ns = os.environ.get("KFTPU_GRAPH_NAMESPACE") or None
    InferenceGraphController(client, namespace=ns).controller().run_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
