"""Inference-graph orchestrator service — Seldon's engine, as a server.

Runs in the orchestrator pod the
:class:`~kubeflow_tpu.serving.graph_controller.InferenceGraphController`
deploys (Seldon equivalent: the service-orchestrator container injected
into every SeldonDeployment predictor pod,
``/root/reference/kubeflow/seldon/core.libsonnet``). Reads the graph and
the node→Service URL map from env, then serves:

- ``POST /v1/graph:predict`` — walk the graph, return predictions + the
  route taken;
- ``POST /v1/graph:feedback`` — ``{"route": [...], "reward": r}`` credits
  router decisions (the MAB reward channel);
- ``GET /v1/graph`` — the graph spec + live router statistics;
- ``GET /healthz``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

from kubeflow_tpu.obs import TRACER, extract
from kubeflow_tpu.serving.graph import (
    GraphError,
    GraphExecutor,
    GraphNode,
    HttpNodeCaller,
)
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.jsonhttp import serve_json

_requests = DEFAULT_REGISTRY.counter(
    "kftpu_graph_requests_total", "inference-graph predict requests")


class GraphService:
    def __init__(self, executor: GraphExecutor) -> None:
        self.executor = executor

    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str = "",
               headers: Optional[Dict[str, str]] = None) -> Tuple[int, Any]:
        if method == "GET" and path == "/healthz":
            return 200, {"ok": True}
        if method == "GET" and path == "/v1/graph":
            return 200, {"graph": self.executor.root.to_dict(),
                         "routers": self.executor.routers.snapshot()}
        if method == "POST" and path == "/v1/graph:predict":
            if not body or "instances" not in body:
                return 400, {"error": "body must contain 'instances'"}
            # continue the edge's trace through the graph walk; node
            # calls made inside inherit via the context-local span
            with TRACER.span("graph.predict",
                             remote=extract(headers)) as sp:
                try:
                    out = self.executor.predict(
                        {"instances": body["instances"]})
                except GraphError as e:
                    sp.attrs["http.status"] = 502
                    return 502, {"error": str(e)}
                sp.attrs["route"] = out.get("route", [])
            _requests.inc()
            return 200, out
        if method == "POST" and path == "/v1/graph:feedback":
            route = (body or {}).get("route")
            reward = (body or {}).get("reward")
            if not isinstance(route, list) or not isinstance(reward,
                                                             (int, float)):
                return 400, {"error": "body must contain 'route' (list) and "
                                      "'reward' (number)"}
            n = self.executor.feedback(route, float(reward))
            return 200, {"credited": n}
        return 404, {"error": "unknown endpoint"}


def main() -> None:  # pragma: no cover - container entrypoint
    root = GraphNode.from_dict(json.loads(os.environ["KFTPU_GRAPH"]))
    backends = json.loads(os.environ.get("KFTPU_GRAPH_BACKENDS", "{}"))
    service = GraphService(GraphExecutor(root, HttpNodeCaller(backends)))
    serve_json(service.handle, int(os.environ.get("KFTPU_GRAPH_PORT", "8600")))


if __name__ == "__main__":  # pragma: no cover
    main()
