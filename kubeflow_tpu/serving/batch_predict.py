"""Batch prediction job: offline inference over a JSONL dataset.

Reference: the tf-batch-predict package — a k8s Job running batch
inference from a model path over GCS input files
(``/root/reference/kubeflow/tf-batch-predict/tf-batch-predict.
libsonnet``). Here the runner loads a versioned model from the store,
streams instances from input JSONL, predicts in size-``batch`` chunks
(padded so XLA compiles one batch shape), and writes predictions JSONL.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.serving.model_store import load_latest, load_version


def _read_instances(path: str) -> Iterator[Any]:
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)


def run_batch_predict(
    model_base_path: str,
    input_path: str,
    output_path: str,
    *,
    version: Optional[int] = None,
    batch_size: int = 32,
) -> Dict[str, Any]:
    """Returns a summary dict; predictions land in ``output_path``."""
    import jax.numpy as jnp

    model = (load_version(model_base_path, version) if version is not None
             else load_latest(model_base_path))
    if model is None:
        raise FileNotFoundError(f"no model versions under {model_base_path}")

    t0 = time.perf_counter()
    n_total = 0
    with open(output_path, "w") as out:
        batch: List[Any] = []

        def flush() -> None:
            nonlocal n_total
            if not batch:
                return
            arr = np.asarray(batch, dtype=np.float32)
            n = arr.shape[0]
            if n < batch_size:  # pad to the compiled batch shape
                pad = np.zeros((batch_size - n,) + arr.shape[1:],
                               dtype=arr.dtype)
                arr = np.concatenate([arr, pad])
            preds = np.asarray(model.predict(jnp.asarray(arr)))[:n]
            for p in preds:
                out.write(json.dumps({"prediction": p.tolist()}) + "\n")
            n_total += n
            batch.clear()

        for inst in _read_instances(input_path):
            batch.append(inst)
            if len(batch) >= batch_size:
                flush()
        flush()
    wall = time.perf_counter() - t0
    return {
        "model_version": model.version,
        "instances": n_total,
        "wall_time_s": round(wall, 3),
        "instances_per_sec": round(n_total / wall, 2) if wall else 0.0,
        "output": output_path,
    }


def batch_predict_job(
    name: str,
    ns: str,
    *,
    image: str = "kubeflow-tpu/serving:v1alpha1",
    model_base_path: str,
    input_path: str,
    output_path: str,
    version: Optional[int] = None,
    batch_size: int = 32,
    tpu_chips: int = 0,
) -> o.Obj:
    """The k8s Job manifest (tf-batch-predict.libsonnet parity)."""
    args = ["--model-base-path", model_base_path,
            "--input", input_path, "--output", output_path,
            "--batch-size", str(batch_size)]
    if version is not None:
        args += ["--version", str(version)]
    resources = ({"limits": {"google.com/tpu": tpu_chips}}
                 if tpu_chips else None)
    pod = o.pod_spec(
        [o.container(
            "batch-predict", image,
            command=["python", "-m", "kubeflow_tpu.serving.batch_predict"],
            args=args,
            resources=resources,
        )],
        restart_policy="OnFailure",
    )
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": o.metadata(name, ns),
        "spec": {"template": {"metadata": {"labels": {"app": name}},
                              "spec": pod},
                 "backoffLimit": 2},
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kubeflow_tpu.serving.batch_predict")
    p.add_argument("--model-base-path", required=True)
    p.add_argument("--input", required=True)
    p.add_argument("--output", required=True)
    p.add_argument("--version", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args(argv)
    summary = run_batch_predict(
        args.model_base_path, args.input, args.output,
        version=args.version, batch_size=args.batch_size)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
