"""gRPC predict service sharing the REST server's model repository.

TF-Serving parity: the reference model server's primary surface is gRPC
:9000 with REST :8500 secondary (``/root/reference/kubeflow/tf-serving/
tf-serving-template.libsonnet:33-48``); its clients speak gRPC through the
http-proxy JSON bridge (``components/k8s-model-server/http-proxy/
server.py:29-35``). Service stubs are hand-wired generic method handlers
(no grpc_tools dependency); messages come from ``predict.proto`` →
``predict_pb2.py``.
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Optional, Tuple

import grpc
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.obs import TRACER, extract, grpc_metadata
from kubeflow_tpu.serving import predict_pb2 as pb
from kubeflow_tpu.serving.engine import EngineClosed
from kubeflow_tpu.serving.server import (
    ModelRepository,
    _pad_batch,
    run_generate,
)
from kubeflow_tpu.utils import DEFAULT_REGISTRY

log = logging.getLogger(__name__)

SERVICE_NAME = "kubeflow_tpu.serving.PredictionService"

_grpc_requests = DEFAULT_REGISTRY.counter(
    "kftpu_serving_grpc_requests_total", "gRPC predict requests")
_grpc_generates = DEFAULT_REGISTRY.counter(
    "kftpu_serving_grpc_generate_requests_total", "gRPC generate requests")

# numpy has no bfloat16; ml_dtypes (a jax dep) provides the wire dtype
try:
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BFLOAT16 = None


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        if _BFLOAT16 is None:
            raise ValueError("bfloat16 wire dtype needs ml_dtypes")
        return _BFLOAT16
    return np.dtype(name)


def tensor_to_array(t: pb.Tensor) -> np.ndarray:
    dtype = _np_dtype(t.dtype or "float32")
    arr = np.frombuffer(t.data, dtype=dtype)
    shape = tuple(t.shape)
    if int(np.prod(shape, dtype=np.int64)) != arr.size:
        raise ValueError(f"shape {shape} does not match {arr.size} elements")
    return arr.reshape(shape)


def array_to_tensor(arr: np.ndarray) -> pb.Tensor:
    arr = np.ascontiguousarray(arr)
    return pb.Tensor(shape=list(arr.shape), dtype=arr.dtype.name,
                     data=arr.tobytes())


class PredictionServicer:
    """Unary handlers over the shared ModelRepository."""

    def __init__(self, repo: ModelRepository, *, max_batch_size: int = 8) -> None:
        self.repo = repo
        self.max_batch_size = max_batch_size

    # -- RPCs --------------------------------------------------------------

    def Predict(self, request: pb.PredictRequest,
                context: grpc.ServicerContext) -> pb.PredictResponse:
        # traceparent rides invocation metadata (the gRPC twin of the
        # HTTP header); the same W3C extract handles both carriers
        with TRACER.span("serving.grpc.predict",
                         remote=extract(context.invocation_metadata()),
                         attrs={"model": request.model_name}):
            return self._predict(request, context)

    def _predict(self, request: pb.PredictRequest,
                 context: grpc.ServicerContext) -> pb.PredictResponse:
        model = self.repo.get(request.model_name,
                              request.version or None)
        if model is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"model {request.model_name!r} not found")
        try:
            arr = tensor_to_array(request.inputs)
        except (ValueError, TypeError) as e:
            # TypeError: np.dtype on a garbage dtype string
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if arr.ndim == 0 or arr.shape[0] > self.max_batch_size:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"batch must be in [1, {self.max_batch_size}]")
        if model.input_shape and tuple(arr.shape[1:]) != tuple(model.input_shape):
            # keep shape mismatches in the client-error class — inside the
            # jitted predict they would surface as INTERNAL
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"instance shape {tuple(arr.shape[1:])} != model "
                          f"input {tuple(model.input_shape)}")
        if np.issubdtype(arr.dtype, np.integer):
            # image clients send uint8 pixels (4× less wire/transfer than
            # f32 — TF-Serving's image convention); models take floats
            arr = arr.astype(np.float32)
        padded, n = _pad_batch(arr, self.max_batch_size)
        try:
            out = np.asarray(model.predict(jnp.asarray(padded)))[:n]
        except (TypeError, ValueError) as e:
            # JAX shape/dtype mismatches — request data, not the server
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"predict failed: {type(e).__name__}: {e}")
        except Exception as e:  # noqa: BLE001 — execution fault, not client
            context.abort(grpc.StatusCode.INTERNAL,
                          f"predict failed: {type(e).__name__}: {e}")
        _grpc_requests.inc(model=request.model_name)
        return pb.PredictResponse(outputs=array_to_tensor(out),
                                  model_version=model.version)

    def _generate_inputs(self, request: pb.GenerateRequest,
                         context: grpc.ServicerContext):
        """Shared Generate/GenerateStream request decoding: model lookup
        + the run_generate body dict. Aborts the RPC on bad input."""
        model = self.repo.get(request.model_name, request.version or None)
        if model is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"model {request.model_name!r} not found")
        try:
            prompt = tensor_to_array(request.prompt)
        except (ValueError, TypeError) as e:
            # TypeError: np.dtype on a garbage dtype string
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        body = {
            "prompt_tokens": prompt,
            "max_new_tokens": request.max_new_tokens or 16,
            "temperature": request.temperature,
            "seed": request.seed,
            "true_len": request.true_len,
            "top_k": request.top_k,
            # proto3 default 0.0 means "unset" — no filter
            "top_p": request.top_p or 1.0,
            "prefix_len": request.prefix_len,
        }
        if request.HasField("eos_id"):
            body["eos_id"] = request.eos_id
        if request.speculative:
            body["speculative"] = True
            if request.draft_len:
                body["draft_len"] = request.draft_len
        return model, body

    def Generate(self, request: pb.GenerateRequest,
                 context: grpc.ServicerContext) -> pb.GenerateResponse:
        """Autoregressive generation over binary prompt tensors — the
        fast-path twin of the REST ``:generate`` endpoint (shared core:
        ``kubeflow_tpu.serving.server.run_generate``)."""
        with TRACER.span("serving.grpc.generate",
                         remote=extract(context.invocation_metadata()),
                         attrs={"model": request.model_name}):
            model, body = self._generate_inputs(request, context)
            code, payload = run_generate(
                model, body, self.max_batch_size,
                model_name=request.model_name,
                engine=self.repo.engine_for(request.model_name, model))
        if code != 200:
            context.abort(_status_for(code),
                          payload.get("error", "generate failed"))
        _grpc_generates.inc(model=request.model_name)
        resp = pb.GenerateResponse(
            tokens=array_to_tensor(np.asarray(payload["tokens"],
                                              np.int32)),
            model_version=int(payload["model_version"]))
        spec = payload.get("speculative")
        if spec:
            resp.speculative.MergeFrom(pb.SpeculativeStats(
                draft=spec["draft"], draft_len=spec["draft_len"],
                rounds=spec["rounds"],
                draft_tokens=spec["draft_tokens"],
                accepted=spec["accepted"],
                acceptance_rate=spec["acceptance_rate"]))
        return resp

    def GenerateStream(self, request: pb.GenerateRequest,
                       context: grpc.ServicerContext):
        """Server-streaming generation: one :class:`GenerateChunk` per
        decode position (a row of tokens across the batch), then a
        final ``done`` chunk. Chunks arrive as the generation core
        yields them."""
        # span covers setup + engine submit (where the request's trace
        # context is captured); the stream itself outlives it
        with TRACER.span("serving.grpc.generate_stream",
                         remote=extract(context.invocation_metadata()),
                         attrs={"model": request.model_name}):
            model, body = self._generate_inputs(request, context)
            code, payload = run_generate(
                model, body, self.max_batch_size,
                model_name=request.model_name, stream=True,
                engine=self.repo.engine_for(request.model_name, model))
        if code != 200:
            context.abort(_status_for(code),
                          payload.get("error", "generate failed"))
        _grpc_generates.inc(model=request.model_name)
        version = int(payload["model_version"])
        try:
            for step_tokens in payload["token_stream"]:
                yield pb.GenerateChunk(tokens=step_tokens,
                                       model_version=version)
        except EngineClosed as e:
            # rollover mid-stream — retryable, same class as pre-stream
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"generate failed: {e}")
        except Exception as e:  # noqa: BLE001 — mid-stream engine fault
            context.abort(grpc.StatusCode.INTERNAL,
                          f"generate failed: {type(e).__name__}: {e}")
        yield pb.GenerateChunk(done=True, model_version=version)

    def GetModelStatus(self, request: pb.ModelStatusRequest,
                       context: grpc.ServicerContext) -> pb.ModelStatusResponse:
        status = self.repo.status(request.model_name)
        if status is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"model {request.model_name!r} not found")
        return pb.ModelStatusResponse(model_version_status=[
            pb.ModelVersionStatus(version=int(s["version"]), state=s["state"])
            for s in status["model_version_status"]
        ])

    def ListModels(self, request: pb.ListModelsRequest,
                   context: grpc.ServicerContext) -> pb.ListModelsResponse:
        return pb.ListModelsResponse(models=self.repo.model_names())


def _status_for(code: int) -> "grpc.StatusCode":
    """HTTP-style core status → gRPC: 4xx = the request was bad, 503 =
    retryable rollover, other 5xx = the model/runtime faulted."""
    if code < 500:
        return grpc.StatusCode.INVALID_ARGUMENT
    if code == 503:
        return grpc.StatusCode.UNAVAILABLE
    return grpc.StatusCode.INTERNAL


def _handlers(servicer: PredictionServicer) -> grpc.GenericRpcHandler:
    method_handlers = {
        "Predict": grpc.unary_unary_rpc_method_handler(
            servicer.Predict,
            request_deserializer=pb.PredictRequest.FromString,
            response_serializer=pb.PredictResponse.SerializeToString),
        "GetModelStatus": grpc.unary_unary_rpc_method_handler(
            servicer.GetModelStatus,
            request_deserializer=pb.ModelStatusRequest.FromString,
            response_serializer=pb.ModelStatusResponse.SerializeToString),
        "ListModels": grpc.unary_unary_rpc_method_handler(
            servicer.ListModels,
            request_deserializer=pb.ListModelsRequest.FromString,
            response_serializer=pb.ListModelsResponse.SerializeToString),
        "Generate": grpc.unary_unary_rpc_method_handler(
            servicer.Generate,
            request_deserializer=pb.GenerateRequest.FromString,
            response_serializer=pb.GenerateResponse.SerializeToString),
        "GenerateStream": grpc.unary_stream_rpc_method_handler(
            servicer.GenerateStream,
            request_deserializer=pb.GenerateRequest.FromString,
            response_serializer=pb.GenerateChunk.SerializeToString),
    }
    return grpc.method_handlers_generic_handler(SERVICE_NAME, method_handlers)


# a batch-8 224×224×3 fp32 tensor is ~4.8 MB — over gRPC's 4 MB default;
# TF-Serving raises both directions the same way for image workloads
MAX_MESSAGE_BYTES = 64 * 1024 * 1024

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
    ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
]


def serve_grpc(repo: ModelRepository, port: int = 9000, *,
               max_batch_size: int = 8,
               max_workers: int = 8) -> Tuple[grpc.Server, int]:
    """Start the gRPC server on a daemon thread pool; returns (server, port)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers),
                         options=_CHANNEL_OPTIONS)
    server.add_generic_rpc_handlers(
        (_handlers(PredictionServicer(repo, max_batch_size=max_batch_size)),))
    bound = server.add_insecure_port(f"0.0.0.0:{port}")
    server.start()
    log.info("gRPC prediction service on :%d", bound)
    return server, bound


class PredictClient:
    """Thin typed client over a grpc channel (no generated stubs needed)."""

    def __init__(self, target: str) -> None:
        self.channel = grpc.insecure_channel(target,
                                             options=_CHANNEL_OPTIONS)
        base = f"/{SERVICE_NAME}/"
        self._predict = self.channel.unary_unary(
            base + "Predict",
            request_serializer=pb.PredictRequest.SerializeToString,
            response_deserializer=pb.PredictResponse.FromString)
        self._status = self.channel.unary_unary(
            base + "GetModelStatus",
            request_serializer=pb.ModelStatusRequest.SerializeToString,
            response_deserializer=pb.ModelStatusResponse.FromString)
        self._list = self.channel.unary_unary(
            base + "ListModels",
            request_serializer=pb.ListModelsRequest.SerializeToString,
            response_deserializer=pb.ListModelsResponse.FromString)
        self._generate = self.channel.unary_unary(
            base + "Generate",
            request_serializer=pb.GenerateRequest.SerializeToString,
            response_deserializer=pb.GenerateResponse.FromString)
        self._generate_stream = self.channel.unary_stream(
            base + "GenerateStream",
            request_serializer=pb.GenerateRequest.SerializeToString,
            response_deserializer=pb.GenerateChunk.FromString)

    def predict(self, model_name: str, inputs: np.ndarray,
                version: Optional[int] = None,
                timeout: float = 120.0) -> Tuple[np.ndarray, int]:
        resp = self._predict(pb.PredictRequest(
            model_name=model_name, version=version or 0,
            inputs=array_to_tensor(np.asarray(inputs))), timeout=timeout,
            metadata=grpc_metadata())
        return tensor_to_array(resp.outputs), resp.model_version

    def _generate_request(self, model_name, prompt, *, max_new_tokens,
                          true_len, temperature, seed, top_k, top_p,
                          eos_id, version,
                          prefix_len: int = 0) -> "pb.GenerateRequest":
        req = pb.GenerateRequest(
            model_name=model_name, version=version or 0,
            prompt=array_to_tensor(np.asarray(prompt, np.int32)),
            true_len=true_len, max_new_tokens=max_new_tokens,
            temperature=temperature, seed=seed,
            top_k=top_k, top_p=top_p, prefix_len=prefix_len)
        if eos_id is not None:
            req.eos_id = eos_id
        return req

    def generate(self, model_name: str, prompt: np.ndarray, *,
                 max_new_tokens: int = 16, true_len: int = 0,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_id: Optional[int] = None,
                 prefix_len: int = 0,
                 version: Optional[int] = None,
                 timeout: float = 300.0) -> Tuple[np.ndarray, int]:
        resp = self._generate(self._generate_request(
            model_name, prompt, max_new_tokens=max_new_tokens,
            true_len=true_len, temperature=temperature, seed=seed,
            top_k=top_k, top_p=top_p, eos_id=eos_id, version=version,
            prefix_len=prefix_len),
            timeout=timeout, metadata=grpc_metadata())
        return tensor_to_array(resp.tokens), resp.model_version

    def generate_speculative(self, model_name: str, prompt: np.ndarray,
                             *, max_new_tokens: int = 16,
                             draft_len: int = 0, true_len: int = 0,
                             version: Optional[int] = None,
                             timeout: float = 300.0
                             ) -> Tuple[np.ndarray, int, dict]:
        """Greedy draft-assisted generation through the model's paired
        speculative draft. Returns ``(tokens, version, stats)`` with
        the acceptance accounting (empty dict if the server sent
        none)."""
        req = self._generate_request(
            model_name, prompt, max_new_tokens=max_new_tokens,
            true_len=true_len, temperature=0.0, seed=0, top_k=0,
            top_p=1.0, eos_id=None, version=version)
        req.speculative = True
        if draft_len:
            req.draft_len = draft_len
        resp = self._generate(req, timeout=timeout,
                              metadata=grpc_metadata())
        stats: dict = {}
        if resp.HasField("speculative"):
            s = resp.speculative
            stats = {"draft": s.draft, "draft_len": s.draft_len,
                     "rounds": s.rounds,
                     "draft_tokens": s.draft_tokens,
                     "accepted": s.accepted,
                     "acceptance_rate": round(s.acceptance_rate, 3)}
        return tensor_to_array(resp.tokens), resp.model_version, stats

    def generate_stream(self, model_name: str, prompt: np.ndarray, *,
                        max_new_tokens: int = 16, true_len: int = 0,
                        temperature: float = 0.0, seed: int = 0,
                        top_k: int = 0, top_p: float = 1.0,
                        eos_id: Optional[int] = None,
                        prefix_len: int = 0,
                        version: Optional[int] = None,
                        timeout: float = 300.0):
        """Yield ``(B,)`` int32 token arrays as decode steps complete."""
        for chunk in self._generate_stream(self._generate_request(
                model_name, prompt, max_new_tokens=max_new_tokens,
                true_len=true_len, temperature=temperature, seed=seed,
                top_k=top_k, top_p=top_p, eos_id=eos_id,
                version=version, prefix_len=prefix_len),
                timeout=timeout, metadata=grpc_metadata()):
            if chunk.done:
                return
            yield np.asarray(chunk.tokens, np.int32)

    def model_status(self, model_name: str, timeout: float = 30.0):
        resp = self._status(pb.ModelStatusRequest(model_name=model_name),
                            timeout=timeout)
        return [(s.version, s.state) for s in resp.model_version_status]

    def list_models(self, timeout: float = 30.0):
        return list(self._list(pb.ListModelsRequest(), timeout=timeout).models)

    def close(self) -> None:
        self.channel.close()
