"""Inference graphs — chained, routed, and ensembled model serving.

The reference ships Seldon core for this (``/root/reference/kubeflow/
seldon/core.libsonnet``: the SeldonDeployment CRD + cluster manager +
service-orchestrator engine that walks a predictor graph per request).
This module is the engine role, TPU-framework-native: a typed graph of
nodes over the framework's own model servers
(:mod:`kubeflow_tpu.serving.server`), one JSON payload convention
(``{"instances": ...}`` → ``{"predictions": ...}``) end to end.

Node types (Seldon's vocabulary, same tree semantics):

- ``model`` / ``transformer`` — call the node's backend, then pipe the
  output through the child chain (a transformer is a model whose output
  feeds the next stage; the split exists for readability of graphs);
- ``router`` — pick ONE child per request: static ``weights`` or
  ``epsilon_greedy`` over recorded reward feedback (Seldon's MAB router);
- ``combiner`` — fan the input to ALL children and merge their
  predictions: ``mean`` (ensemble average) or ``vote`` (argmax majority).

The executor is transport-agnostic: a *caller* maps node name → callable
(HTTP to in-cluster Services in production, in-process functions in
tests — the same seam :class:`~kubeflow_tpu.k8s.client.KubeClient` gives
operators).
"""

from __future__ import annotations

import json
import random
import re
import threading
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

NODE_TYPES = ("model", "transformer", "router", "combiner")
ROUTER_STRATEGIES = ("weights", "epsilon_greedy")
COMBINERS = ("mean", "vote")

# payload convention shared with the model server
Payload = Dict[str, Any]
NodeCaller = Callable[[str, Payload], Payload]


class GraphError(Exception):
    """Invalid graph spec or failed node call."""


# node names become k8s object names (controller) and model names (URLs);
# DNS-1123 keeps both worlds valid
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]{0,61}[a-z0-9])?$")


@dataclass
class GraphNode:
    name: str
    type: str
    children: List["GraphNode"] = field(default_factory=list)
    # router-only
    strategy: str = "weights"
    weights: Dict[str, float] = field(default_factory=dict)
    epsilon: float = 0.1
    # combiner-only
    combine: str = "mean"

    @classmethod
    def from_dict(cls, d: Dict[str, Any], *, _seen=None) -> "GraphNode":
        _seen = set() if _seen is None else _seen
        name = d.get("name", "")
        if not name:
            raise GraphError("node missing 'name'")
        if not _NAME_RE.match(name):
            raise GraphError(
                f"node name {name!r} must be a DNS-1123 label "
                "(lowercase alphanumerics and '-')")
        if name in _seen:
            raise GraphError(f"duplicate node name {name!r}")
        _seen.add(name)
        ntype = d.get("type", "model")
        if ntype not in NODE_TYPES:
            raise GraphError(f"node {name!r}: unknown type {ntype!r}")
        node = cls(
            name=name,
            type=ntype,
            children=[cls.from_dict(c, _seen=_seen)
                      for c in d.get("children", []) or []],
            strategy=d.get("strategy", "weights"),
            weights=dict(d.get("weights", {}) or {}),
            epsilon=float(d.get("epsilon", 0.1)),
            combine=d.get("combine", "mean"),
        )
        node.validate()
        return node

    def validate(self) -> None:
        if self.type == "router":
            if len(self.children) < 2:
                raise GraphError(f"router {self.name!r} needs >=2 children")
            if self.strategy not in ROUTER_STRATEGIES:
                raise GraphError(f"router {self.name!r}: unknown strategy "
                                 f"{self.strategy!r}")
            if self.strategy == "weights":
                missing = [c.name for c in self.children
                           if c.name not in self.weights]
                if missing:
                    raise GraphError(
                        f"router {self.name!r}: no weight for {missing}")
                if any(w < 0 for w in self.weights.values()):
                    # random.choices silently misroutes on non-monotonic
                    # cumulative weights instead of erroring
                    raise GraphError(
                        f"router {self.name!r}: weights must be >= 0")
                if sum(self.weights.values()) <= 0:
                    raise GraphError(
                        f"router {self.name!r}: weights must sum > 0")
        if self.type == "combiner":
            if len(self.children) < 2:
                raise GraphError(f"combiner {self.name!r} needs >=2 children")
            if self.combine not in COMBINERS:
                raise GraphError(f"combiner {self.name!r}: unknown combine "
                                 f"{self.combine!r}")

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "type": self.type}
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        if self.type == "router":
            d["strategy"] = self.strategy
            if self.weights:
                d["weights"] = dict(self.weights)
            d["epsilon"] = self.epsilon
        if self.type == "combiner":
            d["combine"] = self.combine
        return d

    def backend_nodes(self) -> List[str]:
        """Names of nodes that need a model backend (model/transformer)."""
        out = [self.name] if self.type in ("model", "transformer") else []
        for c in self.children:
            out.extend(c.backend_nodes())
        return out


class RouterState:
    """Per-router reward statistics for epsilon-greedy routing.

    Seldon's multi-armed-bandit router keeps (pulls, reward) per child
    and exploits the best arm with probability 1-ε. Feedback arrives via
    the orchestrator's ``:feedback`` endpoint after the caller scores a
    prediction.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pulls: Dict[Tuple[str, str], int] = {}
        self._reward: Dict[Tuple[str, str], float] = {}

    def record(self, router: str, child: str, reward: float) -> None:
        key = (router, child)
        with self._lock:
            self._pulls[key] = self._pulls.get(key, 0) + 1
            self._reward[key] = self._reward.get(key, 0.0) + reward

    def mean_reward(self, router: str, child: str) -> float:
        key = (router, child)
        with self._lock:
            n = self._pulls.get(key, 0)
            return self._reward.get(key, 0.0) / n if n else 0.0

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                f"{r}/{c}": {"pulls": n,
                             "mean_reward": self._reward.get((r, c), 0.0) / n}
                for (r, c), n in self._pulls.items() if n
            }


def _combiner_fanout(node: GraphNode) -> int:
    """Pool workers one request can occupy: each combiner submits all
    children but the last (which runs inline in the calling thread)."""
    own = max(0, len(node.children) - 1) if node.type == "combiner" else 0
    return own + sum(_combiner_fanout(c) for c in node.children)


def _has_nested_combiner(node: GraphNode, *, inside: bool = False) -> bool:
    """True when a combiner sits below another combiner anywhere."""
    if node.type == "combiner":
        if inside:
            return True
        inside = True
    return any(_has_nested_combiner(c, inside=inside)
               for c in node.children)


# headroom for concurrent in-flight requests sharing the executor's pool;
# threads are created lazily, so a generous cap costs nothing until used
_POOL_CONCURRENCY = 32


class GraphExecutor:
    """Walks a graph per request, calling node backends through ``caller``."""

    def __init__(self, root: GraphNode, caller: NodeCaller, *,
                 seed: Optional[int] = None) -> None:
        self.root = root
        self.caller = caller
        self.routers = RouterState()
        self._rng = random.Random(seed)
        # one long-lived pool for combiner fan-out — per-request executor
        # creation would churn threads on the serving hot path. The last
        # child of every combiner runs inline in the caller's thread.
        # NESTED combiners can still deadlock any bounded shared pool
        # (pool workers block on tasks queued behind other requests'
        # workers), so that rare shape falls back to per-request threads:
        # correctness over thread reuse.
        fanout = _combiner_fanout(root)
        self._nested = _has_nested_combiner(root)
        self._pool = (ThreadPoolExecutor(
            max_workers=max(fanout * _POOL_CONCURRENCY, 4))
            if fanout and not self._nested else None)

    # -- predict -----------------------------------------------------------

    def predict(self, payload: Payload) -> Payload:
        """Evaluate the graph; the response carries the route taken."""
        route: List[str] = []
        out = self._eval(self.root, payload, route)
        out["route"] = route
        return out

    def _eval(self, node: GraphNode, payload: Payload,
              route: List[str]) -> Payload:
        if node.type in ("model", "transformer"):
            route.append(node.name)
            out = self.caller(node.name, payload)
            # chain: each child consumes the previous stage's predictions
            for child in node.children:
                out = self._eval(child, _as_input(out), route)
            return out
        if node.type == "router":
            child = self._route(node)
            route.append(f"{node.name}->{child.name}")
            return self._eval(child, payload, route)
        # combiner: same input to every child concurrently — ensemble
        # latency is max(children), not sum (this is the serving hot
        # path). Each child records into its own sub-route, appended in
        # child order afterwards, so routes stay deterministic and router
        # decisions under a combiner still receive feedback credit.
        route.append(node.name)
        sub_routes: List[List[str]] = [[] for _ in node.children]
        if self._pool is not None:
            futs = [self._pool.submit(self._eval, c, payload, sub_routes[i])
                    for i, c in enumerate(node.children[:-1])]
        else:  # nested combiners: per-request threads, deadlock-free
            results: List[Any] = [None] * (len(node.children) - 1)

            def run(i: int, c: GraphNode) -> None:
                try:
                    results[i] = ("ok", self._eval(c, payload, sub_routes[i]))
                except Exception as e:  # noqa: BLE001 — re-raised below
                    results[i] = ("err", e)

            threads = [threading.Thread(target=run, args=(i, c))
                       for i, c in enumerate(node.children[:-1])]
            for t in threads:
                t.start()
        last = self._eval(node.children[-1], payload, sub_routes[-1])
        if self._pool is not None:
            outs = [f.result() for f in futs] + [last]
        else:
            for t in threads:
                t.join()
            for tag, val in results:
                if tag == "err":
                    raise val
            outs = [val for _, val in results] + [last]
        for sub in sub_routes:
            route.extend(sub)
        return _combine(node.combine, outs)

    def _route(self, node: GraphNode) -> GraphNode:
        if node.strategy == "weights":
            names = [c.name for c in node.children]
            weights = [node.weights[n] for n in names]
            pick = self._rng.choices(names, weights=weights, k=1)[0]
        else:  # epsilon_greedy
            if self._rng.random() < node.epsilon:
                pick = self._rng.choice([c.name for c in node.children])
            else:
                pick = max(node.children,
                           key=lambda c: self.routers.mean_reward(
                               node.name, c.name)).name
        return next(c for c in node.children if c.name == pick)

    # -- feedback ----------------------------------------------------------

    def feedback(self, route: List[str], reward: float) -> int:
        """Credit a reward to every router decision on a taken route."""
        n = 0
        for hop in route:
            if "->" in hop:
                router, child = hop.split("->", 1)
                self.routers.record(router, child, reward)
                n += 1
        return n


def _as_input(out: Payload) -> Payload:
    """A stage's predictions become the next stage's instances."""
    if "predictions" in out:
        return {"instances": out["predictions"]}
    return out


def _combine(how: str, outs: List[Payload]) -> Payload:
    preds = [o.get("predictions") for o in outs]
    if any(p is None for p in preds):
        raise GraphError("combiner child returned no predictions")
    if how == "mean":
        import numpy as np

        arrs = [np.asarray(p, dtype=np.float32) for p in preds]
        shapes = {a.shape for a in arrs}
        if len(shapes) != 1:
            raise GraphError(f"combiner 'mean' shape mismatch: {shapes}")
        merged = np.mean(arrs, axis=0)
        return {"predictions": merged.tolist(),
                "combined_from": len(arrs)}
    # vote: per-instance argmax majority over children
    import numpy as np

    arrs = [np.asarray(p) for p in preds]
    if any(a.ndim != 2 for a in arrs):
        raise GraphError("combiner 'vote' needs (batch, classes) outputs")
    votes = np.stack([a.argmax(axis=-1) for a in arrs])  # (children, batch)
    n_classes = arrs[0].shape[-1]
    counts = np.apply_along_axis(
        lambda col: np.bincount(col, minlength=n_classes), 0, votes)
    return {"predictions": counts.argmax(axis=0).tolist(),
            "combined_from": len(arrs)}


# -- HTTP caller (production transport) ------------------------------------

class HttpNodeCaller:
    """node name → model-server URL; the in-cluster transport."""

    def __init__(self, backends: Dict[str, str], *,
                 timeout_s: float = 30.0) -> None:
        self.backends = {k: v.rstrip("/") for k, v in backends.items()}
        self.timeout_s = timeout_s

    def __call__(self, node: str, payload: Payload) -> Payload:
        base = self.backends.get(node)
        if base is None:
            raise GraphError(f"no backend configured for node {node!r}")
        url = f"{base}/v1/models/{node}:predict"
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            raise GraphError(f"node {node!r} returned {e.code}") from e
        except (urllib.error.URLError, OSError) as e:
            raise GraphError(f"node {node!r} unreachable: {e}") from e
