"""Reconcile loop: decisions → warmed, drained replica state.

The loop closes what Knative's KPA + activator pair does for the
reference platform: every tick it promotes finished warmups, retires
drained replicas, asks the recommender for a count, asks the planner
for concrete slices, and drives a :class:`ReplicaDriver` to make the
fleet match. Two ordering guarantees the serving tier depends on:

- **warm before admit** — a new replica is created, its compile/prefill
  warmup hook runs, and only a replica the driver reports warm counts
  as admitting capacity (``can_admit``). A cold TPU replica answering
  traffic would serve its first requests through XLA compiles.
- **drain before destroy** — scale-down marks a replica draining (no
  new admissions) and destroys it only once the driver reports zero
  in-flight work.

State transitions are synchronous inside ``reconcile`` and time is an
explicit parameter, so tests schedule bursts and idles deterministically.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

from kubeflow_tpu.autoscale.metrics import MetricsAggregator
from kubeflow_tpu.autoscale.planner import CapacityPlanner, Plan
from kubeflow_tpu.autoscale.policy import AutoscalePolicy
from kubeflow_tpu.autoscale.recommender import Decision, Recommender
from kubeflow_tpu.obs import Tracer
from kubeflow_tpu.scheduler.inventory import SliceInfo
from kubeflow_tpu.utils import DEFAULT_REGISTRY

_ready_g = DEFAULT_REGISTRY.gauge(
    "kftpu_autoscale_ready_replicas", "replicas warmed and admitting")
_warming_g = DEFAULT_REGISTRY.gauge(
    "kftpu_autoscale_warming_replicas", "replicas created but not warm")
_draining_g = DEFAULT_REGISTRY.gauge(
    "kftpu_autoscale_draining_replicas", "replicas draining before stop")

WARMING, READY, DRAINING = "warming", "ready", "draining"

log = logging.getLogger(__name__)


class ReplicaDriver:
    """How the autoscaler touches actual serving capacity.

    Subclasses bind the loop to a backend: stub replicas in tests, a
    Deployment-scaling driver on a cluster, in-process engines in dev.
    ``create`` may return any handle; the reconciler treats it opaquely.
    """

    def create(self, model: str, slice_id: str) -> Any:
        raise NotImplementedError

    def warmup(self, model: str, handle: Any) -> None:
        """Start the compile/prefill warmup for a fresh replica. May
        complete asynchronously; ``is_warm`` gates admission."""
        raise NotImplementedError

    def is_warm(self, model: str, handle: Any) -> bool:
        raise NotImplementedError

    def drain(self, model: str, handle: Any) -> None:
        """Stop routing new work to the replica (best-effort notify)."""

    def in_flight(self, model: str, handle: Any) -> int:
        """Requests still being served — 0 means safe to destroy."""
        return 0

    def destroy(self, model: str, handle: Any) -> None:
        raise NotImplementedError


@dataclasses.dataclass
class ReplicaState:
    handle: Any
    slice_id: str
    phase: str                  # WARMING | READY | DRAINING
    created_at: float
    warmed_at: Optional[float] = None


class _ModelLoop:
    def __init__(self, policy: AutoscalePolicy, model: str) -> None:
        self.policy = policy
        self.recommender = Recommender(policy, model)
        self.planner = CapacityPlanner(policy)
        self.replicas: List[ReplicaState] = []
        self.events: Deque[Tuple[float, str]] = collections.deque(maxlen=64)
        self.last_decision: Optional[Decision] = None
        self.last_plan: Optional[Plan] = None
        self.persisted_scale: Optional[int] = None


class Autoscaler:
    """One control loop over every served model.

    ``inventory`` is a zero-arg callable returning the scheduler's
    current free-slice scan (``GangScheduler.inventory(shape)`` bound on
    a cluster, a plain list in tests). ``registry`` (optional) is a
    :class:`~kubeflow_tpu.serving.registry.ModelRegistry`-shaped object
    whose ``set_scale`` persists the granted count, so the serving tier
    and dashboard read replica state from the same document the model's
    lifecycle stage lives in.
    """

    def __init__(self, policy: AutoscalePolicy, driver: ReplicaDriver,
                 aggregator: Optional[MetricsAggregator] = None, *,
                 inventory: Optional[
                     Callable[[], Sequence[SliceInfo]]] = None,
                 registry: Any = None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.policy = policy.validate()
        self.driver = driver
        self.clock = clock if clock is not None else time.monotonic
        self.aggregator = (aggregator if aggregator is not None
                           else MetricsAggregator(clock=self.clock))
        self.inventory = inventory if inventory is not None else (lambda: [])
        self.registry = registry
        # decision spans share the loop's clock: deterministic under the
        # fake clocks the autoscale tests drive
        self.tracer = Tracer(clock=self.clock)
        self._loops: Dict[str, _ModelLoop] = {}
        self._lock = threading.Lock()
        # fleet-edge wiring (docs/EDGE.md): model -> (edge, url_for),
        # per model like _loops — every reconcile tick adopts that
        # model's READY replica set into its edge's hash ring
        self._fleet: Dict[str, Tuple[Any, Any]] = {}

    def _loop(self, model: str) -> _ModelLoop:
        lp = self._loops.get(model)
        if lp is None:
            lp = self._loops[model] = _ModelLoop(self.policy, model)
        return lp

    # -- admission gate ------------------------------------------------------

    def can_admit(self, model: str) -> bool:
        """True when a warmed replica is accepting traffic. The proxy
        holds (503 + retry) requests for models where this is False —
        the activator role: a request against a scaled-to-zero model
        triggers scale-up via its telemetry and is admitted only once
        warmup finished."""
        with self._lock:
            lp = self._loops.get(model)
            if lp is None:
                return True  # model not autoscaled: never block traffic
            return any(r.phase == READY for r in lp.replicas)

    def watch(self, model: str) -> None:
        """Register a model with zero replicas (scale-from-zero start)."""
        with self._lock:
            self._loop(model)

    # -- the loop ------------------------------------------------------------

    def reconcile(self, model: str, now: Optional[float] = None) -> Decision:
        """One tick for one model. Returns the decision for observability."""
        now = self.clock() if now is None else now
        # sample current telemetry so idle seconds enter the windows
        self.aggregator.tick(model, now)
        stable, panic = self.aggregator.stats(model, self.policy, now)
        with self._lock:
            lp = self._loop(model)
            self._promote_and_retire(model, lp, now)
            active = [r for r in lp.replicas if r.phase != DRAINING]
            decision = lp.recommender.recommend(
                stable, panic, len(active), now)
            plan = lp.planner.plan(
                decision.desired,
                [r.slice_id for r in active],
                list(self.inventory()),
                busy=[r.slice_id for r in lp.replicas
                      if r.phase == DRAINING])
            self._apply(model, lp, plan, now)
            # a synchronous warmup (dev drivers, pre-warmed checkpoints)
            # may already be warm: promote in the same tick so the first
            # request isn't held a full reconcile interval for nothing.
            # Promotion only — a replica marked draining above must keep
            # a full tick between drain and destroy.
            self._promote(model, lp, now)
            lp.last_decision, lp.last_plan = decision, plan
            for msg in plan.events:
                lp.events.append((now, msg))
            self._export(model, lp)
        if self.registry is not None and lp.persisted_scale != plan.granted:
            try:
                self.registry.set_scale(model, plan.granted,
                                        reason=decision.reason)
                lp.persisted_scale = plan.granted
            except Exception:  # noqa: BLE001 — registry is observability,
                pass           # never fail the control loop on it
        # decision marker span: why the fleet changed (or didn't) at
        # this tick — the "p99 regressed, did we scale?" correlation
        self.tracer.record(
            "autoscale.reconcile", start=now, end=now,
            attrs={"model": model, "desired": decision.desired,
                   "granted": plan.granted, "panic": decision.panic,
                   "reason": decision.reason, "capped": plan.capped})
        self._sync_fleet(model)
        return decision

    def reconcile_all(self, now: Optional[float] = None) -> None:
        for model in sorted(set(self.aggregator.models())
                            | set(self._loops)):
            self.reconcile(model, now)

    # -- fleet-edge wiring (docs/EDGE.md) ------------------------------------

    def wire_fleet(self, edge: Any, model: str,
                   url_for: Optional[Callable[[str, str], str]] = None
                   ) -> None:
        """Adopt scale events into the fleet edge's hash ring on every
        reconcile tick — ROADMAP open item 5's missing wire: the
        ``FleetRouter.sync`` hook existed, nothing called it
        periodically. ``edge`` is anything with ``sync_replicas``
        (:class:`~kubeflow_tpu.edge.fleet.FleetEdge` — preferred, it
        also drops removed replicas' gate pressure) or a bare
        ``sync`` (:class:`~kubeflow_tpu.edge.fleet.FleetRouter`);
        ``url_for(model, slice_id)`` builds each replica's dispatch
        target (default: the replica name as a bare http host, the
        headless-Service DNS shape). Per-model, like the scaling loops
        themselves — wiring a second model never unwires the first;
        re-wiring the same model replaces its edge. Runs inside
        :meth:`reconcile`, so the ``build_controller`` periodic tick
        carries it — a scale event reaches the ring without any
        manual call."""
        with self._lock:
            self._fleet[model] = (edge, url_for)

    def _sync_fleet(self, model: str) -> None:
        with self._lock:
            wired = self._fleet.get(model)
            if wired is None:
                return
            edge, url_for = wired
            lp = self._loops.get(model)
            ready = [r.slice_id for r in (lp.replicas if lp else [])
                     if r.phase == READY]
        try:
            replicas = {}
            for slice_id in ready:
                name = f"{model}-{slice_id}"
                replicas[name] = (url_for(model, slice_id) if url_for
                                  else f"http://{name}")
            sync = getattr(edge, "sync_replicas", None)
            if sync is None:
                sync = edge.sync
            sync(replicas)
        except Exception:  # noqa: BLE001 — routing hygiene (including a
            # raising user url_for or a mis-shaped edge) must never fail
            # the scaling loop; the next tick retries
            log.exception("fleet ring sync failed for %s", model)

    def _promote(self, model: str, lp: _ModelLoop, now: float) -> None:
        for r in lp.replicas:
            if r.phase == WARMING and self.driver.is_warm(model, r.handle):
                r.phase = READY
                r.warmed_at = now
                lp.events.append(
                    (now, f"replica on {r.slice_id} warmed "
                          f"({now - r.created_at:.1f}s)"))

    def _promote_and_retire(self, model: str, lp: _ModelLoop,
                            now: float) -> None:
        self._promote(model, lp, now)
        done = [r for r in lp.replicas
                if r.phase == DRAINING
                and self.driver.in_flight(model, r.handle) == 0]
        for r in done:
            self.driver.destroy(model, r.handle)
            lp.replicas.remove(r)
            lp.events.append((now, f"replica on {r.slice_id} drained "
                                   "and destroyed"))

    def _apply(self, model: str, lp: _ModelLoop, plan: Plan,
               now: float) -> None:
        for slice_id in plan.grow:
            handle = self.driver.create(model, slice_id)
            self.driver.warmup(model, handle)
            lp.replicas.append(ReplicaState(
                handle=handle, slice_id=slice_id, phase=WARMING,
                created_at=now))
            lp.events.append((now, f"replica created on {slice_id}; "
                                   "warming"))
        shrink = set(plan.shrink)
        for r in lp.replicas:
            if r.slice_id in shrink and r.phase != DRAINING:
                r.phase = DRAINING
                self.driver.drain(model, r.handle)
                lp.events.append((now, f"replica on {r.slice_id} "
                                       "draining"))

    def _export(self, model: str, lp: _ModelLoop) -> None:
        counts = collections.Counter(r.phase for r in lp.replicas)
        _ready_g.set(counts[READY], model=model)
        _warming_g.set(counts[WARMING], model=model)
        _draining_g.set(counts[DRAINING], model=model)

    # -- runtime -------------------------------------------------------------

    def build_controller(self, interval_s: float = 2.0):
        """The autoscale tick on the shared workqueue runtime
        (:meth:`kubeflow_tpu.operators.controller.Controller.periodic`):
        one uniformly-traced ``controller.reconcile`` per tick instead
        of the hand-rolled ``while/sleep`` thread, so autoscaling shows
        up on the same trace/metric surface as the operators and the
        scheduler queue."""

        def tick(_ns: str, _name: str) -> float:
            self.reconcile_all()
            return interval_s

        from kubeflow_tpu.operators.controller import Controller

        return Controller.periodic(tick, name="autoscaler",
                                   tracer=self.tracer)

    # -- observability -------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The dashboard's ``GET /api/metrics/autoscale`` payload."""
        out: Dict[str, Any] = {"policy": dataclasses.asdict(self.policy),
                               "models": {}}
        with self._lock:
            for model, lp in sorted(self._loops.items()):
                counts = collections.Counter(
                    r.phase for r in lp.replicas)
                d, p = lp.last_decision, lp.last_plan
                out["models"][model] = {
                    "replicas": {
                        "ready": counts[READY],
                        "warming": counts[WARMING],
                        "draining": counts[DRAINING],
                    },
                    "slices": [
                        {"slice": r.slice_id, "phase": r.phase}
                        for r in lp.replicas],
                    "desired": d.desired if d else None,
                    "panic": d.panic if d else False,
                    "reason": d.reason if d else "",
                    "capped": p.capped if p else False,
                    "inflight": self.aggregator.inflight(model),
                    "events": [
                        {"t": round(t, 3), "message": m}
                        for t, m in list(lp.events)[-16:]],
                }
        return out
