"""Autoscale policy: the knobs of the control loop, with canned presets.

The reference gets these from Knative KPA annotations
(``autoscaling.knative.dev/target``, ``targetBurstCapacity``, panic
window percentage...); here the same dials are one frozen dataclass a
deployment preset or the ``autoscaler`` manifest component fills in.
Windows follow the KPA split: a long *stable* window for steady-state
decisions and a short *panic* window so a burst is seen within seconds,
not after a minute of averaging.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Mapping, Optional

# the injectable-clock contract (re-exported from its neutral home so
# autoscale callers keep importing it from the subsystem that set the
# convention; tpulint TPU003 enforces it repo-wide)
from kubeflow_tpu.utils.clock import Clock, Sleep  # noqa: F401


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    # steady-state in-flight requests one replica is expected to absorb
    # (Knative's autoscaling.knative.dev/target). For the decode engine
    # this is slot occupancy, so target ≈ slots keeps replicas saturated.
    target_concurrency: float = 4.0
    # sliding-window lengths; the panic window is short so one reconcile
    # tick inside a burst already sees the spike (KPA default is 10% of
    # the stable window)
    stable_window_s: float = 60.0
    panic_window_s: float = 6.0
    # enter panic when the panic-window desired count reaches this
    # multiple of the current ready capacity (KPA panic-threshold 200%)
    panic_threshold: float = 2.0
    # per-decision rate bounds: never grow by more than x`up` or shrink
    # by more than ÷`down` in one tick (ready>0); bounds oscillation
    max_scale_up_rate: float = 10.0
    max_scale_down_rate: float = 2.0
    # hysteresis: desired must stay below current for this long before a
    # scale-down is applied (prevents flapping around a step edge)
    scale_down_delay_s: float = 30.0
    # idle duration (zero concurrency AND empty queue) before dropping
    # to zero replicas; only honored when min_replicas == 0
    scale_to_zero_grace_s: float = 30.0
    min_replicas: int = 0
    max_replicas: int = 32
    # TPU slice shape each replica occupies (platform.slices name, e.g.
    # "v5e-4"); the planner turns replica counts into whole slices
    slice_shape: str = "v5e-4"
    # round scale-ups to power-of-two replica counts when inventory
    # allows: compiled-program buckets and mesh shapes are pow2, so
    # pow2 fleets keep serving shards uniform
    pow2_packing: bool = True

    def validate(self) -> "AutoscalePolicy":
        if self.target_concurrency <= 0:
            raise ValueError("target_concurrency must be > 0")
        if not 0 < self.panic_window_s <= self.stable_window_s:
            raise ValueError(
                "need 0 < panic_window_s <= stable_window_s, got "
                f"{self.panic_window_s} / {self.stable_window_s}")
        if self.panic_threshold < 1.0:
            raise ValueError("panic_threshold must be >= 1.0")
        if not 0 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 0 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas} / {self.max_replicas}")
        return self


# canned profiles, mirroring config/presets.py's deployment presets:
# - serving: the default latency-first loop (scale up fast, down slow)
# - batch: throughput-first — replicas run hot, bursts tolerated longer
# - dev: one small slice, aggressive scale-to-zero for shared dev pools
POLICY_PRESETS: Dict[str, AutoscalePolicy] = {
    "serving": AutoscalePolicy(),
    "batch": AutoscalePolicy(
        target_concurrency=16.0,
        panic_threshold=4.0,
        scale_down_delay_s=120.0,
        scale_to_zero_grace_s=300.0,
    ),
    "dev": AutoscalePolicy(
        target_concurrency=2.0,
        max_replicas=2,
        scale_down_delay_s=10.0,
        scale_to_zero_grace_s=10.0,
        pow2_packing=False,
    ),
}


def policy_preset(name: str) -> AutoscalePolicy:
    if name not in POLICY_PRESETS:
        known = ", ".join(sorted(POLICY_PRESETS))
        raise KeyError(f"unknown autoscale policy {name!r}; known: {known}")
    return POLICY_PRESETS[name]


def policy_from_env(env: Optional[Mapping[str, str]] = None) -> AutoscalePolicy:
    """Resolve the policy the manifest component configures via env:
    ``KFTPU_AUTOSCALE_POLICY`` names a preset, individual
    ``KFTPU_AUTOSCALE_*`` vars override single fields."""
    e = os.environ if env is None else env
    base = policy_preset(e.get("KFTPU_AUTOSCALE_POLICY", "serving"))
    overrides = {}
    for field in dataclasses.fields(AutoscalePolicy):
        var = f"KFTPU_AUTOSCALE_{field.name.upper()}"
        if var not in e:
            continue
        raw = e[var]
        if field.type == "bool":
            overrides[field.name] = raw.lower() in ("1", "true", "yes")
        elif field.type == "int":
            overrides[field.name] = int(raw)
        elif field.type == "float":
            overrides[field.name] = float(raw)
        else:
            overrides[field.name] = raw
    return dataclasses.replace(base, **overrides).validate()
