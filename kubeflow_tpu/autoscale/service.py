"""Autoscaler service: REST surface + the periodic reconcile thread.

The deployable face of the subsystem (``manifests/components/
autoscaler.py`` runs this module). Routes:

- ``GET  /healthz``
- ``GET  /api/autoscale/status``          — full loop state (dashboard view)
- ``GET  /api/autoscale/can_admit?model=m`` — the remote activator
  gate: True when a warmed replica is admitting (the proxy's
  ``RemoteAdmitGate`` polls this, cached, failing open);
- ``POST /api/autoscale/report``          — remote telemetry: the proxy
  (or any frontend) posts ``{"model": m, "event": "start"|"finish"}``
  per request, engines post ``{"model": m, "event": "observe",
  "queueDepth": q, "activeSlots": a}`` — the cross-pod equivalent of
  handing the in-process aggregator to the proxy constructor;
- ``POST /api/autoscale/watch``           — register a model at zero
  replicas so scale-from-zero has a loop to wake.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional, Tuple

from kubeflow_tpu.autoscale.reconciler import Autoscaler

log = logging.getLogger(__name__)


class AutoscaleService:
    def __init__(self, autoscaler: Autoscaler) -> None:
        self.autoscaler = autoscaler

    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str = "") -> Tuple[int, Any]:
        if method == "GET" and path == "/healthz":
            return 200, {"ok": True}
        if method == "GET" and path == "/api/autoscale/status":
            return 200, self.autoscaler.status()
        if method == "GET" and path.startswith("/api/autoscale/can_admit"):
            from urllib.parse import parse_qsl, urlsplit

            q = dict(parse_qsl(urlsplit(path).query))
            model = q.get("model", "")
            if not model:
                return 400, {"error": "can_admit needs ?model="}
            return 200, {"model": model,
                         "canAdmit": self.autoscaler.can_admit(model)}
        if method == "POST" and path == "/api/autoscale/watch":
            model = (body or {}).get("model", "")
            if not model:
                return 400, {"error": "body needs 'model'"}
            self.autoscaler.watch(model)
            return 200, {"watching": model}
        if method == "POST" and path == "/api/autoscale/report":
            return self._report(body or {})
        return 404, {"error": "unknown endpoint"}

    def _report(self, body: Dict[str, Any]) -> Tuple[int, Any]:
        model = body.get("model", "")
        event = body.get("event", "")
        if not model:
            return 400, {"error": "body needs 'model'"}
        agg = self.autoscaler.aggregator
        if event == "start":
            agg.request_start(model)
        elif event == "finish":
            agg.request_finish(model)
        elif event == "observe":
            agg.observe(model,
                        queue_depth=float(body.get("queueDepth", 0.0)),
                        active_slots=(
                            float(body["activeSlots"])
                            if "activeSlots" in body else None))
        else:
            return 400, {"error": f"unknown event {event!r}; valid: "
                                  "start, finish, observe"}
        return 200, {"ok": True}


class _StopEvent(threading.Event):
    """An Event whose ``set()`` also stops the controller and releases
    ``join`` waiters — keeps the old ``handle.stop.set()`` thread
    contract over the runtime lift."""

    def __init__(self, controller, done: threading.Event) -> None:
        super().__init__()
        self._controller = controller
        self._done = done

    def set(self) -> None:  # noqa: A003 — threading.Event API
        super().set()
        self._controller.stop()
        self._done.set()


class _LoopHandle:
    """:func:`run_loop`'s return: looks enough like the old Thread
    (``.stop`` event, ``.join(timeout)`` that *waits*, not kills) that
    callers keep working, but the loop underneath is a periodic
    Controller on the shared runtime."""

    def __init__(self, controller) -> None:
        self.controller = controller
        self._done = threading.Event()
        self.stop = _StopEvent(controller, self._done)

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait (bounded) for the loop to be stopped — the old daemon-
        Thread semantics: joining never terminates the loop itself."""
        self._done.wait(timeout)


def run_loop(autoscaler: Autoscaler, interval_s: float,
             stop: Optional[threading.Event] = None) -> _LoopHandle:
    """Reconcile every model each ``interval_s`` until stopped.

    Runs on the shared workqueue runtime
    (:meth:`~kubeflow_tpu.operators.controller.Controller.periodic`)
    rather than a hand-rolled sleep thread: ticks are deduplicated,
    single-flight, uniformly traced reconciles — and a tick that throws
    is logged by the runtime while the loop lives on, exactly the old
    contract. Stop via the returned handle's ``.stop.set()`` (or pass
    your own ``stop`` Event and set it)."""
    ctrl = autoscaler.build_controller(interval_s=interval_s)
    handle = _LoopHandle(ctrl)
    if stop is not None:
        if stop.is_set():
            # the old `while not stop.wait(...)` loop exited before its
            # first tick on a pre-set Event; never start the controller
            handle._done.set()
            return handle
        # honor a caller-owned Event: chain its set() to the controller
        orig_set = stop.set

        def chained() -> None:
            orig_set()
            handle.stop.set()

        stop.set = chained  # type: ignore[method-assign]
    ctrl.start()
    return handle


def main() -> None:  # pragma: no cover - container entrypoint
    import os

    from kubeflow_tpu.autoscale.policy import policy_from_env
    from kubeflow_tpu.autoscale.reconciler import ReplicaDriver
    from kubeflow_tpu.k8s.client import HttpKubeClient
    from kubeflow_tpu.scheduler.inventory import GangScheduler
    from kubeflow_tpu.serving.registry import ENV_REGISTRY_DIR, ModelRegistry
    from kubeflow_tpu.utils.jsonhttp import serve_json

    policy = policy_from_env()
    client = HttpKubeClient()
    scheduler = GangScheduler(client)

    class DeploymentDriver(ReplicaDriver):
        """Scales ONE serving Deployment by patching spec.replicas; a
        replica is warm once the Deployment's ready count covers every
        live handle (the server's own startup warmup gates readiness).
        One Deployment per driver: point KFTPU_AUTOSCALE_MODELS at the
        single model this Deployment serves."""

        def __init__(self) -> None:
            self.ns = os.environ.get("KFTPU_NAMESPACE", "kubeflow")
            self.deploy = os.environ.get("KFTPU_AUTOSCALE_TARGET",
                                         "model-server-v1")
            # live handles, not a monotonic counter: readiness compares
            # against the CURRENT fleet size, so a grow overlapping a
            # drain can't demand more ready pods than spec.replicas
            self._handles: set = set()
            self._seq = 0

        def _patch(self) -> None:
            obj = client.get("apps/v1", "Deployment", self.ns, self.deploy)
            obj["spec"]["replicas"] = len(self._handles)
            client.update(obj)

        def create(self, model: str, slice_id: str) -> int:
            self._seq += 1
            self._handles.add(self._seq)
            self._patch()
            return self._seq

        def warmup(self, model: str, handle: int) -> None:
            pass  # pod startup runs the server's compile warmup

        def is_warm(self, model: str, handle: int) -> bool:
            obj = client.get("apps/v1", "Deployment", self.ns, self.deploy)
            ready = (obj.get("status", {}) or {}).get("readyReplicas", 0)
            return int(ready or 0) >= len(self._handles)

        def destroy(self, model: str, handle: int) -> None:
            self._handles.discard(handle)
            self._patch()

    registry = None
    reg_dir = os.environ.get(ENV_REGISTRY_DIR)
    if reg_dir:
        registry = ModelRegistry(reg_dir)
    autoscaler = Autoscaler(
        policy, DeploymentDriver(),
        inventory=lambda: scheduler.inventory(policy.slice_shape),
        registry=registry)
    for model in os.environ.get("KFTPU_AUTOSCALE_MODELS", "").split(","):
        if model.strip():
            autoscaler.watch(model.strip())
    run_loop(autoscaler,
             float(os.environ.get("KFTPU_AUTOSCALE_INTERVAL_S", "2.0")))
    serve_json(AutoscaleService(autoscaler).handle,
               int(os.environ.get("KFTPU_AUTOSCALE_PORT", "8090")))


if __name__ == "__main__":  # pragma: no cover
    main()
