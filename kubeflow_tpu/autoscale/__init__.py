"""TPU-slice-aware serving autoscaler.

Closes the loop the reference platform delegates to Knative/KFServing's
concurrency-based pod autoscaler: request telemetry (proxy + decode
engine) → sliding stable/panic windows (:mod:`metrics`) → desired
replica count with burst panic, hysteresis and scale-to-zero
(:mod:`recommender`) → concrete TPU slices against the scheduler's
inventory (:mod:`planner`) → warmed, drained replica state
(:mod:`reconciler`). Everything takes an injectable clock so tests are
wall-clock-free.
"""

from kubeflow_tpu.autoscale.metrics import (  # noqa: F401
    MetricsAggregator,
    WindowStats,
)
from kubeflow_tpu.autoscale.planner import (  # noqa: F401
    CapacityPlanner,
    Plan,
)
from kubeflow_tpu.autoscale.policy import (  # noqa: F401
    POLICY_PRESETS,
    AutoscalePolicy,
    Clock,
    Sleep,
    policy_preset,
)
from kubeflow_tpu.autoscale.recommender import (  # noqa: F401
    Decision,
    Recommender,
)
from kubeflow_tpu.autoscale.reconciler import (  # noqa: F401
    Autoscaler,
    ReplicaDriver,
    ReplicaState,
)
