"""Per-model request telemetry over sliding stable/panic windows.

The Knative-KPA shape: the autoscaler does not see raw requests, it
sees windowed averages of *concurrency* (in-flight requests), queue
depth and arrival rate. Sources:

- the serving proxy reports every request's start/finish
  (:meth:`MetricsAggregator.request_start` / ``request_finish``) — the
  concurrency signal;
- the reconcile loop polls each model's decode engines and reports slot
  occupancy + admission-queue depth (:meth:`observe_engine`) — the
  saturation signal batching hides from per-request concurrency.

Time is injectable (``clock`` callable or explicit ``now=`` on every
call): tests drive a fake clock, production passes nothing and gets
``time.monotonic``. Samples land in one-second buckets; a window stat
is the average over the buckets it covers, so the math is deterministic
for a deterministic event schedule.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Deque, Dict, Optional, Tuple

from kubeflow_tpu.utils import DEFAULT_REGISTRY

_inflight_g = DEFAULT_REGISTRY.gauge(
    "kftpu_autoscale_inflight", "in-flight requests seen by the autoscaler")
_rps_g = DEFAULT_REGISTRY.gauge(
    "kftpu_autoscale_stable_rps", "stable-window requests per second")


@dataclasses.dataclass(frozen=True)
class WindowStats:
    """Aggregates over one sliding window."""

    concurrency: float      # avg in-flight requests (incl. engine slots)
    queue_depth: float      # avg requests waiting for an engine slot
    rps: float              # arrivals per second
    samples: int            # concurrency samples the average is over

    @property
    def load(self) -> float:
        """The signal the recommender divides by target concurrency:
        requests being served plus requests waiting to be served."""
        return self.concurrency + self.queue_depth


@dataclasses.dataclass
class _Bucket:
    second: int
    conc_sum: float = 0.0
    conc_n: int = 0
    queue_sum: float = 0.0
    queue_n: int = 0
    starts: int = 0


class _ModelSeries:
    """Ring of per-second buckets + the live in-flight gauge."""

    def __init__(self, horizon_s: float) -> None:
        self.horizon_s = horizon_s
        self.inflight = 0
        self.buckets: Deque[_Bucket] = collections.deque()

    def bucket(self, now: float) -> _Bucket:
        sec = int(now)
        if self.buckets and self.buckets[-1].second == sec:
            return self.buckets[-1]
        b = _Bucket(second=sec)
        self.buckets.append(b)
        while self.buckets and self.buckets[0].second < sec - self.horizon_s:
            self.buckets.popleft()
        return b

    def sample(self, now: float) -> None:
        b = self.bucket(now)
        b.conc_sum += self.inflight
        b.conc_n += 1

    def window(self, window_s: float, now: float) -> WindowStats:
        lo = now - window_s
        conc_sum = conc_n = 0.0
        q_sum = q_n = 0.0
        starts = 0
        for b in self.buckets:
            if b.second < lo or b.second > now:
                continue
            conc_sum += b.conc_sum
            conc_n += b.conc_n
            q_sum += b.queue_sum
            q_n += b.queue_n
            starts += b.starts
        # an empty window means nothing happened: the in-flight gauge is
        # still authoritative (a long-running request with no events in
        # the window must not read as idle)
        conc = conc_sum / conc_n if conc_n else float(self.inflight)
        queue = q_sum / q_n if q_n else 0.0
        return WindowStats(concurrency=conc, queue_depth=queue,
                           rps=starts / window_s if window_s > 0 else 0.0,
                           samples=int(conc_n))


class MetricsAggregator:
    """Thread-safe telemetry sink shared by proxy and reconcile loop."""

    def __init__(self, *, horizon_s: float = 120.0,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.horizon_s = horizon_s
        self.clock = clock if clock is not None else time.monotonic
        self._series: Dict[str, _ModelSeries] = {}
        self._lock = threading.Lock()

    def _get(self, model: str) -> _ModelSeries:
        s = self._series.get(model)
        if s is None:
            s = self._series[model] = _ModelSeries(self.horizon_s)
        return s

    # -- proxy-facing --------------------------------------------------------

    def request_start(self, model: str,
                      now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        with self._lock:
            s = self._get(model)
            s.inflight += 1
            s.bucket(now).starts += 1
            s.sample(now)
        _inflight_g.set(s.inflight, model=model)

    def request_finish(self, model: str,
                       now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        with self._lock:
            s = self._get(model)
            s.inflight = max(0, s.inflight - 1)
            s.sample(now)
        _inflight_g.set(s.inflight, model=model)

    # -- reconcile-loop-facing ----------------------------------------------

    def observe(self, model: str, *, queue_depth: float = 0.0,
                active_slots: Optional[float] = None,
                now: Optional[float] = None) -> None:
        """Record one poll of a model's serving backend: admission-queue
        depth and (optionally) engine slot occupancy. Occupancy counts
        toward concurrency — continuous batching serves many streams off
        few HTTP requests, so proxy-side in-flight alone undercounts."""
        now = self.clock() if now is None else now
        with self._lock:
            s = self._get(model)
            b = s.bucket(now)
            b.queue_sum += float(queue_depth)
            b.queue_n += 1
            if active_slots is not None:
                b.conc_sum += float(active_slots)
                b.conc_n += 1
            else:
                s.sample(now)

    def observe_engine(self, model: str, engine,
                       now: Optional[float] = None) -> None:
        """Poll a :class:`~kubeflow_tpu.serving.engine.DecodeEngine`
        (or a :class:`~kubeflow_tpu.serving.multiplex.ModelMultiplexer`
        wrapping one — its snapshot is an engine-snapshot superset).

        Paged engines report their page pool (``pages_total`` /
        ``pages_free``): token-level occupancy. A few long-context
        streams can exhaust KV pages while most slots sit free, so the
        concurrency signal is the WORSE of slot occupancy and page
        occupancy scaled to slot units — scale decisions then track
        tokens, not just row count.

        Multiplexed backends additionally report model-occupancy
        (``models_resident`` / ``models_max``): resident-weight
        pressure. A backend whose weight pager is thrashing needs
        capacity even with KV pages free, so the same worse-of fold
        applies — idle resident models (``models_evictable``) are
        reclaimable cache, not load, exactly like evictable prefix
        pages."""
        snap = engine.snapshot()
        active = float(snap["active_slots"])
        pages_total = float(snap.get("pages_total") or 0.0)
        if pages_total > 0:
            # evictable prefix-store pins are reclaimable cache, not
            # load — an idle engine with a warm prefix cache must read
            # as idle or it can never scale in
            held = (pages_total - float(snap.get("pages_free", 0.0))
                    - float(snap.get("pages_evictable", 0.0)))
            util = max(0.0, held) / pages_total
            active = max(active, util * float(snap.get("slots", 0.0)))
        models_max = float(snap.get("models_max") or 0.0)
        if models_max > 0:
            held_m = (float(snap.get("models_resident", 0.0))
                      + float(snap.get("models_loading", 0.0))
                      - float(snap.get("models_evictable", 0.0)))
            util_m = max(0.0, held_m) / models_max
            # slot units when an engine is attached; the pager's own
            # capacity otherwise (a standalone multiplexer still has to
            # produce a non-zero signal)
            unit = float(snap.get("slots") or 0.0) or models_max
            active = max(active, util_m * unit)
        self.observe(model, queue_depth=snap["pending"],
                     active_slots=active, now=now)

    def tick(self, model: str, now: Optional[float] = None) -> None:
        """Record a no-event sample so idle seconds read as zero load
        instead of carrying the last busy bucket forward."""
        now = self.clock() if now is None else now
        with self._lock:
            self._get(model).sample(now)

    # -- read path -----------------------------------------------------------

    def models(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._series))

    def inflight(self, model: str) -> int:
        with self._lock:
            return self._get(model).inflight

    def window(self, model: str, window_s: float,
               now: Optional[float] = None) -> WindowStats:
        now = self.clock() if now is None else now
        with self._lock:
            return self._get(model).window(window_s, now)

    def stats(self, model: str, policy,
              now: Optional[float] = None) -> Tuple[WindowStats,
                                                    WindowStats]:
        """(stable, panic) window stats under one clock read."""
        now = self.clock() if now is None else now
        with self._lock:
            s = self._get(model)
            stable = s.window(policy.stable_window_s, now)
            panic = s.window(policy.panic_window_s, now)
        _rps_g.set(stable.rps, model=model)
        return stable, panic
