"""Desired replicas → concrete TPU slices against the scheduler inventory.

A serving replica is not a pod on an arbitrary node: it occupies one
whole TPU slice of the policy's shape (``platform.slices``), so the
planner is the bridge between the recommender's integer and
``scheduler/inventory.py``'s concrete free-slice accounting. Selection
reuses the gang scheduler's best-fit + adjacency scoring
(:func:`~kubeflow_tpu.scheduler.inventory.choose_slices`), one slice
per replica; replica counts prefer power-of-two packing (uniform
compiled-program buckets across the fleet) and degrade gracefully —
when inventory can't cover the ask, the planner grants what fits and
reports the shortfall as an event instead of failing the loop
(contention-aware degradation, PAPERS: Scheduling Ring-All-Reduce Jobs
in Multi-Tenant Clusters).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from kubeflow_tpu.autoscale.policy import AutoscalePolicy
from kubeflow_tpu.platform.slices import slice_shape
from kubeflow_tpu.scheduler.inventory import SliceInfo, choose_slices
from kubeflow_tpu.utils import DEFAULT_REGISTRY

_capped_c = DEFAULT_REGISTRY.counter(
    "kftpu_autoscale_inventory_capped_total",
    "scale-ups granted only partially because slice inventory ran out")


def pow2_ceil(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass(frozen=True)
class Plan:
    """Concrete outcome of one planning pass."""

    desired: int            # what the recommender asked for
    granted: int            # replicas the fleet should actually run
    grow: List[str]         # slice ids to start new replicas on
    shrink: List[str]       # slice ids whose replicas should drain
    capped: bool            # True when inventory cut the ask short
    events: List[str]


class CapacityPlanner:
    def __init__(self, policy: AutoscalePolicy) -> None:
        self.policy = policy.validate()
        self.shape = slice_shape(policy.slice_shape)

    def plan(self, desired: int, assigned: Sequence[str],
             inventory: Sequence[SliceInfo],
             busy: Sequence[str] = ()) -> Plan:
        """Round ``desired`` against what the cluster can actually hold.

        ``assigned`` — slice ids current replicas occupy (ready or
        warming), in age order (oldest first).  ``inventory`` — the
        scheduler's free-slice scan for the policy shape; slices in
        ``assigned`` are counted as ours even though the scan reports
        them busy. ``busy`` — slice ids that must not be granted even
        if the scan says they are free: a *draining* replica still owns
        its slice until it is destroyed, and an inventory scan that
        races the teardown would double-book it.
        """
        events: List[str] = []
        current = len(assigned)
        target = desired
        if self.policy.pow2_packing and desired > current:
            target = min(pow2_ceil(desired), self.policy.max_replicas)
            if target != desired:
                events.append(
                    f"pow2 packing: rounding {desired} -> {target}")

        if target <= current:
            # shrink newest-first: oldest replicas hold the warmed
            # compiled-program caches worth keeping
            shrink = list(assigned[target:])
            return Plan(desired=desired, granted=target, grow=[],
                        shrink=shrink, capped=False, events=events)

        want_new = target - current
        grow = self._select(want_new, assigned, inventory, busy)
        if len(grow) < want_new and target > desired:
            # pow2 round-up didn't fit — retry at the raw ask before
            # declaring the scale-up capped
            events.append("pow2 target missed inventory; "
                          f"retrying at {desired}")
            target = desired
            want_new = max(target - current, 0)
            grow = self._select(want_new, assigned, inventory, busy)
        capped = len(grow) < want_new
        if capped:
            _capped_c.inc(shape=self.shape.name)
            events.append(
                f"slice inventory exhausted: granted {len(grow)} of "
                f"{want_new} new {self.shape.name} replicas")
        return Plan(desired=desired, granted=current + len(grow),
                    grow=grow, shrink=[], capped=capped, events=events)

    def _select(self, want: int, assigned: Sequence[str],
                inventory: Sequence[SliceInfo],
                busy: Sequence[str] = ()) -> List[str]:
        """Up to ``want`` free slice ids, best-fit-scored, largest
        feasible count first (graceful degradation)."""
        if want <= 0:
            return []
        ours = set(assigned) | set(busy)
        free = [s for s in inventory
                if s.slice_id not in ours and s.free_hosts == s.hosts
                and s.hosts >= self.shape.hosts]
        if not free:
            return []
        hosts = [s.hosts for s in free]
        free_hosts = [s.free_hosts for s in free]
        for k in range(min(want, len(free)), 0, -1):
            chosen = choose_slices(hosts, free_hosts, k, self.shape.hosts)
            if chosen is not None:
                return [free[i].slice_id for i in chosen]
        return []
