"""Window stats → desired replica count (Knative-KPA decision logic).

Stable mode sizes the fleet from the long window; a burst that pushes
the short panic window past ``panic_threshold``× current capacity flips
the recommender into panic mode, where it scales straight to the panic
demand and refuses to scale down until the burst has been quiet for a
full stable window. Scale-down is additionally delayed
(``scale_down_delay_s`` hysteresis), and an idle model (no load, empty
queue) drops to zero only after ``scale_to_zero_grace_s`` — the related
scheduling work (PAPERS: Prediction-Assisted Online DL Workload
Scheduling) motivates exactly this asymmetry: react to demand in one
short window, release capacity slowly enough that prediction error
never thrashes slices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

from kubeflow_tpu.autoscale.metrics import WindowStats
from kubeflow_tpu.autoscale.policy import AutoscalePolicy
from kubeflow_tpu.utils import DEFAULT_REGISTRY

_desired_g = DEFAULT_REGISTRY.gauge(
    "kftpu_autoscale_desired_replicas", "recommender desired replicas")
_panic_g = DEFAULT_REGISTRY.gauge(
    "kftpu_autoscale_panic_mode", "1 while the recommender is in panic")


@dataclasses.dataclass(frozen=True)
class Decision:
    desired: int
    panic: bool
    reason: str


class Recommender:
    """Per-model decision state machine. Not thread-safe on its own —
    the reconciler serializes calls (one control loop per model)."""

    def __init__(self, policy: AutoscalePolicy, model: str = "") -> None:
        self.policy = policy.validate()
        self.model = model or "model"
        self.panic_mode = False
        # last instant the panic condition held (panic exit requires a
        # stable window of quiet after this)
        self._panic_until: float = 0.0
        # highest desired seen during the current panic — panic never
        # scales down, even if the burst sags mid-panic
        self._panic_high: int = 0
        # when `desired < current` started holding (hysteresis anchor)
        self._below_since: Optional[float] = None
        self._idle_since: Optional[float] = None

    def _raw_desired(self, stats: WindowStats) -> int:
        return int(math.ceil(stats.load / self.policy.target_concurrency))

    def recommend(self, stable: WindowStats, panic: WindowStats,
                  current: int, now: float) -> Decision:
        """One decision tick.

        ``current`` is the replica count the fleet is actually at
        (ready + warming): rate limits and the panic threshold are
        relative to real capacity, not to a prior recommendation.
        """
        p = self.policy
        want_stable = self._raw_desired(stable)
        want_panic = self._raw_desired(panic)

        # -- panic entry/exit ------------------------------------------------
        # capacity the panic demand is compared against; at zero
        # replicas any demand is a panic (cold-start burst)
        threshold = max(current, 1) * p.panic_threshold
        if want_panic >= threshold and panic.load > 0:
            self._panic_until = now + p.stable_window_s
            if not self.panic_mode:
                self.panic_mode = True
                self._panic_high = 0
        elif self.panic_mode and now >= self._panic_until:
            self.panic_mode = False
            self._panic_high = 0

        if self.panic_mode:
            desired = max(want_panic, current, self._panic_high)
            self._panic_high = desired
            reason = (f"panic: window load {panic.load:.1f} needs "
                      f"{want_panic} replicas (have {current})")
        else:
            desired = want_stable
            reason = (f"stable: window load {stable.load:.1f} / target "
                      f"{p.target_concurrency:g}")

        # -- scale to zero ----------------------------------------------------
        # an idle model heads to zero only after the grace period; until
        # then at least one replica stays (Knative's grace window). The
        # grace-ok zero bypasses rate limits and hysteresis below (both
        # only act on desired > 0).
        idle = stable.load <= 0 and panic.load <= 0
        if idle and not self.panic_mode:
            if self._idle_since is None:
                self._idle_since = now
            if (p.min_replicas == 0
                    and now - self._idle_since >= p.scale_to_zero_grace_s):
                desired = 0
                reason = (f"idle {now - self._idle_since:.0f}s >= grace "
                          f"{p.scale_to_zero_grace_s:g}s: scale to zero")
            elif desired == 0 and current > 0:
                desired = 1
                reason += " (scale-to-zero grace pending)"
        else:
            self._idle_since = None

        # -- rate limits ------------------------------------------------------
        if current > 0 and desired > 0:
            up_cap = max(int(math.floor(current * p.max_scale_up_rate)),
                         current + 1)
            down_cap = int(math.floor(current / p.max_scale_down_rate))
            if desired > up_cap:
                desired, reason = up_cap, reason + " (rate-limited up)"
            if desired < down_cap:
                desired, reason = down_cap, reason + " (rate-limited down)"

        # -- scale-down hysteresis -------------------------------------------
        if 0 < desired < current:
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since < p.scale_down_delay_s:
                desired = current
                reason += " (scale-down held)"
        elif desired >= current:
            self._below_since = None

        desired = min(max(desired, p.min_replicas), p.max_replicas)
        _desired_g.set(desired, model=self.model)
        _panic_g.set(1.0 if self.panic_mode else 0.0, model=self.model)
        return Decision(desired=desired, panic=self.panic_mode,
                        reason=reason)
