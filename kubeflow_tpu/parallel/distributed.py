"""Multi-host bootstrap: the TPU-native replacement for TF_CONFIG / hostfiles.

The reference wires distributed jobs through environment protocols the
operator injects: ``TF_CONFIG`` JSON for PS jobs (consumed at
``/root/reference/tf-controller-examples/tf-cnn/launcher.py:68-80``), MPI
hostfiles + kubectl-delivery for MPIJob
(``/root/reference/kubeflow/mpi-job/mpi-operator.libsonnet:287-289``), and
MASTER_ADDR env for DDP. Here a single env contract carries the JAX
coordinator address; XLA wires collectives over ICI within a slice and DCN
across slices — no ssh, no hostfile, no driver DaemonSet.

Env contract (injected by the TpuJob operator, see
``kubeflow_tpu/operators/tpujob.py``):

- ``KFTPU_COORDINATOR_ADDRESS``  host:port of process 0 (headless Service)
- ``KFTPU_NUM_PROCESSES``        total host processes in the job
- ``KFTPU_PROCESS_ID``           this process's rank
- ``KFTPU_JOB_NAME`` / ``KFTPU_NAMESPACE``  identity, for logging/metrics
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Optional

log = logging.getLogger(__name__)

ENV_COORDINATOR = "KFTPU_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "KFTPU_NUM_PROCESSES"
ENV_PROCESS_ID = "KFTPU_PROCESS_ID"
ENV_JOB_NAME = "KFTPU_JOB_NAME"
ENV_NAMESPACE = "KFTPU_NAMESPACE"


@dataclasses.dataclass(frozen=True)
class ProcessEnv:
    """Parsed view of the operator-injected distributed environment."""

    coordinator_address: Optional[str]
    num_processes: int
    process_id: int
    job_name: str = ""
    namespace: str = "default"

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def from_env(environ=None) -> ProcessEnv:
    env = os.environ if environ is None else environ
    return ProcessEnv(
        coordinator_address=env.get(ENV_COORDINATOR),
        num_processes=int(env.get(ENV_NUM_PROCESSES, "1")),
        process_id=int(env.get(ENV_PROCESS_ID, "0")),
        job_name=env.get(ENV_JOB_NAME, ""),
        namespace=env.get(ENV_NAMESPACE, "default"),
    )


def initialize(
    penv: Optional[ProcessEnv] = None,
    *,
    timeout_s: float = 300.0,
    retry_interval_s: float = 5.0,
) -> ProcessEnv:
    """Call ``jax.distributed.initialize`` from the env contract, with retries.

    The reference's TF_CONFIG was static — every process could start in any
    order because PS/gRPC reconnected forever. JAX's coordinator (process 0)
    must be reachable first, so non-zero ranks retry with backoff until the
    coordinator's Service resolves (SURVEY.md §7 "hard parts" (c)).
    Single-process jobs return immediately without touching jax.distributed.
    """
    penv = penv or from_env()
    if not penv.is_distributed:
        log.info("single-process job; skipping jax.distributed")
        return penv
    if not penv.coordinator_address:
        raise RuntimeError(
            f"{ENV_NUM_PROCESSES}>1 but {ENV_COORDINATOR} is not set"
        )
    import jax

    deadline = time.monotonic() + timeout_s
    attempt = 0
    while True:
        attempt += 1
        remaining = max(deadline - time.monotonic(), retry_interval_s)
        try:
            jax.distributed.initialize(
                coordinator_address=penv.coordinator_address,
                num_processes=penv.num_processes,
                process_id=penv.process_id,
                initialization_timeout=int(remaining),
            )
            log.info(
                "jax.distributed up: rank %d/%d via %s",
                penv.process_id, penv.num_processes, penv.coordinator_address,
            )
            return penv
        except Exception as e:  # noqa: BLE001 — grpc raises various types
            # jax assigns its global distributed client before connect(), so
            # a failed attempt must be torn down or every retry dies with
            # "initialize should only be called once".
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001
                pass
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"could not reach coordinator {penv.coordinator_address} "
                    f"after {attempt} attempts"
                ) from e
            log.warning("coordinator not ready (attempt %d): %s", attempt, e)
            time.sleep(retry_interval_s)
