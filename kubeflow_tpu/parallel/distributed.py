"""Multi-host bootstrap: the TPU-native replacement for TF_CONFIG / hostfiles.

The reference wires distributed jobs through environment protocols the
operator injects: ``TF_CONFIG`` JSON for PS jobs (consumed at
``/root/reference/tf-controller-examples/tf-cnn/launcher.py:68-80``), MPI
hostfiles + kubectl-delivery for MPIJob
(``/root/reference/kubeflow/mpi-job/mpi-operator.libsonnet:287-289``), and
MASTER_ADDR env for DDP. Here a single env contract carries the JAX
coordinator address; XLA wires collectives over ICI within a slice and DCN
across slices — no ssh, no hostfile, no driver DaemonSet.

Env contract (injected by the TpuJob operator, see
``kubeflow_tpu/operators/tpujob.py``):

- ``KFTPU_COORDINATOR_ADDRESS``  host:port of process 0 (headless Service)
- ``KFTPU_NUM_PROCESSES``        total host processes in the job
- ``KFTPU_PROCESS_ID``           this process's rank
- ``KFTPU_JOB_NAME`` / ``KFTPU_NAMESPACE``  identity, for logging/metrics
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Optional

log = logging.getLogger(__name__)

ENV_COORDINATOR = "KFTPU_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "KFTPU_NUM_PROCESSES"
ENV_PROCESS_ID = "KFTPU_PROCESS_ID"
ENV_JOB_NAME = "KFTPU_JOB_NAME"
ENV_NAMESPACE = "KFTPU_NAMESPACE"
# Multi-slice topology (also injected by the operator; the names follow the
# TPU runtime's megascale convention so the XLA runtime picks them up too)
ENV_SLICE_ID = "MEGASCALE_SLICE_ID"
ENV_NUM_SLICES = "MEGASCALE_NUM_SLICES"


@dataclasses.dataclass(frozen=True)
class ProcessEnv:
    """Parsed view of the operator-injected distributed environment."""

    coordinator_address: Optional[str]
    num_processes: int
    process_id: int
    job_name: str = ""
    namespace: str = "default"
    slice_id: int = 0
    num_slices: int = 1

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    @property
    def is_multislice(self) -> bool:
        return self.num_slices > 1


def from_env(environ=None) -> ProcessEnv:
    env = os.environ if environ is None else environ
    return ProcessEnv(
        coordinator_address=env.get(ENV_COORDINATOR),
        num_processes=int(env.get(ENV_NUM_PROCESSES, "1")),
        process_id=int(env.get(ENV_PROCESS_ID, "0")),
        job_name=env.get(ENV_JOB_NAME, ""),
        namespace=env.get(ENV_NAMESPACE, "default"),
        slice_id=int(env.get(ENV_SLICE_ID, "0")),
        num_slices=int(env.get(ENV_NUM_SLICES, "1")),
    )


def initialize(
    penv: Optional[ProcessEnv] = None,
    *,
    timeout_s: float = 300.0,
    retry_interval_s: float = 5.0,
) -> ProcessEnv:
    """Call ``jax.distributed.initialize`` from the env contract, with retries.

    The reference's TF_CONFIG was static — every process could start in any
    order because PS/gRPC reconnected forever. JAX's coordinator (process 0)
    must be reachable first, so non-zero ranks retry with backoff until the
    coordinator's Service resolves (SURVEY.md §7 "hard parts" (c)).
    Single-process jobs return immediately without touching jax.distributed.
    """
    penv = penv or from_env()
    if not penv.is_distributed:
        log.info("single-process job; skipping jax.distributed")
        return penv
    if not penv.coordinator_address:
        raise RuntimeError(
            f"{ENV_NUM_PROCESSES}>1 but {ENV_COORDINATOR} is not set"
        )
    import jax

    deadline = time.monotonic() + timeout_s
    attempt = 0
    while True:
        attempt += 1
        remaining = max(deadline - time.monotonic(), retry_interval_s)
        try:
            jax.distributed.initialize(
                coordinator_address=penv.coordinator_address,
                num_processes=penv.num_processes,
                process_id=penv.process_id,
                initialization_timeout=int(remaining),
            )
            log.info(
                "jax.distributed up: rank %d/%d via %s",
                penv.process_id, penv.num_processes, penv.coordinator_address,
            )
            return penv
        except Exception as e:  # noqa: BLE001 — grpc raises various types
            # jax assigns its global distributed client before connect(), so
            # a failed attempt must be torn down or every retry dies with
            # "initialize should only be called once".
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001
                pass
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"could not reach coordinator {penv.coordinator_address} "
                    f"after {attempt} attempts"
                ) from e
            log.warning("coordinator not ready (attempt %d): %s", attempt, e)
            time.sleep(retry_interval_s)


def multislice_mesh(
    penv: Optional[ProcessEnv] = None,
    *,
    pp: int = 1,
    tp: int = 1,
    devices=None,
):
    """Build the cross-slice training mesh from the operator's env contract.

    The operator injects ``MEGASCALE_SLICE_ID``/``MEGASCALE_NUM_SLICES``
    (``kubeflow_tpu/operators/tpujob.py``), and after
    :func:`initialize` the global ``jax.devices()`` spans every slice.
    This maps that topology onto the 4-axis mesh: ``dcn = num_slices``
    (outer data parallelism — only the gradient allreduce crosses DCN),
    and the per-slice chips factor into ``dp × pp × tp`` over ICI.

    The reference's equivalent is assembling an MPI hostfile across hosts
    (``/root/reference/kubeflow/mpi-job/mpi-operator.libsonnet:283-289``);
    here the mesh *is* the topology and XLA emits the hierarchical
    collectives (reduce-scatter over ICI, allreduce of the partial sums
    over DCN, all-gather back over ICI).

    ``devices`` orders slice-major (all of slice 0, then slice 1, …) —
    this is jax's process-major device order when the operator assigns
    ranks slice-major, and tests pass virtual CPU devices the same way.
    """
    import jax

    from kubeflow_tpu.parallel.mesh import MeshConfig, create_mesh

    penv = penv or from_env()
    devs = list(devices) if devices is not None else jax.devices()
    n_slices = penv.num_slices
    if len(devs) % n_slices:
        raise ValueError(
            f"{len(devs)} devices do not divide into {n_slices} slices")
    per_slice = len(devs) // n_slices
    if per_slice % (pp * tp):
        raise ValueError(
            f"pp*tp={pp * tp} does not divide slice size {per_slice}")
    config = MeshConfig(
        dcn=n_slices, dp=per_slice // (pp * tp), pp=pp, tp=tp)
    return create_mesh(config, devices=devs if devices is not None else None)
