"""Pipeline parallelism: SPMD microbatch pipeline over the ``pp`` mesh axis.

The reference has no model parallelism of any kind (SURVEY.md §2c: PP =
"ABSENT"). This module adds GPipe-style pipelining the TPU-native way: not
per-stage processes with send/recv (the GPU framework shape), but a single
SPMD program under partial-manual ``shard_map`` — manual over ``pp`` only,
so every device runs the same tick loop and activations move one
``ppermute`` hop per tick (XLA lowers the hop onto the ICI link between
neighbouring stages), while dp/tp stay auto-sharded inside each stage (tp
constraints in the block code keep working).

Schedule (one stage per pp-rank): tick t: stage 0 ingests microbatch t
(while t < M); every stage applies its layers to its current activation;
activations shift right; stage S-1's output for microbatch t emerges at
tick t + S - 1. Forward+backward flow through ``lax.scan`` autodiff — the
classic GPipe bubble (S-1)/M, amortized by more microbatches.

Stage weights are the scanned transformer block stack
(``kubeflow_tpu/models/transformer.py`` stacks blocks with a leading layer
axis) reshaped so each pp-rank holds ``n_layers / pp`` contiguous layers —
the reshape happens inside jit, so the same checkpoint loads pipelined or
not.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu import compat

# stage_fn(stage_params, x) -> y; applies one stage's layers to a microbatch
StageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]


def _axis_size(mesh: Mesh, axis: str) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape))[axis]


def split_stages(stacked_params: Any, n_stages: int) -> Any:
    """Reshape leading layer axis L -> (n_stages, L/n_stages) on every leaf."""

    def reshape(leaf):
        L = leaf.shape[0]
        if L % n_stages:
            raise ValueError(f"layers {L} not divisible by stages {n_stages}")
        return leaf.reshape(n_stages, L // n_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, stacked_params)


def merge_stages(staged_params: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda leaf: leaf.reshape(-1, *leaf.shape[2:]), staged_params
    )


def pipeline_apply(
    stage_fn: StageFn,
    staged_params: Any,
    microbatches: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "pp",
) -> jnp.ndarray:
    """Run microbatches through the stage pipeline; returns stacked outputs.

    ``staged_params`` leaves have leading dim = pp size (sharded over
    ``axis``); ``microbatches`` is (M, mb, ...), replicated along ``axis``
    (dp/tp sharding of the inner dims is orthogonal — those axes stay auto).
    Output is (M, mb, ...) replicated along ``axis``: the last stage's
    results are broadcast back with one ``psum``-sized hop so the loss code
    after the pipeline is ordinary SPMD.
    """
    n_stages = _axis_size(mesh, axis)
    M = microbatches.shape[0]
    total = M + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def per_device(staged_local, mb_local):
        # staged_local: (1, L/S, ...) this rank's stage; mb_local (M, mb, ...)
        params_me = jax.tree_util.tree_map(lambda l: l[0], staged_local)
        rank = jax.lax.axis_index(axis)
        # pvary: carries become rank-dependent after the first tick, so their
        # init must already be typed varying-over-pp for the scan carry
        def _vary(x):
            return compat.pvary(x, (axis,))

        state = _vary(jnp.zeros(mb_local.shape[1:], mb_local.dtype))
        out = _vary(jnp.zeros_like(mb_local))

        def tick(carry, t):
            state, out = carry
            # stage 0 ingests microbatch t (clamped; ticks t >= M recompute
            # the last microbatch on stage 0 — wasted flops, not wrong,
            # since only the last stage's writes reach the output)
            feed = mb_local[jnp.minimum(t, M - 1)]
            x = jnp.where(rank == 0, feed, state)
            y = stage_fn(params_me, x)
            done_idx = t - (n_stages - 1)
            write = jnp.logical_and(rank == n_stages - 1, done_idx >= 0)
            out = jnp.where(
                write,
                jax.lax.dynamic_update_index_in_dim(
                    out, y, jnp.maximum(done_idx, 0), 0
                ),
                out,
            )
            state = jax.lax.ppermute(y, axis, perm)
            return (state, out), None

        (_, out), _ = jax.lax.scan(tick, (state, out), jnp.arange(total))
        # broadcast the last stage's outputs to every rank
        mask = (rank == n_stages - 1).astype(out.dtype)
        return jax.lax.psum(out * mask, axis)

    fn = compat.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        axis_names={axis},  # manual over pp only; dp/tp stay auto
    )
    return fn(staged_params, microbatches)


# ---------------------------------------------------------------------------
# Pipelined transformer LM forward
# ---------------------------------------------------------------------------


def make_pipelined_lm_forward(
    model,
    mesh: Mesh,
    *,
    n_microbatches: int,
    axis: str = "pp",
):
    """Forward fn (params, tokens) -> logits with the block stack pipelined.

    Embedding and the final norm/unembed run replicated on every pp rank
    (cheap relative to the block stack); the scanned block stack is staged
    over ``axis``. Requires ``scan_layers=True`` params (the stacked
    "blocks" subtree).
    """
    import flax.linen as nn

    from kubeflow_tpu.models.transformer import (  # local import: no cycle
        Block,
        RMSNorm,
        rope_tables,
    )

    n_stages = _axis_size(mesh, axis)
    c = model.config
    # honor config.remat here too — pipelining targets exactly the
    # large-model regime where un-rematted activations would blow HBM
    block_cls = nn.remat(Block, prevent_cse=False) if c.remat else Block
    block = block_cls(c)
    final_norm = RMSNorm(param_dtype=c.param_dtype)

    def forward(params, tokens):
        B, S = tokens.shape
        if B % n_microbatches:
            raise ValueError(
                f"batch {B} not divisible by microbatches {n_microbatches}"
            )
        embed = params["token_embed"].astype(c.dtype)
        x = jnp.take(embed, tokens, axis=0)
        sin, cos = rope_tables(S, c.head_dim, c.rope_theta)

        staged = split_stages(params["blocks"], n_stages)

        def stage_fn(stage_params, x):
            def layer(x, layer_params):
                y, _ = block.apply({"params": layer_params}, x, (sin, cos))
                return y, None

            x, _ = jax.lax.scan(layer, x, stage_params)
            return x

        mbs = x.reshape(n_microbatches, B // n_microbatches, S, c.d_model)
        y = pipeline_apply(stage_fn, staged, mbs, mesh=mesh, axis=axis)
        x = y.reshape(B, S, c.d_model)

        x = final_norm.apply({"params": params["final_norm"]}, x)
        logits = jnp.einsum("bsd,vd->bsv", x, embed).astype(jnp.float32)
        if c.logits_softcap:
            logits = c.logits_softcap * jnp.tanh(logits / c.logits_softcap)
        return logits

    return forward
