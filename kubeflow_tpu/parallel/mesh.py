"""Device-mesh construction and logical-axis sharding rules.

This is the heart of the parallelism the reference platform *lacks* (SURVEY.md
§2c): the reference only offers process-level data parallelism (TFJob PS mode,
MPIJob ring-allreduce, PyTorchJob DDP — see
``/root/reference/kubeflow/tf-training/tf-job-operator.libsonnet:14-46``,
``/root/reference/kubeflow/mpi-job/mpi-operator.libsonnet``). Here TP/PP/SP/EP
are first-class mesh axes, and XLA emits the collectives over ICI.

Physical mesh axes
------------------
``("dcn", "dp", "pp", "tp")`` — cross-slice data, in-slice data,
pipeline-stage, and tensor axes. ``dcn`` is the multi-slice axis: its
collectives ride the data-center network between TPU slices (the
reference's analogue is multi-host MPI ring allreduce over the pod
network, ``/root/reference/kubeflow/mpi-job/mpi-operator.libsonnet:283-289``),
so only the once-per-step gradient allreduce is mapped onto it — never
per-layer tensor collectives. On a single slice ``dcn`` has size 1 and
vanishes from the compiled program. Two further *logical* parallelism
forms ride these physical axes, which is the standard TPU mapping:

- **sequence/context parallel (sp)** shards activations' sequence dimension
  over the ``tp`` group (Megatron-style sequence parallelism: the tensor
  group is already exchanging activations per layer, so the sequence shards
  ride the same ICI neighbours; ring attention runs over the same axis).
- **expert parallel (ep)** shards MoE experts over the ``dp`` group
  (DeepSpeed-MoE-style EP-on-DP: tokens all_to_all within the dp group).

Logical axis names used by models are mapped to mesh axes through a rules
table so a model is written once and resharded by swapping rules.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubeflow_tpu import compat

MESH_AXES = ("dcn", "dp", "pp", "tp")

# logical axis -> mesh axis (or None = replicated). Order matters only for
# first-match lookup; each logical name appears once.
AxisRules = Tuple[Tuple[str, Optional[Union[str, Tuple[str, ...]]]], ...]

DEFAULT_RULES: AxisRules = (
    ("batch", ("dcn", "dp")),  # per-example batch dim: outer-dp over DCN × dp
    ("stage", ("pp",)),        # stacked pipeline-stage dim on stage-stacked params
    ("embed", None),           # d_model dim of activations: replicated in tp group
    ("seq", ("tp",)),          # sequence-parallel regions (norms/residual)
    ("heads", ("tp",)),        # attention heads
    ("kv", None),              # per-head dim
    ("mlp", ("tp",)),          # ffn hidden
    ("vocab", ("tp",)),        # embedding/unembedding vocab dim
    ("expert", ("dp",)),       # MoE experts ride the dp axis (EP-on-DP)
    ("expert_mlp", ("tp",)),   # within-expert ffn hidden
)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Shape of the device mesh. Product must equal the device count.

    ``dcn`` is the number of TPU slices joined over DCN (outer data
    parallelism); ``dp``/``pp``/``tp`` describe the per-slice layout."""

    dp: int = 1
    pp: int = 1
    tp: int = 1
    dcn: int = 1

    @property
    def size(self) -> int:
        return self.dcn * self.dp * self.pp * self.tp

    @property
    def slice_size(self) -> int:
        """Chips per slice (mesh size within one ICI domain)."""
        return self.dp * self.pp * self.tp

    def axis_sizes(self) -> Tuple[int, int, int, int]:
        return (self.dcn, self.dp, self.pp, self.tp)


def auto_mesh_config(
    n_devices: int, *, pp: int = 1, tp: Optional[int] = None
) -> MeshConfig:
    """Pick a mesh shape for ``n_devices``.

    Defaults to pure data parallelism with a modest tp dimension when the
    device count allows: tp = gcd(n/pp, 2) unless given. Callers with real
    topology knowledge should construct :class:`MeshConfig` directly.
    """
    if n_devices % pp:
        raise ValueError(f"pp={pp} does not divide device count {n_devices}")
    rem = n_devices // pp
    if tp is None:
        tp = 2 if rem % 2 == 0 and rem > 1 else 1
    if rem % tp:
        raise ValueError(f"tp={tp} does not divide {rem}")
    return MeshConfig(dp=rem // tp, pp=pp, tp=tp)


def create_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with axes ``("dcn", "dp", "pp", "tp")``.

    On real TPU slices, ``mesh_utils.create_device_mesh`` lays the axes out so
    the innermost (tp) axis falls on ICI-adjacent chips — tp/sp collectives
    (the per-layer ones) ride the fastest links, dp allreduce amortises over
    the step. With ``dcn > 1`` (multi-slice), the hybrid mesh builder places
    the dcn axis across slices so exactly one collective — the gradient
    allreduce — crosses DCN, and everything else stays on ICI.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if config is None:
        config = auto_mesh_config(len(devs))
    if config.size != len(devs):
        raise ValueError(
            f"mesh {config.axis_sizes()} needs {config.size} devices, have {len(devs)}"
        )
    if devices is None and devs[0].platform == "tpu":
        from jax.experimental import mesh_utils

        if config.dcn > 1:
            arr = mesh_utils.create_hybrid_device_mesh(
                (1, config.dp, config.pp, config.tp),
                dcn_mesh_shape=(config.dcn, 1, 1, 1),
                devices=devs,
            )
        else:
            arr = mesh_utils.create_device_mesh(
                config.axis_sizes(), devices=devs)
    else:
        # virtual/explicit devices: dcn-major order, i.e. devices are grouped
        # into contiguous per-slice blocks (matches how jax orders devices by
        # process and how the operator assigns ranks slice-major)
        arr = np.asarray(devs).reshape(config.axis_sizes())
    return Mesh(arr, MESH_AXES)


def logical_to_mesh_axes(
    logical_axes: Sequence[Optional[str]], rules: AxisRules = DEFAULT_RULES
) -> PartitionSpec:
    """Map a tuple of logical axis names (None = replicated) to a PartitionSpec."""
    table = dict(rules)
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
            continue
        if name not in table:
            raise KeyError(f"no sharding rule for logical axis {name!r}")
        mesh_axes = table[name]
        if mesh_axes is None:
            out.append(None)
        elif isinstance(mesh_axes, str):
            out.append(mesh_axes)
        elif len(mesh_axes) == 1:
            out.append(mesh_axes[0])
        else:
            out.append(tuple(mesh_axes))
    # trim trailing Nones for canonical form
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def data_parallel_size(mesh: Mesh) -> int:
    """Global batch-sharding width: product of the dcn and dp axis sizes."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return sizes.get("dcn", 1) * sizes.get("dp", 1)


def _filter_spec(spec: PartitionSpec, keep) -> PartitionSpec:
    """Rebuild ``spec`` keeping only axis names where ``keep(name)``,
    collapsing emptied entries to None and trimming trailing Nones."""
    out = []
    for entry in spec:
        if entry is None or entry is PartitionSpec.UNCONSTRAINED:
            out.append(entry)
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        axes = tuple(a for a in axes if keep(a))
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def spec_for_mesh(spec: PartitionSpec, mesh) -> PartitionSpec:
    """Drop axis names ``mesh`` does not have.

    Models and train steps are written against the full 4-axis rules
    (batch over ``("dcn", "dp")``); this keeps them runnable on reduced
    meshes — a plain dp/tp mesh, a collective-test mesh — where the
    missing axis would otherwise be a hard error. Dropping an absent axis
    is exact: an axis the mesh lacks has size 1, and sharding over a
    size-1 axis is replication."""
    names = set(mesh.axis_names)
    return _filter_spec(spec, names.__contains__)


def named_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: AxisRules = DEFAULT_RULES,
) -> NamedSharding:
    return NamedSharding(mesh, logical_to_mesh_axes(logical_axes, rules))


def shard_constraint(x, logical_axes, rules: AxisRules = DEFAULT_RULES):
    """``with_sharding_constraint`` by logical axis names.

    No-op only when no mesh is current (plain eager/test use); inside a mesh
    a malformed spec raises rather than silently dropping the constraint.
    Axis names the current mesh lacks are dropped (see
    :func:`spec_for_mesh`), as are axes that are *manual* at the current
    trace point: inside a shard_map region a manual axis is already a
    per-device dim, so a constraint over it is meaningless — and on
    jax<0.5 it aborts the XLA partitioner outright. A fully-manual
    region (every mesh axis bound, the legacy-shard_map shape) skips
    the constraint entirely.
    """
    spec = logical_to_mesh_axes(logical_axes, rules)
    mesh = compat.current_mesh()
    if getattr(mesh, "empty", True):
        return x
    spec = spec_for_mesh(spec, mesh)
    manual = compat.bound_axes(mesh.axis_names)
    if manual:
        if manual >= set(mesh.axis_names):
            return x
        spec = _filter_spec(spec, lambda a: a not in manual)
    return jax.lax.with_sharding_constraint(x, spec)


def mesh_context(mesh: Mesh):
    """Context manager making ``mesh`` current for bare-PartitionSpec
    sharding constraints; spans the jax 0.8/0.9 use_mesh→set_mesh rename
    and the jax<0.5 ``with mesh:`` form (see ``kubeflow_tpu/compat``)."""
    return compat.mesh_context(mesh)


def shape_aware_spec(
    spec: PartitionSpec, shape: Tuple[int, ...], mesh: Mesh
) -> PartitionSpec:
    """Drop sharding on dims the mesh cannot divide evenly.

    Lets one rules table serve models whose small dims (e.g. GQA kv heads)
    don't divide a large tp axis: those dims replicate instead of erroring.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    out = []
    padded = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
    for dim, axis in zip(shape, padded):
        if axis is None:
            out.append(None)
            continue
        axes = (axis,) if isinstance(axis, str) else tuple(axis)
        n = 1
        for a in axes:
            n *= sizes.get(a, 1)
        out.append(axis if dim % n == 0 else None)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def validate_mesh_for_model(
    config: MeshConfig, *, n_heads: int, d_ff: int, n_experts: int = 0
) -> None:
    """Fail fast when a mesh shape cannot shard a model's dimensions."""
    if n_heads % config.tp:
        raise ValueError(f"tp={config.tp} must divide n_heads={n_heads}")
    if d_ff % config.tp:
        raise ValueError(f"tp={config.tp} must divide d_ff={d_ff}")
    if n_experts and n_experts % config.dp != 0:
        raise ValueError(
            f"dp={config.dp} must divide n_experts={n_experts} "
            f"(experts shard over the dp axis)"
        )
