"""Device-mesh construction and logical-axis sharding rules.

This is the heart of the parallelism the reference platform *lacks* (SURVEY.md
§2c): the reference only offers process-level data parallelism (TFJob PS mode,
MPIJob ring-allreduce, PyTorchJob DDP — see
``/root/reference/kubeflow/tf-training/tf-job-operator.libsonnet:14-46``,
``/root/reference/kubeflow/mpi-job/mpi-operator.libsonnet``). Here TP/PP/SP/EP
are first-class mesh axes, and XLA emits the collectives over ICI.

Physical mesh axes
------------------
``("dp", "pp", "tp")`` — data, pipeline-stage, and tensor axes. Two further
*logical* parallelism forms ride these physical axes, which is the standard
TPU mapping:

- **sequence/context parallel (sp)** shards activations' sequence dimension
  over the ``tp`` group (Megatron-style sequence parallelism: the tensor
  group is already exchanging activations per layer, so the sequence shards
  ride the same ICI neighbours; ring attention runs over the same axis).
- **expert parallel (ep)** shards MoE experts over the ``dp`` group
  (DeepSpeed-MoE-style EP-on-DP: tokens all_to_all within the dp group).

Logical axis names used by models are mapped to mesh axes through a rules
table so a model is written once and resharded by swapping rules.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("dp", "pp", "tp")

# logical axis -> mesh axis (or None = replicated). Order matters only for
# first-match lookup; each logical name appears once.
AxisRules = Tuple[Tuple[str, Optional[Union[str, Tuple[str, ...]]]], ...]

DEFAULT_RULES: AxisRules = (
    ("batch", ("dp",)),        # per-example batch dim
    ("stage", ("pp",)),        # stacked pipeline-stage dim on stage-stacked params
    ("embed", None),           # d_model dim of activations: replicated in tp group
    ("seq", ("tp",)),          # sequence-parallel regions (norms/residual)
    ("heads", ("tp",)),        # attention heads
    ("kv", None),              # per-head dim
    ("mlp", ("tp",)),          # ffn hidden
    ("vocab", ("tp",)),        # embedding/unembedding vocab dim
    ("expert", ("dp",)),       # MoE experts ride the dp axis (EP-on-DP)
    ("expert_mlp", ("tp",)),   # within-expert ffn hidden
)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Shape of the device mesh. Product must equal the device count."""

    dp: int = 1
    pp: int = 1
    tp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.tp

    def axis_sizes(self) -> Tuple[int, int, int]:
        return (self.dp, self.pp, self.tp)


def auto_mesh_config(
    n_devices: int, *, pp: int = 1, tp: Optional[int] = None
) -> MeshConfig:
    """Pick a mesh shape for ``n_devices``.

    Defaults to pure data parallelism with a modest tp dimension when the
    device count allows: tp = gcd(n/pp, 2) unless given. Callers with real
    topology knowledge should construct :class:`MeshConfig` directly.
    """
    if n_devices % pp:
        raise ValueError(f"pp={pp} does not divide device count {n_devices}")
    rem = n_devices // pp
    if tp is None:
        tp = 2 if rem % 2 == 0 and rem > 1 else 1
    if rem % tp:
        raise ValueError(f"tp={tp} does not divide {rem}")
    return MeshConfig(dp=rem // tp, pp=pp, tp=tp)


def create_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with axes ``("dp", "pp", "tp")``.

    On real TPU slices, ``mesh_utils.create_device_mesh`` lays the axes out so
    the innermost (tp) axis falls on ICI-adjacent chips — tp/sp collectives
    (the per-layer ones) ride the fastest links, dp allreduce amortises over
    the step.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if config is None:
        config = auto_mesh_config(len(devs))
    if config.size != len(devs):
        raise ValueError(
            f"mesh {config.axis_sizes()} needs {config.size} devices, have {len(devs)}"
        )
    if devices is None and devs[0].platform == "tpu":
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(config.axis_sizes(), devices=devs)
    else:
        arr = np.asarray(devs).reshape(config.axis_sizes())
    return Mesh(arr, MESH_AXES)


def logical_to_mesh_axes(
    logical_axes: Sequence[Optional[str]], rules: AxisRules = DEFAULT_RULES
) -> PartitionSpec:
    """Map a tuple of logical axis names (None = replicated) to a PartitionSpec."""
    table = dict(rules)
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
            continue
        if name not in table:
            raise KeyError(f"no sharding rule for logical axis {name!r}")
        mesh_axes = table[name]
        if mesh_axes is None:
            out.append(None)
        elif isinstance(mesh_axes, str):
            out.append(mesh_axes)
        elif len(mesh_axes) == 1:
            out.append(mesh_axes[0])
        else:
            out.append(tuple(mesh_axes))
    # trim trailing Nones for canonical form
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def named_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: AxisRules = DEFAULT_RULES,
) -> NamedSharding:
    return NamedSharding(mesh, logical_to_mesh_axes(logical_axes, rules))


def shard_constraint(x, logical_axes, rules: AxisRules = DEFAULT_RULES):
    """``with_sharding_constraint`` by logical axis names.

    No-op only when no mesh is current (plain eager/test use); inside a mesh
    a malformed spec raises rather than silently dropping the constraint.
    """
    spec = logical_to_mesh_axes(logical_axes, rules)
    try:
        no_mesh = jax.sharding.get_abstract_mesh().empty
    except AttributeError:
        no_mesh = False
    if no_mesh:
        return x
    return jax.lax.with_sharding_constraint(x, spec)


def mesh_context(mesh: Mesh):
    """Context manager making ``mesh`` current for bare-PartitionSpec
    sharding constraints; spans the jax 0.8/0.9 use_mesh→set_mesh rename."""
    if hasattr(jax.sharding, "use_mesh"):
        return jax.sharding.use_mesh(mesh)
    if hasattr(jax.sharding, "set_mesh"):
        return jax.sharding.set_mesh(mesh)
    return mesh


def shape_aware_spec(
    spec: PartitionSpec, shape: Tuple[int, ...], mesh: Mesh
) -> PartitionSpec:
    """Drop sharding on dims the mesh cannot divide evenly.

    Lets one rules table serve models whose small dims (e.g. GQA kv heads)
    don't divide a large tp axis: those dims replicate instead of erroring.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    out = []
    padded = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
    for dim, axis in zip(shape, padded):
        if axis is None:
            out.append(None)
            continue
        axes = (axis,) if isinstance(axis, str) else tuple(axis)
        n = 1
        for a in axes:
            n *= sizes.get(a, 1)
        out.append(axis if dim % n == 0 else None)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def validate_mesh_for_model(
    config: MeshConfig, *, n_heads: int, d_ff: int, n_experts: int = 0
) -> None:
    """Fail fast when a mesh shape cannot shard a model's dimensions."""
    if n_heads % config.tp:
        raise ValueError(f"tp={config.tp} must divide n_heads={n_heads}")
    if d_ff % config.tp:
        raise ValueError(f"tp={config.tp} must divide d_ff={d_ff}")
    if n_experts and n_experts % config.dp != 0:
        raise ValueError(
            f"dp={config.dp} must divide n_experts={n_experts} "
            f"(experts shard over the dp axis)"
        )
