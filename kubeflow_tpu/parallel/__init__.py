"""Parallelism library: mesh construction, sharding rules, distributed init.

First-class DP/TP/PP/SP/EP where the reference only orchestrated
process-level data parallelism (SURVEY.md §2c).
"""

from kubeflow_tpu.parallel.mesh import (  # noqa: F401
    DEFAULT_RULES,
    MESH_AXES,
    MeshConfig,
    auto_mesh_config,
    create_mesh,
    logical_to_mesh_axes,
    named_sharding,
    shard_constraint,
    validate_mesh_for_model,
)
from kubeflow_tpu.parallel.distributed import (  # noqa: F401
    ProcessEnv,
    from_env,
    initialize,
    multislice_mesh,
)
from kubeflow_tpu.parallel.pipeline import (  # noqa: F401
    make_pipelined_lm_forward,
    merge_stages,
    pipeline_apply,
    split_stages,
)
