// Notebook manager frontend over the NotebookWebApp REST routes
// (kubeflow_tpu/notebooks/webapp.py). Relative API paths: works at /
// (port-forward) and at /jupyter/ (gateway prefix-strip) unchanged.

"use strict";

const $ = (id) => document.getElementById(id);

function showError(msg) {
  const el = $("error");
  el.textContent = msg;
  el.style.display = "block";
  setTimeout(() => { el.style.display = "none"; }, 8000);
}

function esc(s) {
  const d = document.createElement("div");
  d.textContent = String(s == null ? "" : s);
  return d.innerHTML;
}

async function api(path, opts) {
  const resp = await fetch(path, {
    credentials: "same-origin",
    headers: { "Content-Type": "application/json" },
    ...opts,
  });
  if (resp.status === 401) {
    window.location.href = "/login.html?next=" +
      encodeURIComponent(window.location.pathname);
    throw new Error("unauthenticated");
  }
  const body = await resp.json().catch(() => ({}));
  if (!resp.ok || body.success === false) {
    throw new Error(body.log || path + " → HTTP " + resp.status);
  }
  return body;
}

const ns = () => encodeURIComponent($("ns-select").value);

async function loadNamespaces() {
  const body = await api("api/namespaces");
  const sel = $("ns-select");
  sel.innerHTML = body.namespaces
    .map((n) => `<option value="${esc(n)}">${esc(n)}</option>`).join("");
  const saved = localStorage.getItem("kftpu-ns");
  if (saved && body.namespaces.includes(saved)) sel.value = saved;
}

function statusOf(nb) {
  if (nb.stopped) return "Stopped";
  return nb.phase || "Waiting";
}

async function loadNotebooks() {
  const body = await api(`api/namespaces/${ns()}/notebooks`);
  $("notebooks").innerHTML = body.notebooks.length
    ? body.notebooks.map((nb) => `
      <tr>
        <td><a href="/${esc(nb.namespace)}/${esc(nb.name)}/">${esc(nb.name)}</a></td>
        <td>${esc(nb.image)}</td>
        <td>${esc(nb.tpuChips)}</td>
        <td><span class="pill ${esc(statusOf(nb))}">${esc(statusOf(nb))}</span></td>
        <td>
          ${nb.stopped
            ? `<button data-act="start" data-name="${esc(nb.name)}">Start</button>`
            : `<button class="secondary" data-act="stop" data-name="${esc(nb.name)}">Stop</button>`}
          <button class="danger" data-act="delete" data-name="${esc(nb.name)}">Delete</button>
        </td>
      </tr>`).join("")
    : "<tr><td colspan=5>no notebooks in this namespace</td></tr>";
}

async function loadPvcs() {
  const body = await api(`api/namespaces/${ns()}/pvcs`);
  $("pvcs").innerHTML = body.pvcs.length
    ? body.pvcs.map((p) => `
      <tr><td>${esc(p.name)}</td><td>${esc(p.size)}</td>
          <td>${esc(p.mode)}</td></tr>`).join("")
    : "<tr><td colspan=3>no volumes</td></tr>";
  $("nb-pvc").innerHTML = '<option value="">none</option>' +
    body.pvcs.map((p) =>
      `<option value="${esc(p.name)}">${esc(p.name)}</option>`).join("");
}

function refresh() {
  Promise.all([loadNotebooks(), loadPvcs()])
    .catch((e) => { if (e.message !== "unauthenticated") showError(e.message); });
}

$("create-form").addEventListener("submit", async (e) => {
  e.preventDefault();
  const spec = {
    image: $("nb-image").value,
    tpuChips: Number($("nb-tpus").value),
  };
  if ($("nb-pvc").value) spec.workspaceVolume = $("nb-pvc").value;
  try {
    await api(`api/namespaces/${ns()}/notebooks`, {
      method: "POST",
      body: JSON.stringify({ name: $("nb-name").value, spec }),
    });
    $("nb-name").value = "";
    refresh();
  } catch (err) { showError(err.message); }
});

$("pvc-form").addEventListener("submit", async (e) => {
  e.preventDefault();
  try {
    await api(`api/namespaces/${ns()}/pvcs`, {
      method: "POST",
      body: JSON.stringify({
        name: $("pvc-name").value,
        size: $("pvc-size").value + "Gi",
      }),
    });
    $("pvc-name").value = "";
    refresh();
  } catch (err) { showError(err.message); }
});

$("notebooks").addEventListener("click", async (e) => {
  const btn = e.target.closest("button[data-act]");
  if (!btn) return;
  const name = encodeURIComponent(btn.dataset.name);
  try {
    if (btn.dataset.act === "delete") {
      if (!window.confirm(`Delete notebook ${btn.dataset.name}?`)) return;
      await api(`api/namespaces/${ns()}/notebooks/${name}`,
                { method: "DELETE" });
    } else {
      await api(`api/namespaces/${ns()}/notebooks/${name}/${btn.dataset.act}`,
                { method: "POST" });
    }
    refresh();
  } catch (err) { showError(err.message); }
});

$("ns-select").addEventListener("change", () => {
  localStorage.setItem("kftpu-ns", $("ns-select").value);
  refresh();
});

loadNamespaces().then(refresh)
  .catch((e) => { if (e.message !== "unauthenticated") showError(e.message); });
setInterval(refresh, 15000);
