"""Idle-notebook culling policy.

Reference: ``/root/reference/components/notebook-controller/pkg/culler/
culler.go`` — annotations record last activity; the controller compares
against a configurable idle window and scales the notebook to zero by
setting a stop annotation, re-checking on a period via RequeueAfter
(``notebook_controller.go:288-305``).
"""

from __future__ import annotations

import calendar
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

# annotation contract (mirrors the reference's kubeflow-resource-stopped /
# notebooks.kubeflow.org/last-activity pair)
STOP_ANNOTATION = "kubeflow-tpu.org/notebook-stopped"
LAST_ACTIVITY_ANNOTATION = "kubeflow-tpu.org/last-activity"

TIME_FMT = "%Y-%m-%dT%H:%M:%SZ"


@dataclass(frozen=True)
class CullingPolicy:
    enabled: bool = False
    idle_seconds: float = 1440 * 60  # reference default: 1440 minutes
    check_period_seconds: float = 60.0

    @classmethod
    def from_env(cls, env: Dict[str, str]) -> "CullingPolicy":
        return cls(
            enabled=env.get("ENABLE_CULLING", "false").lower() == "true",
            idle_seconds=float(env.get("CULL_IDLE_TIME", "1440")) * 60,
            check_period_seconds=float(env.get("IDLE_TIME_CHECK_PERIOD",
                                               "1")) * 60,
        )


def _annotations(notebook: Dict[str, Any]) -> Dict[str, str]:
    return notebook.get("metadata", {}).get("annotations", {}) or {}


def is_stopped(notebook: Dict[str, Any]) -> bool:
    return STOP_ANNOTATION in _annotations(notebook)


def last_activity(notebook: Dict[str, Any]) -> Optional[float]:
    raw = _annotations(notebook).get(LAST_ACTIVITY_ANNOTATION)
    if not raw:
        return None
    try:
        # timegm, not mktime: the annotation is UTC (written via gmtime);
        # mktime would skew idle detection by the host's UTC offset
        return float(calendar.timegm(time.strptime(raw, TIME_FMT)))
    except ValueError:
        return None


def touch(notebook: Dict[str, Any], now: Optional[float] = None) -> None:
    """Record activity now (webapp calls this on user traffic)."""
    md = notebook.setdefault("metadata", {})
    md.setdefault("annotations", {})[LAST_ACTIVITY_ANNOTATION] = time.strftime(
        TIME_FMT, time.gmtime(now if now is not None else time.time()))


def should_cull(notebook: Dict[str, Any], policy: CullingPolicy,
                now: Optional[float] = None) -> bool:
    """True when the notebook has been idle past the policy window.

    A notebook with no recorded activity is never culled (the reference
    likewise only culls on a positive idle signal from the jupyter API).
    """
    if not policy.enabled or is_stopped(notebook):
        return False
    seen = last_activity(notebook)
    if seen is None:
        return False
    now = now if now is not None else time.time()
    return (now - seen) > policy.idle_seconds


def stop(notebook: Dict[str, Any], now: Optional[float] = None) -> None:
    md = notebook.setdefault("metadata", {})
    md.setdefault("annotations", {})[STOP_ANNOTATION] = time.strftime(
        TIME_FMT, time.gmtime(now if now is not None else time.time()))


def resume(notebook: Dict[str, Any]) -> None:
    _annotations(notebook).pop(STOP_ANNOTATION, None)
