"""Notebook controller: Notebook CR → StatefulSet + Service (+ culling).

Reference: ``/root/reference/components/notebook-controller/controllers/
notebook_controller.go`` — reconcile at :167-307 builds a StatefulSet
(replicas 0 when the stop annotation is set) and a Service :80→8888,
mirrors pod container state into status conditions (:309-336), and drives
idle culling via annotations + RequeueAfter (:288-305). TPU twist: a
notebook can request TPU chips, which lands as a ``google.com/tpu``
resource limit + accelerator node selector.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.k8s.client import ApiError, KubeClient, register_plural
from kubeflow_tpu.manifests.components.tpujob_operator import GROUP, VERSION
from kubeflow_tpu.notebooks import culler
from kubeflow_tpu.operators.controller import Controller

log = logging.getLogger(__name__)

NOTEBOOK_API_VERSION = f"{GROUP}/{VERSION}"
NOTEBOOK_KIND = "Notebook"
NOTEBOOK_PLURAL = "notebooks"
NOTEBOOK_LABEL = "kubeflow-tpu.org/notebook-name"

NOTEBOOK_PORT = 8888
DEFAULT_IMAGE = "jupyter/scipy-notebook:latest"

register_plural(NOTEBOOK_KIND, NOTEBOOK_PLURAL)


@dataclass
class NotebookSpec:
    """Typed view of a Notebook CR's spec."""

    image: str = DEFAULT_IMAGE
    cpu: str = "500m"
    memory: str = "1Gi"
    tpu_chips: int = 0
    accelerator: str = "v5e-8"
    env: Dict[str, str] = field(default_factory=dict)
    volumes: List[Dict[str, Any]] = field(default_factory=list)
    volume_mounts: List[Dict[str, Any]] = field(default_factory=list)

    @classmethod
    def from_dict(cls, spec: Dict[str, Any]) -> "NotebookSpec":
        return cls(
            image=spec.get("image", DEFAULT_IMAGE),
            cpu=str(spec.get("cpu", "500m")),
            memory=str(spec.get("memory", "1Gi")),
            tpu_chips=int(spec.get("tpuChips", 0)),
            accelerator=spec.get("accelerator", "v5e-8"),
            env=dict(spec.get("env", {}) or {}),
            volumes=list(spec.get("volumes", []) or []),
            volume_mounts=list(spec.get("volumeMounts", []) or []),
        )


def notebook(name: str, ns: str, spec: Optional[Dict[str, Any]] = None) -> o.Obj:
    return {
        "apiVersion": NOTEBOOK_API_VERSION,
        "kind": NOTEBOOK_KIND,
        "metadata": {"name": name, "namespace": ns},
        "spec": dict(spec or {}),
    }


def build_statefulset(nb: o.Obj) -> o.Obj:
    name = nb["metadata"]["name"]
    ns = nb["metadata"]["namespace"]
    spec = NotebookSpec.from_dict(nb.get("spec", {}))

    resources: Dict[str, Any] = {
        "requests": {"cpu": spec.cpu, "memory": spec.memory},
        "limits": {"cpu": spec.cpu, "memory": spec.memory},
    }
    node_selector = None
    if spec.tpu_chips:
        from kubeflow_tpu.platform.slices import slice_shape

        resources["limits"]["google.com/tpu"] = spec.tpu_chips
        # select on the GKE accelerator TYPE the node pool advertises,
        # not the framework's shape name
        node_selector = {
            "cloud.google.com/gke-tpu-accelerator":
                slice_shape(spec.accelerator).accelerator}

    env = dict(spec.env)
    # same base-url contract as the reference's sync-notebook.jsonnet:12-23
    env.setdefault("NB_PREFIX", f"/notebook/{ns}/{name}")
    ctr = o.container(
        "notebook",
        spec.image,
        env=env,
        ports=[NOTEBOOK_PORT],
        resources=resources,
        volume_mounts=spec.volume_mounts or None,
    )
    pod = o.pod_spec(
        [ctr],
        volumes=spec.volumes or None,
        node_selector=node_selector,
    )
    replicas = 0 if culler.is_stopped(nb) else 1
    sts = o.stateful_set(
        name, ns, pod, replicas=replicas, service_name=name,
        labels={NOTEBOOK_LABEL: name, "app": name},
    )
    # a real apiserver defaults fields the builder omits, so comparing the
    # stored template against the desired one is permanently unequal and
    # would apply/watch/reconcile in a hot loop; compare this hash instead
    sts["metadata"].setdefault("annotations", {})[SPEC_HASH_ANNOTATION] = (
        _spec_hash(sts))
    return o.set_owner(sts, nb)


SPEC_HASH_ANNOTATION = "kubeflow-tpu.org/spec-hash"


def _spec_hash(sts: o.Obj) -> str:
    import hashlib
    import json

    payload = json.dumps(sts["spec"], sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def build_service(nb: o.Obj) -> o.Obj:
    name = nb["metadata"]["name"]
    ns = nb["metadata"]["namespace"]
    svc = o.service(
        name, ns, {NOTEBOOK_LABEL: name},
        [{"name": "http", "port": 80, "targetPort": NOTEBOOK_PORT}],
        labels={NOTEBOOK_LABEL: name},
    )
    return o.set_owner(svc, nb)


def build_virtual_service(nb: o.Obj, *,
                          gateway: str = "kubeflow/kubeflow-gateway") -> o.Obj:
    """Istio route for the notebook's browser path.

    The reference controller creates one per Notebook when USE_ISTIO
    (``/root/reference/components/notebook-controller/pkg/controller/
    notebook/notebook_controller.go:208-243``): /notebook/<ns>/<name>/ on
    the shared gateway, rewritten to the pod's base path."""
    name = nb["metadata"]["name"]
    ns = nb["metadata"]["namespace"]
    prefix = f"/notebook/{ns}/{name}/"
    vs = {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "VirtualService",
        "metadata": {"name": f"notebook-{name}", "namespace": ns,
                     "labels": {NOTEBOOK_LABEL: name}},
        "spec": {
            "hosts": ["*"],
            "gateways": [gateway],
            "http": [{
                "match": [{"uri": {"prefix": prefix}}],
                "rewrite": {"uri": prefix},  # NB_PREFIX keeps the base path
                "route": [{"destination": {
                    "host": f"{name}.{ns}.svc.cluster.local",
                    "port": {"number": 80},
                }}],
                "timeout": "300s",
            }],
        },
    }
    return o.set_owner(vs, nb)


class NotebookController:
    """Reconciles Notebook CRs; culls idle notebooks when enabled."""

    def __init__(self, client: KubeClient, namespace: Optional[str] = None,
                 policy: Optional[culler.CullingPolicy] = None,
                 use_istio: Optional[bool] = None) -> None:
        import os

        self.client = client
        self.namespace = namespace
        self.policy = policy or culler.CullingPolicy()
        # reference gates the per-notebook VirtualService on USE_ISTIO
        self.use_istio = (os.environ.get("USE_ISTIO", "").lower()
                          in ("1", "true") if use_istio is None else use_istio)

    def reconcile(self, ns: str, name: str) -> Optional[float]:
        nb = self.client.get_or_none(NOTEBOOK_API_VERSION, NOTEBOOK_KIND,
                                     ns, name)
        if nb is None:
            return None

        if culler.should_cull(nb, self.policy):
            culler.stop(nb)
            nb = self.client.update(nb)
            log.info("culled idle notebook %s/%s", ns, name)

        desired_sts = build_statefulset(nb)
        existing = self.client.get_or_none("apps/v1", "StatefulSet", ns, name)
        desired_hash = desired_sts["metadata"]["annotations"][
            SPEC_HASH_ANNOTATION]
        if existing is None:
            self.client.create(desired_sts)
        elif (existing.get("metadata", {}).get("annotations", {})
                      .get(SPEC_HASH_ANNOTATION) != desired_hash):
            self.client.apply(desired_sts)
        if self.client.get_or_none("v1", "Service", ns, name) is None:
            try:
                self.client.create(build_service(nb))
            except ApiError as e:
                if e.code != 409:
                    raise
        if self.use_istio:
            vs = build_virtual_service(nb)
            if self.client.get_or_none(vs["apiVersion"], vs["kind"], ns,
                                       vs["metadata"]["name"]) is None:
                try:
                    self.client.create(vs)
                except ApiError as e:
                    if e.code != 409:
                        raise

        self._update_status(nb)
        if self.policy.enabled and not culler.is_stopped(nb):
            return self.policy.check_period_seconds
        return None

    def _update_status(self, nb: o.Obj) -> None:
        """Mirror the notebook pod's container state into status, the way
        the reference surfaces pod state (notebook_controller.go:309-336)."""
        ns = nb["metadata"]["namespace"]
        name = nb["metadata"]["name"]
        pods = self.client.list("v1", "Pod", ns,
                                label_selector={NOTEBOOK_LABEL: name})
        status: Dict[str, Any] = {"readyReplicas": 0, "phase": "Waiting"}
        if culler.is_stopped(nb):
            status["phase"] = "Stopped"
        for pod in pods:
            pphase = pod.get("status", {}).get("phase")
            if pphase == "Running":
                status["readyReplicas"] = 1
                status["phase"] = "Running"
            container_states = pod.get("status", {}).get(
                "containerStatuses", [])
            if container_states:
                status["containerState"] = container_states[0].get("state", {})
        if nb.get("status") != status:
            nb = dict(nb)
            nb["status"] = status
            try:
                self.client.update_status(nb)
            except ApiError as e:
                if e.code != 404:
                    raise

    def build_controller(self) -> Controller:
        ctrl = Controller(
            self.client, NOTEBOOK_API_VERSION, NOTEBOOK_KIND, self.reconcile,
            namespace=self.namespace, name="notebook-controller",
        )

        def pod_to_nb(pod: o.Obj):
            labels = pod.get("metadata", {}).get("labels", {}) or {}
            nb = labels.get(NOTEBOOK_LABEL)
            if nb:
                return (pod["metadata"].get("namespace", ""), nb)
            return None

        ctrl.watch_owned("v1", "Pod", pod_to_nb)
        ctrl.watch_owned("apps/v1", "StatefulSet", pod_to_nb)
        return ctrl


def main() -> None:
    from kubeflow_tpu.k8s.client import HttpKubeClient

    logging.basicConfig(level=logging.INFO)
    policy = culler.CullingPolicy.from_env(dict(os.environ))
    ns = os.environ.get("KFTPU_NOTEBOOK_NAMESPACE") or None
    NotebookController(HttpKubeClient(), namespace=ns,
                       policy=policy).build_controller().run_forever()


if __name__ == "__main__":
    main()
