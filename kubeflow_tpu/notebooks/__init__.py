"""Notebook subsystem: CRD + controller + culler + web backend.

Reference surface: the notebook-controller kubebuilder program
(``/root/reference/components/notebook-controller/``), the jupyter ksonnet
package (``/root/reference/kubeflow/jupyter/``), and the jupyter-web-app
Flask backend (``/root/reference/components/jupyter-web-app/``). Here the
controller runs on the framework's own controller runtime, and notebook
pods are schedulable onto TPU hosts via a chips request.
"""

from kubeflow_tpu.notebooks.controller import (  # noqa: F401
    NOTEBOOK_API_VERSION,
    NOTEBOOK_KIND,
    NotebookController,
    notebook,
)
from kubeflow_tpu.notebooks.culler import CullingPolicy, should_cull  # noqa: F401
from kubeflow_tpu.notebooks.webapp import NotebookWebApp  # noqa: F401
